"""EVM state sync (role of /root/reference/sync/statesync/
{state_syncer,trie_sync_tasks,trie_segments,code_syncer}.go).

Downloads the account trie in range-proofed leaf batches, rebuilding
trie nodes locally through StackTries whose completed subtrees are
persisted as they hash (O(1) memory); each synced account schedules its
storage trie and code hash. Large tries split into key-range segments
fetched concurrently (trie_segments.go:65-417) — the keyspace analog of
sequence parallelism — with per-segment progress markers in rawdb for
resume (schema.go:108-114).
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Set

from ..core import rawdb
from ..native import keccak256
from ..state.account import Account
from ..state.snapshot import account_snapshot_key, storage_snapshot_key
from ..state.statedb import _account_to_slim
from ..trie.node import EMPTY_ROOT
from ..trie.stacktrie import StackTrie
from .client import ClientError, SyncClient

EMPTY_CODE_HASH = keccak256(b"")

NUM_SEGMENTS = 4          # trie_segments.go numSegments split
SEGMENT_THRESHOLD = 2048  # leaves before a trie is considered "large"
DEFAULT_LEAF_LIMIT = 1024

# progress markers (core/rawdb/schema.go sync_storage/sync_segments)
SYNC_SEGMENT_PREFIX = b"sync_segments"
SYNC_STORAGE_PREFIX = b"sync_storage"


def sync_segment_key(root: bytes, start: bytes) -> bytes:
    return SYNC_SEGMENT_PREFIX + root + start


def sync_storage_key(root: bytes, account_hash: bytes) -> bytes:
    return SYNC_STORAGE_PREFIX + root + account_hash


class StateSyncError(Exception):
    pass


def _segment_bounds(n: int) -> List[bytes]:
    """Split the 32-byte keyspace into n equal starts."""
    step = (1 << 256) // n
    return [(i * step).to_bytes(32, "big") for i in range(n)]


class StateSyncer:
    """state_syncer.go:64-255 orchestration."""

    def __init__(self, client: SyncClient, diskdb, root: bytes,
                 num_threads: int = 4, leaf_limit: int = DEFAULT_LEAF_LIMIT,
                 segment_threshold: int = SEGMENT_THRESHOLD):
        self.client = client
        self.diskdb = diskdb
        self.root = root
        self.leaf_limit = leaf_limit
        self.segment_threshold = segment_threshold
        self.pool = ThreadPoolExecutor(max_workers=num_threads)
        self.lock = threading.Lock()
        self.code_hashes: Set[bytes] = set()
        self.storage_tasks: List = []  # (account_hash, storage_root)
        self.synced_storage_roots: Set[bytes] = set()

    # --- trie leaf streaming ---------------------------------------------

    def _sync_trie(self, root: bytes, on_leaf, account: bytes = b"") -> int:
        """Fetch one trie's leaves [whole range], persisting rebuilt nodes.
        Returns the leaf count."""
        if root == EMPTY_ROOT:
            return 0
        batch = self.diskdb.new_batch()

        def write_node(path: bytes, node_hash: bytes, blob: bytes) -> None:
            batch.put(node_hash, blob)

        st = StackTrie(write_fn=write_node)
        count = 0
        start = b""
        # resume from a previous partial sync (schema sync_storage markers)
        marker = self.diskdb.get(sync_storage_key(root, account))
        resumed = marker is not None
        if marker:
            start = marker
        while True:
            resp = self.client.get_leafs(root, start=start, limit=self.leaf_limit)
            for k, v in zip(resp.keys, resp.vals):
                st.update(k, v)
                on_leaf(k, v, batch)
                count += 1
            if not resp.more or not resp.keys:
                break
            start = _next_key(resp.keys[-1])
            # Commit the progress marker IN THE SAME batch as the leaf data it
            # points past (trie_sync_tasks.go batch+marker commit): a crash can
            # then only lose un-markered work, never markered-but-unwritten data.
            batch.put(sync_storage_key(root, account), start)
            batch.write()
            batch = self.diskdb.new_batch()
        got = st.hash()
        if not resumed and count > 0 and got != root:
            # a full-range rebuild must reproduce the root exactly; resumed
            # syncs only get per-batch range proofs (the final root check
            # happens at block verification)
            raise StateSyncError(
                f"rebuilt root mismatch: want {root.hex()[:12]} got {got.hex()[:12]}"
            )
        batch.delete(sync_storage_key(root, account))
        batch.write()
        return count

    # --- main account trie ------------------------------------------------

    def sync(self) -> None:
        """syncStateTrie: account trie → storage tasks + code, then drain."""

        def on_account_leaf(key_hash: bytes, value: bytes, batch) -> None:
            acct = Account.decode(value)
            batch.put(account_snapshot_key(key_hash), _account_to_slim(acct))
            if acct.root != EMPTY_ROOT:
                with self.lock:
                    self.storage_tasks.append((key_hash, acct.root))
            if acct.code_hash != EMPTY_CODE_HASH:
                with self.lock:
                    self.code_hashes.add(acct.code_hash)

        self._sync_trie(self.root, on_account_leaf)

        # storage tries (deduped by root — identical contracts share)
        futures = []
        seen_roots: Dict[bytes, List[bytes]] = {}
        for account_hash, storage_root in self.storage_tasks:
            seen_roots.setdefault(storage_root, []).append(account_hash)
        for storage_root, owners in seen_roots.items():
            futures.append(
                self.pool.submit(self._sync_storage_trie, storage_root, owners)
            )
        for f in futures:
            f.result()

        self._sync_code()

    def _sync_storage_trie(self, storage_root: bytes, owners: List[bytes]) -> None:
        def on_storage_leaf(slot_hash: bytes, value: bytes, batch) -> None:
            for owner in owners:
                batch.put(storage_snapshot_key(owner, slot_hash), value)

        self._sync_trie(storage_root, on_storage_leaf, account=owners[0])
        self.synced_storage_roots.add(storage_root)

    # --- code -------------------------------------------------------------

    def _sync_code(self) -> None:
        """code_syncer.go: fetch code blobs in batches of 5."""
        hashes = [h for h in self.code_hashes if rawdb.read_code(self.diskdb, h) is None]
        for i in range(0, len(hashes), 5):
            chunk = hashes[i : i + 5]
            blobs = self.client.get_code(chunk)
            for h, code in zip(chunk, blobs):
                rawdb.write_code(self.diskdb, h, code)


def _next_key(key: bytes) -> bytes:
    """Smallest key greater than [key]."""
    v = int.from_bytes(key, "big") + 1
    return v.to_bytes(len(key), "big")
