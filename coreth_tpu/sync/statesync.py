"""EVM state sync (role of /root/reference/sync/statesync/
{state_syncer,trie_sync_tasks,trie_segments,code_syncer}.go).

Downloads tries as range-proofed leaf batches; each synced account
schedules its storage trie and code hash.

Small tries stream through a single StackTrie whose completed subtrees
persist as they hash (O(1) memory, one request for the common case).

Large tries (first response full with more remaining) switch to
SEGMENTED sync — the capability of trie_segments.go:65-417, keyspace
parallelism as the sync-time analog of sequence parallelism:

  * the 256-bit keyspace splits into NUM_SEGMENTS ranges fetched
    CONCURRENTLY, each an independent range-proofed stream
  * every segment persists a resume marker (sync_segment_key) in the
    same batch as the leaf data it points past, so an interrupted sync
    resumes each segment where it stopped — markered data is always on
    disk, unmarkered work is refetched (schema.go:108-114 semantics)
  * leaves land in an on-disk buffer (plus the flat snapshot); when all
    segments finish, ONE StackTrie rebuild over the ordered buffer
    reconstructs and persists the trie nodes and must reproduce the
    target root bit-exactly (stronger than the reference's per-segment
    stitching: the final root check covers the whole keyspace even
    across resumes). The rebuild is idempotent — a crash during it
    replays from the still-markered buffer.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Set

from ..core import rawdb
from ..native import keccak256
from ..state.account import Account
from ..state.snapshot import account_snapshot_key, storage_snapshot_key
from ..state.statedb import _account_to_slim
from ..trie.node import EMPTY_ROOT
from ..trie.stacktrie import StackTrie
from .client import ClientError, SyncClient

EMPTY_CODE_HASH = keccak256(b"")

NUM_SEGMENTS = 4          # trie_segments.go numSegments split
SEGMENT_THRESHOLD = 2048  # leaves before a trie is considered "large"
DEFAULT_LEAF_LIMIT = 1024

# progress markers (core/rawdb/schema.go sync_storage/sync_segments)
SYNC_SEGMENT_PREFIX = b"sync_segments"
SYNC_STORAGE_PREFIX = b"sync_storage"
# temporary raw-leaf buffer for segmented rebuilds (deleted after the
# StackTrie pass verifies the root)
SYNC_LEAF_PREFIX = b"sync_leafbuf"

# segment marker values: b"D" done, b"S" + next_start in progress
_SEG_DONE = b"D"


def sync_segment_key(root: bytes, start: bytes) -> bytes:
    return SYNC_SEGMENT_PREFIX + root + start


def sync_storage_key(root: bytes, account_hash: bytes) -> bytes:
    return SYNC_STORAGE_PREFIX + root + account_hash


def sync_leaf_key(root: bytes, leaf_key: bytes) -> bytes:
    return SYNC_LEAF_PREFIX + root + leaf_key


class StateSyncError(Exception):
    pass


def _segment_bounds(n: int) -> List[bytes]:
    """Split the 32-byte keyspace into n equal starts."""
    step = (1 << 256) // n
    return [(i * step).to_bytes(32, "big") for i in range(n)]


class StateSyncer:
    """state_syncer.go:64-255 orchestration."""

    def __init__(self, client: SyncClient, diskdb, root: bytes,
                 num_threads: int = 4, leaf_limit: int = DEFAULT_LEAF_LIMIT,
                 segment_threshold: int = SEGMENT_THRESHOLD):
        self.client = client
        self.diskdb = diskdb
        self.root = root
        self.leaf_limit = leaf_limit
        self.segment_threshold = segment_threshold
        self.pool = ThreadPoolExecutor(max_workers=num_threads)
        self.lock = threading.Lock()
        self.code_hashes: Set[bytes] = set()
        self.storage_tasks: List = []  # (account_hash, storage_root)
        self.synced_storage_roots: Set[bytes] = set()

    # --- trie leaf streaming ---------------------------------------------

    def _sync_trie(self, root: bytes, on_leaf, account: bytes = b"",
                   on_unleaf=None) -> int:
        """Fetch one trie's leaves, persisting rebuilt nodes; returns the
        leaf count. Small tries stream through one StackTrie; large tries
        (>= segment_threshold leaves with more coming) switch to
        concurrent segments. on_unleaf(key, batch) undoes on_leaf's
        key-addressed side effects — used when discarding unverified
        buffered leaves (lying-peer recovery) so phantom snapshot entries
        cannot outlive the data that created them."""
        if root == EMPTY_ROOT:
            return 0

        # a previously-interrupted SEGMENTED sync resumes segmented
        seg_starts = _segment_bounds(NUM_SEGMENTS)
        if any(self.diskdb.get(sync_segment_key(root, s)) is not None
               for s in seg_starts):
            return self._sync_trie_segmented(root, on_leaf, on_unleaf)

        batch = self.diskdb.new_batch()

        def write_node(path: bytes, node_hash: bytes, blob: bytes) -> None:
            batch.put(node_hash, blob)

        st = StackTrie(write_fn=write_node)
        count = 0
        start = b""
        # resume from a previous partial UNSEGMENTED sync
        marker = self.diskdb.get(sync_storage_key(root, account))
        resumed = marker is not None
        if marker:
            start = marker
        # pre-switch leaves held in MEMORY (bounded by segment_threshold):
        # small tries — the overwhelmingly common case — never touch the
        # disk buffer; the leaves flush into it only at the actual switch
        pre_switch: List = [] if not resumed else None
        while True:
            resp = self.client.get_leafs(root, start=start, limit=self.leaf_limit)
            for k, v in zip(resp.keys, resp.vals):
                st.update(k, v)
                on_leaf(k, v, batch)
                if pre_switch is not None:
                    pre_switch.append((k, v))
                count += 1
            if not resp.more or not resp.keys:
                break
            if pre_switch is not None and count >= self.segment_threshold:
                # the trie IS large (>= threshold leaves and more coming):
                # buffer everything fetched so far + mark segment coverage
                # in one atomic batch, then go concurrent. Resumed
                # pre-switch syncs never take this path (their early
                # leaves were never retained). Stray buffer entries from a
                # crashed older sync of this root are cleared (with their
                # snapshot side effects) before the fresh seed.
                self._clear_leaf_buffer(root, on_unleaf)
                batch.delete(sync_storage_key(root, account))
                self._seed_segments(root, pre_switch, seg_starts, batch)
                return self._sync_trie_segmented(root, on_leaf, on_unleaf)
            start = _next_key(resp.keys[-1])
            # Commit the progress marker IN THE SAME batch as the leaf data it
            # points past (trie_sync_tasks.go batch+marker commit): a crash can
            # then only lose un-markered work, never markered-but-unwritten data.
            batch.put(sync_storage_key(root, account), start)
            batch.write()
            batch = self.diskdb.new_batch()
        got = st.hash()
        if not resumed and count > 0 and got != root:
            # a full-range rebuild must reproduce the root exactly; resumed
            # syncs only get per-batch range proofs (the final root check
            # happens at block verification)
            raise StateSyncError(
                f"rebuilt root mismatch: want {root.hex()[:12]} got {got.hex()[:12]}"
            )
        batch.delete(sync_storage_key(root, account))
        batch.write()
        return count

    # --- segmented path (trie_segments.go:65-417 capability) ---------------

    def _seed_segments(self, root: bytes, pre_switch, seg_starts,
                       batch) -> None:
        """Flush the single-stream prefix into the disk buffer and mark
        every segment done/in-progress/virgin relative to its last key —
        one atomic batch, so the switch either fully happens or the
        unsegmented marker path resumes as if it never did."""
        for k, v in pre_switch:
            batch.put(sync_leaf_key(root, k), v)
        last_key = pre_switch[-1][0]
        nxt = _next_key(last_key)
        ends = _segment_ends(seg_starts)
        for i, s in enumerate(seg_starts):
            if ends[i] <= last_key:
                batch.put(sync_segment_key(root, s), _SEG_DONE)
            elif s <= last_key:
                batch.put(sync_segment_key(root, s), b"S" + nxt)
            else:
                batch.put(sync_segment_key(root, s), b"S" + s)
        batch.write()

    def _sync_trie_segmented(self, root: bytes, on_leaf, on_unleaf=None) -> int:
        seg_starts = _segment_bounds(NUM_SEGMENTS)
        ends = _segment_ends(seg_starts)
        with ThreadPoolExecutor(max_workers=NUM_SEGMENTS) as seg_pool:
            futures = [
                seg_pool.submit(self._fetch_segment, root, on_leaf, s, e)
                for s, e in zip(seg_starts, ends)
            ]
            fetched = sum(f.result() for f in futures)
        count = self._rebuild_from_buffer(root, seg_starts, on_leaf, on_unleaf)
        return count if count else fetched

    def _clear_leaf_buffer(self, root: bytes, on_unleaf=None) -> None:
        """Drop buffered leaves for [root] — and, when discarding
        UNVERIFIED data (on_unleaf set), undo the snapshot entries those
        leaves wrote, so a lying peer's phantom keys don't survive."""
        batch = self.diskdb.new_batch()
        n = 0
        prefix = SYNC_LEAF_PREFIX + root
        for full_key, _v in self.diskdb.iterate(prefix):
            if on_unleaf is not None:
                on_unleaf(full_key[len(prefix):], batch)
            batch.delete(full_key)
            n += 1
            if n % 4096 == 0:
                batch.write()
                batch = self.diskdb.new_batch()
        batch.write()

    def _fetch_segment(self, root: bytes, on_leaf, seg_start: bytes,
                       seg_end: bytes) -> int:
        """Stream one key-range segment; every batch lands with its resume
        marker atomically. seg_end is the INCLUSIVE last key served."""
        key = sync_segment_key(root, seg_start)
        marker = self.diskdb.get(key)
        if marker == _SEG_DONE:
            return 0
        start = marker[1:] if marker else seg_start
        count = 0
        empty_more = 0
        while True:
            resp = self.client.get_leafs(
                root, start=start, end=seg_end, limit=self.leaf_limit)
            batch = self.diskdb.new_batch()
            for k, v in zip(resp.keys, resp.vals):
                batch.put(sync_leaf_key(root, k), v)
                on_leaf(k, v, batch)
                count += 1
            if resp.keys and resp.more:
                start = _next_key(resp.keys[-1])
                batch.put(key, b"S" + start)
                batch.write()
                empty_more = 0
                continue
            if resp.more:
                # zero keys but "more": a deadline-pressured server served
                # nothing this round — retry the same range (bounded)
                # instead of stamping DONE over an unfinished segment
                batch.write()
                empty_more += 1
                if empty_more > 5:
                    raise StateSyncError(
                        f"segment {seg_start.hex()[:8]} starves: server "
                        "keeps answering empty with more=True"
                    )
                continue
            batch.put(key, _SEG_DONE)
            batch.write()
            return count

    def _rebuild_from_buffer(self, root: bytes, seg_starts, on_leaf,
                             on_unleaf=None) -> int:
        """One ordered StackTrie pass over the buffered leaves: persists
        the trie nodes, REPLAYS on_leaf (so a resumed sync re-derives the
        storage/code tasks its crashed predecessor collected only in
        memory), and verifies the root over the FULL keyspace. Cleanup
        order is crash-safe: markers clear in the same batch as the trie
        nodes, the buffer strictly after — a crash mid-cleanup leaves
        either a fully-markered buffer (rebuild replays) or no markers
        plus stray buffer entries (cleared at the next sync's switch)."""
        batch = self.diskdb.new_batch()

        def write_node(path: bytes, node_hash: bytes, blob: bytes) -> None:
            batch.put(node_hash, blob)

        st = StackTrie(write_fn=write_node)
        prefix = SYNC_LEAF_PREFIX + root
        count = 0
        # nodes/snapshot writes stream out in chunks — hash-keyed blobs are
        # self-verifying, so pre-verification flushes can at worst orphan
        # garbage (same as a crash), never corrupt; memory stays O(chunk)
        for full_key, v in self.diskdb.iterate(prefix):
            leaf_key = full_key[len(prefix):]
            st.update(leaf_key, v)
            on_leaf(leaf_key, v, batch)
            count += 1
            if count % 4096 == 0:
                batch.write()
                batch = self.diskdb.new_batch()
        got = st.hash()
        if got != root:
            # a lying peer's truncated more=False can only surface here;
            # reset the segment state so the NEXT attempt (likely against
            # an honest peer) refetches instead of wedging forever on
            # done-marked holes. The buffer clear also undoes the
            # snapshot entries the unverified leaves wrote (on_unleaf).
            batch = self.diskdb.new_batch()
            for s in seg_starts:
                batch.delete(sync_segment_key(root, s))
            batch.write()
            self._clear_leaf_buffer(root, on_unleaf)
            raise StateSyncError(
                f"segmented rebuild root mismatch: want {root.hex()[:12]} "
                f"got {got.hex()[:12]} (segment state reset for refetch)"
            )
        # 1) remaining nodes + replayed side effects + marker clear: one batch
        for s in seg_starts:
            batch.delete(sync_segment_key(root, s))
        batch.write()
        # 2) buffer clear, strictly after the markers are gone
        self._clear_leaf_buffer(root)
        return count

    # --- main account trie ------------------------------------------------

    def sync(self) -> None:
        """syncStateTrie: account trie → storage tasks + code, then drain."""

        def on_account_leaf(key_hash: bytes, value: bytes, batch) -> None:
            acct = Account.decode(value)
            batch.put(account_snapshot_key(key_hash), _account_to_slim(acct))
            if acct.root != EMPTY_ROOT:
                with self.lock:
                    self.storage_tasks.append((key_hash, acct.root))
            if acct.code_hash != EMPTY_CODE_HASH:
                with self.lock:
                    self.code_hashes.add(acct.code_hash)

        def un_account_leaf(key_hash: bytes, batch) -> None:
            batch.delete(account_snapshot_key(key_hash))

        self._sync_trie(self.root, on_account_leaf,
                        on_unleaf=un_account_leaf)

        # storage tries (deduped by root — identical contracts share; owner
        # sets dedupe the rebuild pass's on_leaf replay)
        futures = []
        seen_roots: Dict[bytes, Set[bytes]] = {}
        for account_hash, storage_root in self.storage_tasks:
            seen_roots.setdefault(storage_root, set()).add(account_hash)
        for storage_root, owners in seen_roots.items():
            futures.append(
                self.pool.submit(
                    self._sync_storage_trie, storage_root, sorted(owners))
            )
        for f in futures:
            f.result()

        self._sync_code()

    def _sync_storage_trie(self, storage_root: bytes, owners: List[bytes]) -> None:
        def on_storage_leaf(slot_hash: bytes, value: bytes, batch) -> None:
            for owner in owners:
                batch.put(storage_snapshot_key(owner, slot_hash), value)

        def un_storage_leaf(slot_hash: bytes, batch) -> None:
            for owner in owners:
                batch.delete(storage_snapshot_key(owner, slot_hash))

        self._sync_trie(storage_root, on_storage_leaf, account=owners[0],
                        on_unleaf=un_storage_leaf)
        self.synced_storage_roots.add(storage_root)

    # --- code -------------------------------------------------------------

    def _sync_code(self) -> None:
        """code_syncer.go: fetch code blobs in batches of 5."""
        hashes = [h for h in self.code_hashes if rawdb.read_code(self.diskdb, h) is None]
        for i in range(0, len(hashes), 5):
            chunk = hashes[i : i + 5]
            blobs = self.client.get_code(chunk)
            for h, code in zip(chunk, blobs):
                rawdb.write_code(self.diskdb, h, code)


def _next_key(key: bytes) -> bytes:
    """Smallest key greater than [key]."""
    v = int.from_bytes(key, "big") + 1
    return v.to_bytes(len(key), "big")


def _segment_ends(seg_starts) -> List[bytes]:
    """INCLUSIVE last key per segment (the wire's `end` bound is
    inclusive; the final segment runs to the keyspace maximum)."""
    ends = []
    for nxt in seg_starts[1:]:
        v = int.from_bytes(nxt, "big") - 1
        ends.append(v.to_bytes(32, "big"))
    ends.append(b"\xff" * 32)
    return ends
