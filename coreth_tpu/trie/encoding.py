"""Trie key encodings (semantics of /root/reference/trie/encoding.go).

Three forms:
  KEYBYTES: raw bytes, application-facing.
  HEX: one nibble per byte, optionally ending with the 0x10 terminator —
       in-memory form in Trie nodes.
  COMPACT (hex-prefix): nibbles packed two-per-byte with a flag nibble
       carrying oddness + terminator — the on-disk/RLP form.
"""

from __future__ import annotations

TERMINATOR = 0x10


def key_to_hex(key: bytes) -> bytes:
    """KEYBYTES -> HEX with terminator."""
    out = bytearray(len(key) * 2 + 1)
    for i, b in enumerate(key):
        out[2 * i] = b >> 4
        out[2 * i + 1] = b & 0x0F
    out[-1] = TERMINATOR
    return bytes(out)


def hex_to_keybytes(hexkey: bytes) -> bytes:
    """HEX (with or without terminator) -> KEYBYTES; must be even nibbles."""
    if has_term(hexkey):
        hexkey = hexkey[:-1]
    if len(hexkey) % 2:
        raise ValueError("can't convert odd-length hex key")
    out = bytearray(len(hexkey) // 2)
    for i in range(len(out)):
        out[i] = (hexkey[2 * i] << 4) | hexkey[2 * i + 1]
    return bytes(out)


def has_term(hexkey: bytes) -> bool:
    return bool(hexkey) and hexkey[-1] == TERMINATOR


def hex_to_compact(hexkey: bytes) -> bytes:
    terminator = 0
    if has_term(hexkey):
        terminator = 1
        hexkey = hexkey[:-1]
    out = bytearray(len(hexkey) // 2 + 1)
    out[0] = terminator << 5  # flag byte
    if len(hexkey) & 1:
        out[0] |= 1 << 4 | hexkey[0]  # odd flag + first nibble
        hexkey = hexkey[1:]
    for i in range(0, len(hexkey), 2):
        out[1 + i // 2] = (hexkey[i] << 4) | hexkey[i + 1]
    return bytes(out)


def compact_to_hex(compact: bytes) -> bytes:
    if not compact:
        return b""
    base = bytearray()
    for b in compact:
        base.append(b >> 4)
        base.append(b & 0x0F)
    # flags: base[0] bit1 = odd, bit2(value 2) = terminator
    chop = 2 - (base[0] & 1)
    out = bytes(base[chop:])
    if base[0] >= 2:
        out += bytes([TERMINATOR])
    return out


def prefix_len(a: bytes, b: bytes) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n
