"""Planned-graph commit: the chain's dirty node graphs drained through the
u32 planned executor (ops/keccak_planned.PlannedCommit).

This is the production wiring of the bench's fast path. The round-1/2
profiling story (PERF.md): per-level dispatches pay the link's fixed cost
~20 times per commit, and byte-level (uint8) work inside jitted programs
costs ~100x the hashing itself. The planned executor fixes both — ONE bulk
u32 transfer, per-segment device steps over device-resident words, patch
tables resolving the parent<-child digest dependency on device in word
space — but until this module existed it was reachable only from bench.py.

`PlannedGraphBuilder` converts in-memory dirty node graphs (what
Trie.hash()/StateDB.intermediate_root actually hold — O(dirty set), NOT a
full-trie rebuild) into the executor's export format:

  * dirty nodes are collected per trie, grouped by height (leaves first),
    bucketed by keccak block count into uniform segments
  * each node's RLP is written once into the flat little-endian u32 word
    stream with zeroed 32-byte holes where a dirty child's digest goes;
    a patch (dst_word, child_lane, shift) resolves each hole on device
  * MULTIPLE tries compose into ONE program: every dirty storage trie's
    levels are merged height-wise, the account trie's levels follow, and
    each account leaf's storage-root field is itself a patch hole pointing
    at the storage trie's root lane — the cross-trie dependency of
    StateDB.commit (reference ordering: core/state/statedb.go:1040-1160,
    storage tries -> account RLP -> account trie) never touches the host.

Reference seams replaced: trie/hasher.go:124-139 (goroutine fan-out),
trie/trie.go:585-626 (commit walk), core/state/statedb.go:1040-1160
(storage-then-account ordering).

Bit-exactness: same embed rule as Hasher/BatchedHasher/FusedHasher (node
RLP < 32 bytes embeds in the parent; each trie's root is always hashed) and
parity-tested against the CPU hasher in tests/test_planned_graph.py.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from .encoding import hex_to_compact
from .hasher import (
    _bytes_enc_len,
    _keccak_pad,
    _list_hdr_len,
    _write_bytes,
    _write_list_hdr,
    collect_levels_with_paths,
)
from .node import FullNode, HashNode, ShortNode, ValueNode

_RATE = 136
_WPB = _RATE // 4  # u32 words per rate block


def _pad_lanes(n: int) -> int:
    """Lane-count bucket — IDENTICAL to the native planners' round_lanes
    (count+1 scratch lane, pow2 floor 16 up to 8192, then 8192 multiples):
    PlannedCommit's step programs are jit-keyed on (lanes, blocks, npatch),
    so matching the rounding lets the chain builder, bench full-commit
    legs, and the incremental planner share one compiled program set."""
    n = n + 1  # scratch lane, as the native layout reserves
    if n <= 8192:
        p = 16
        while p < n:
            p <<= 1
        return p
    return ((n + 8191) // 8192) * 8192


def _pad_patches(n: int) -> int:
    if n == 0:
        return 0
    p = 16
    while p < n:
        p <<= 1
    return p


class _TrieEntry:
    __slots__ = ("root", "levels", "holes", "root_lane")

    def __init__(self, root, holes):
        self.root = root
        self.levels: List[List[Tuple[object, bytes]]] = []
        self.holes = holes  # hex path -> (value_offset, src _TrieEntry)
        self.root_lane: Optional[int] = None


class PlannedGraphBuilder:
    """Collects dirty node graphs; builds one planned-executor program.

    Usage:
        b = PlannedGraphBuilder()
        h1 = b.add_trie(storage_root_node)          # any number of these
        b.add_account_trie(acct_root_node, holes={hexpath: (off, h1)})
        root_hash = b.run()                          # device round-trip
    after run(): every hashed node's flags.hash is set, value holes are
    healed with the real child-root bytes, and `b.digest(handle)` returns
    a trie's root digest.
    """

    def __init__(self):
        self._tries: List[_TrieEntry] = []
        self._account: Optional[_TrieEntry] = None

    # ------------------------------------------------------------ collection

    def add_trie(self, root) -> _TrieEntry:
        if not isinstance(root, (ShortNode, FullNode)):
            raise TypeError("planned builder needs a Short/Full dirty root")
        e = _TrieEntry(root, {})
        e.levels = collect_levels_with_paths(root)
        self._tries.append(e)
        return e

    def add_account_trie(self, root, holes: Dict[bytes, Tuple[int, _TrieEntry]]):
        if not isinstance(root, (ShortNode, FullNode)):
            raise TypeError("planned builder needs a Short/Full dirty root")
        e = _TrieEntry(root, holes or {})
        e.levels = collect_levels_with_paths(root)
        self._account = e
        return e

    # ----------------------------------------------------------------- build

    def build(self):
        """Lay out segments; returns (specs, flat_words, dst, child, shift,
        root_pos) in CommitPlan.export_words() format, or None when the
        graph needs more segments than the executor's metadata table holds
        (caller falls back to the level-batched hasher)."""
        from ..ops.keccak_fused import SegmentSpec
        from ..ops.keccak_planned import MAX_SEGMENTS

        # merged height levels: storage tries first (their level h merged
        # across tries), account trie's levels strictly after
        merged: List[List[Tuple[_TrieEntry, object, bytes]]] = []
        for e in self._tries:
            for h, lvl in enumerate(e.levels):
                while len(merged) <= h:
                    merged.append([])
                merged[h].extend((e, n, p) for n, p in lvl)
        if self._account is not None:
            for lvl in self._account.levels:
                merged.append([(self._account, n, p) for n, p in lvl])

        # pass 1: per node, build (padded_msg, rel_patches) and assign
        # lanes segment by segment. info maps id(node) -> ("gid", lane) |
        # ("embed", bytes); children are always processed before parents.
        info: Dict[int, Tuple[str, object]] = {}
        segs: List[dict] = []   # {blocks, msgs:[bytes], patches:[(lane_rel=None..)]}
        self._hashed: List[Tuple[object, int]] = []  # (node, gid)
        self._healed: List[Tuple[object, int, _TrieEntry]] = []

        for level in merged:
            by_blocks: Dict[int, dict] = {}
            for e, n, path in level:
                msg, rel_patches, is_embed = self._encode_node(e, n, path, info)
                if is_embed:
                    info[id(n)] = ("embed", msg)
                    continue
                padded, blocks = _keccak_pad(msg)
                seg = by_blocks.get(blocks)
                if seg is None:
                    seg = by_blocks[blocks] = {"blocks": blocks, "msgs": [],
                                               "patches": [], "nodes": []}
                seg["msgs"].append(padded)
                seg["patches"].append(rel_patches)
                seg["nodes"].append(n)
                # parents encoded later this pass only need to know this
                # node hashes (child ref = 33 bytes); the real lane number
                # lands in pass 2
                info[id(n)] = ("gid", None)
            for blocks in sorted(by_blocks):
                segs.append(by_blocks[blocks])

        if len(segs) > MAX_SEGMENTS:
            return None

        # pass 2: assign gids (padded lane numbering), absolute word offsets
        word_off = 0
        gstart = 0
        for seg in segs:
            padded_lanes = _pad_lanes(len(seg["msgs"]))
            seg["gstart"] = gstart
            seg["word_off"] = word_off
            seg["lanes_padded"] = padded_lanes
            for i, n in enumerate(seg["nodes"]):
                info[id(n)] = ("gid", gstart + i)
                self._hashed.append((n, gstart + i))
            gstart += padded_lanes
            word_off += padded_lanes * seg["blocks"] * _WPB
        total_words = word_off
        total_lanes = gstart
        for e in self._tries + ([self._account] if self._account else []):
            kind, lane = info[id(e.root)]
            assert kind == "gid", "trie root must be hashed (forced)"
            e.root_lane = lane

        # pass 3: materialize flat words + patch tables
        flat = np.zeros(total_words * 4, dtype=np.uint8)
        specs = []
        dst_l: List[np.ndarray] = []
        child_l: List[np.ndarray] = []
        shift_l: List[np.ndarray] = []
        for seg in segs:
            blocks = seg["blocks"]
            msg_bytes = blocks * _RATE
            base = seg["word_off"] * 4
            joined = b"".join(seg["msgs"])
            flat[base:base + len(joined)] = np.frombuffer(joined, np.uint8)
            # resolve this segment's patches to absolute coordinates
            dsts: List[int] = []
            childs: List[int] = []
            shifts: List[int] = []
            for lane, rel in enumerate(seg["patches"]):
                lane_byte = base + lane * msg_bytes
                for byte_off, child_node, src_entry in rel:
                    if child_node is not None:
                        kind, payload = info[id(child_node)]
                        assert kind == "gid", "patched child must be hashed"
                        child_gid = payload
                    else:
                        child_gid = src_entry.root_lane
                    abs_byte = lane_byte + byte_off
                    dsts.append(abs_byte // 4)
                    childs.append(child_gid)
                    shifts.append(abs_byte % 4)
            npat = len(dsts)
            npad = _pad_patches(npat)
            dsts.extend([0] * (npad - npat))      # zero strip: harmless add
            childs.extend([-1] * (npad - npat))   # -1 -> zero sentinel row
            shifts.extend([0] * (npad - npat))
            dst_l.append(np.asarray(dsts, np.int32))
            child_l.append(np.asarray(childs, np.int32))
            shift_l.append(np.asarray(shifts, np.int32))
            specs.append(SegmentSpec(blocks=blocks, lanes=seg["lanes_padded"],
                                     gstart=seg["gstart"], n_patches=npad))

        cat = (lambda xs: np.concatenate(xs) if xs else np.zeros(0, np.int32))
        root_entry = self._account if self._account is not None else self._tries[-1]
        root_pos = root_entry.root_lane
        flat_words = flat.view(np.uint32)
        return (tuple(specs), flat_words, cat(dst_l), cat(child_l),
                cat(shift_l), root_pos, total_lanes)

    def _encode_node(self, entry: _TrieEntry, n, path: bytes, info):
        """Single-pass RLP writer with zeroed digest holes.

        Returns (msg_bytes, patches [(byte_off, child_node|None, src_entry)],
        is_embed). Child lengths come from `info` (children processed
        first), so no separate sizing traversal."""
        patches: List[Tuple[int, Optional[object], Optional[_TrieEntry]]] = []

        def child_len(c) -> int:
            if c is None:
                return 1
            if isinstance(c, (HashNode, ValueNode)):
                return _bytes_enc_len(bytes(c))
            if c.flags.hash is not None:
                return 33
            kind, payload = info[id(c)]
            return 33 if kind == "gid" else len(payload)

        def write_child(c, out: bytearray) -> None:
            if c is None:
                out.append(0x80)
                return
            if isinstance(c, (HashNode, ValueNode)):
                _write_bytes(bytes(c), out)
                return
            if c.flags.hash is not None:
                _write_bytes(c.flags.hash, out)
                return
            kind, payload = info[id(c)]
            if kind == "gid":
                out.append(0xA0)
                patches.append((len(out), c, None))
                out.extend(b"\x00" * 32)
            else:
                out.extend(payload)

        # holes are keyed by the leaf's FULL hex key (prefix + short key)
        hole = None
        if entry.holes and isinstance(n, ShortNode) and isinstance(n.val, ValueNode):
            hole = entry.holes.get(path + n.key)

        if isinstance(n, ShortNode):
            key_enc = hex_to_compact(n.key)
            payload_len = _bytes_enc_len(key_enc) + child_len(n.val)
            total_len = _list_hdr_len(payload_len) + payload_len
            buf = bytearray()
            _write_list_hdr(payload_len, buf)
            _write_bytes(key_enc, buf)
            if hole is not None and isinstance(n.val, ValueNode):
                off_in_value, src = hole
                vb = bytes(n.val)
                content_start = len(buf) + (_bytes_enc_len(vb) - len(vb))
                _write_bytes(vb, buf)
                patches.append((content_start + off_in_value, None, src))
                self._healed.append((n, off_in_value, src))
            else:
                write_child(n.val, buf)
        elif isinstance(n, FullNode):
            payload_len = 0
            for i in range(16):
                payload_len += child_len(n.children[i])
            v = n.children[16]
            payload_len += _bytes_enc_len(bytes(v)) if isinstance(v, ValueNode) else 1
            total_len = _list_hdr_len(payload_len) + payload_len
            buf = bytearray()
            _write_list_hdr(payload_len, buf)
            for i in range(16):
                write_child(n.children[i], buf)
            if isinstance(v, ValueNode):
                _write_bytes(bytes(v), buf)
            else:
                buf.append(0x80)
        else:
            raise TypeError(f"cannot encode {type(n)}")

        is_embed = total_len < 32 and n is not entry.root
        if is_embed and patches:
            # an embedded node cannot carry patches: its bytes inline into
            # the parent, so hole offsets would shift. Dirty children of an
            # embedded node are themselves embedded (their RLP is < its
            # 32-byte bound), so patches here are impossible by
            # construction; assert the invariant.
            raise AssertionError("embedded node with digest holes")
        return (bytes(buf), patches, is_embed)

    # ------------------------------------------------------------------ run

    def run(self, planned=None, seg_impl=None) -> bytes:
        """Execute on device; assigns flags.hash on every hashed node,
        heals value holes, returns the final (account) root digest.

        Raises _TooManySegments when the graph exceeds the executor's
        segment table; callers fall back to the level-batched hasher."""
        from ..metrics import phase_timer

        with phase_timer("planned/phase/plan"):
            built = self.build()
        if built is None:
            raise TooManySegments()
        specs, flat_words, dst, child, shift, root_pos, total_lanes = built
        if planned is None:
            from ..ops.keccak_planned import default_planned_commit

            planned = default_planned_commit()
        # the device round-trip runs under the degradation ladder: a
        # watchdogged/retried dispatch that raises DeviceDegradedError
        # after demoting — callers fall back to the (host-routed) level
        # hashers exactly like the TooManySegments escape
        from ..ops.device import default_ladder

        _root, dig = default_ladder().dispatch(
            lambda: planned.run(specs, flat_words, dst, child, shift,
                                root_pos, want_digests=True),
            "planned device commit")
        with phase_timer("planned/phase/absorb"):
            digs = np.ascontiguousarray(dig).view(np.uint8).reshape(-1, 32)

            for n, gid in self._hashed:
                n.flags.hash = digs[gid].tobytes()
                n.flags.dirty = True
            for n, off, src in self._healed:
                root_digest = digs[src.root_lane].tobytes()
                vb = bytearray(bytes(n.val))
                vb[off:off + 32] = root_digest
                n.val = ValueNode(bytes(vb))
            return digs[root_pos].tobytes()

    def digest(self, entry: _TrieEntry) -> bytes:
        return entry.root.flags.hash


class TooManySegments(Exception):
    """Graph shape exceeds the planned executor's segment table."""


class PlannedHasher:
    """Single-trie wrapper: Trie.hash()'s planned-mode backend.

    Same contract as BatchedHasher.hash_root / FusedHasher.hash_root;
    raises TooManySegments for pathological graph shapes (caller falls
    back to the level-batched hasher)."""

    def __init__(self, planned=None):
        self._planned = planned

    def hash_root(self, root) -> HashNode:
        b = PlannedGraphBuilder()
        b.add_trie(root)
        return HashNode(b.run(self._planned))
