"""Merkle-Patricia-Trie (semantics of /root/reference/trie/trie.go).

Insert/delete/get with lazy node resolution through a NodeReader, hashing
through the pluggable hasher seam (CPU recursive or TPU level-batched —
see hasher.py), and commit into a trienode.NodeSet.

Writes after commit are rejected the same way the reference forbids them
(trie/trie.go:87 'committed' flag).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

from .encoding import key_to_hex, prefix_len
from .hasher import BATCH_THRESHOLD, BatchedHasher, Hasher, node_to_bytes
from .node import (
    EMPTY_ROOT,
    FullNode,
    HashNode,
    MissingNodeError,
    ShortNode,
    ValueNode,
    must_decode_node,
    new_flag,
)
from .trienode import Node, NodeSet


class NodeReader:
    """Resolves node blobs by (path, hash). Dict-backed default."""

    def __init__(self, store=None):
        self._store = store if store is not None else {}

    def node(self, path: bytes, node_hash: bytes) -> Optional[bytes]:
        return self._store.get(node_hash)


class Trie:
    def __init__(
        self,
        root: bytes = EMPTY_ROOT,
        reader: Optional[NodeReader] = None,
        batch_keccak: Optional[Callable] = None,
    ):
        self._reader = reader or NodeReader()
        self._batch_keccak = batch_keccak
        self.root = None if root == EMPTY_ROOT or root == b"" else HashNode(root)
        self.unhashed = 0
        self.committed = False

    def copy(self) -> "Trie":
        t = Trie.__new__(Trie)
        t._reader = self._reader
        t._batch_keccak = self._batch_keccak
        t.root = _copy_node(self.root)
        t.unhashed = self.unhashed
        t.committed = self.committed
        return t

    # ------------------------------------------------------------------ get

    def get(self, key: bytes) -> Optional[bytes]:
        if self.committed:
            raise RuntimeError("trie is already committed")
        value, newroot, resolved = self._get(self.root, key_to_hex(key), 0)
        if resolved:
            self.root = newroot
        return value

    def _get(self, n, key: bytes, pos: int):
        if n is None:
            return None, None, False
        if isinstance(n, ValueNode):
            return bytes(n), n, False
        if isinstance(n, ShortNode):
            klen = len(n.key)
            if len(key) - pos < klen or n.key != key[pos:pos + klen]:
                return None, n, False
            value, newval, resolved = self._get(n.val, key, pos + klen)
            if resolved:
                n = n.copy()
                n.val = newval
            return value, n, resolved
        if isinstance(n, FullNode):
            value, newchild, resolved = self._get(n.children[key[pos]], key, pos + 1)
            if resolved:
                n = n.copy()
                n.children[key[pos]] = newchild
            return value, n, resolved
        if isinstance(n, HashNode):
            child = self._resolve(n, key[:pos])
            value, newnode, _ = self._get(child, key, pos)
            return value, newnode, True
        raise TypeError(f"invalid node {type(n)}")

    # --------------------------------------------------------------- update

    def update(self, key: bytes, value: bytes) -> None:
        if self.committed:
            raise RuntimeError("trie is already committed")
        self.unhashed += 1
        hexkey = key_to_hex(key)
        if value:
            _, self.root = self._insert(self.root, b"", hexkey, ValueNode(value))
        else:
            _, self.root = self._delete(self.root, b"", hexkey)

    def delete(self, key: bytes) -> None:
        self.update(key, b"")

    def _insert(self, n, prefix: bytes, key: bytes, value) -> Tuple[bool, object]:
        if len(key) == 0:
            if isinstance(n, ValueNode):
                return bytes(value) != bytes(n), value
            return True, value
        if n is None:
            return True, ShortNode(key, value, new_flag())
        if isinstance(n, ShortNode):
            matchlen = prefix_len(key, n.key)
            if matchlen == len(n.key):
                dirty, nn = self._insert(
                    n.val, prefix + key[:matchlen], key[matchlen:], value
                )
                if not dirty:
                    return False, n
                return True, ShortNode(n.key, nn, new_flag())
            # diverge: create a branch at the split point
            branch = FullNode(flags=new_flag())
            _, branch.children[n.key[matchlen]] = self._insert(
                None, prefix + n.key[:matchlen + 1], n.key[matchlen + 1:], n.val
            )
            _, branch.children[key[matchlen]] = self._insert(
                None, prefix + key[:matchlen + 1], key[matchlen + 1:], value
            )
            if matchlen == 0:
                return True, branch
            return True, ShortNode(key[:matchlen], branch, new_flag())
        if isinstance(n, FullNode):
            dirty, nn = self._insert(
                n.children[key[0]], prefix + key[:1], key[1:], value
            )
            if not dirty:
                return False, n
            n = n.copy()
            n.flags = new_flag()
            n.children[key[0]] = nn
            return True, n
        if isinstance(n, HashNode):
            rn = self._resolve(n, prefix)
            dirty, nn = self._insert(rn, prefix, key, value)
            if not dirty:
                return False, rn
            return True, nn
        raise TypeError(f"invalid node {type(n)}")

    # --------------------------------------------------------------- delete

    def _delete(self, n, prefix: bytes, key: bytes) -> Tuple[bool, object]:
        if n is None:
            return False, None
        if isinstance(n, ShortNode):
            matchlen = prefix_len(key, n.key)
            if matchlen < len(n.key):
                return False, n
            if matchlen == len(key):
                return True, None  # exact match: remove
            dirty, child = self._delete(
                n.val, prefix + key[:len(n.key)], key[len(n.key):]
            )
            if not dirty:
                return False, n
            if isinstance(child, ShortNode):
                # merge the two short nodes (deletion collapsed the child)
                return True, ShortNode(n.key + child.key, child.val, new_flag())
            return True, ShortNode(n.key, child, new_flag())
        if isinstance(n, FullNode):
            dirty, nn = self._delete(n.children[key[0]], prefix + key[:1], key[1:])
            if not dirty:
                return False, n
            n = n.copy()
            n.flags = new_flag()
            n.children[key[0]] = nn
            # if only one child remains, collapse into a short node
            pos = -1
            for i, cld in enumerate(n.children):
                if cld is not None:
                    if pos == -1:
                        pos = i
                    else:
                        pos = -2
                        break
            if pos >= 0:
                if pos != 16:
                    cnode = n.children[pos]
                    if isinstance(cnode, HashNode):
                        cnode = self._resolve(cnode, prefix + bytes([pos]))
                    if isinstance(cnode, ShortNode):
                        return True, ShortNode(
                            bytes([pos]) + cnode.key, cnode.val, new_flag()
                        )
                    return True, ShortNode(bytes([pos]), cnode, new_flag())
                return True, ShortNode(bytes([16]), n.children[16], new_flag())
            return True, n
        if isinstance(n, ValueNode):
            return True, None
        if isinstance(n, HashNode):
            rn = self._resolve(n, prefix)
            dirty, nn = self._delete(rn, prefix, key)
            if not dirty:
                return False, rn
            return True, nn
        raise TypeError(f"invalid node {type(n)}")

    # -------------------------------------------------------------- resolve

    def _resolve(self, n: HashNode, prefix: bytes):
        blob = self._reader.node(prefix, bytes(n))
        if not blob:
            raise MissingNodeError(bytes(n), prefix)
        return must_decode_node(bytes(n), blob)

    # ------------------------------------------------------- hash & commit

    def hash(self) -> bytes:
        """Root hash; dirty nodes get hashed (batched on TPU when large)."""
        if self.root is None:
            return EMPTY_ROOT
        if isinstance(self.root, HashNode):
            return bytes(self.root)
        if (
            self._batch_keccak is not None
            and self.unhashed >= BATCH_THRESHOLD
        ):
            if getattr(self._batch_keccak, "planned", False):
                # the u32 planned executor: one bulk word transfer,
                # on-device digest patching, zero byte ops on device
                from ..ops.device import DeviceDegradedError
                from .planned import PlannedHasher, TooManySegments

                try:
                    h = PlannedHasher().hash_root(self.root)
                except (TooManySegments, DeviceDegradedError):
                    # pathological segment shape, or the ladder demoted
                    # the device mid-call: the level hashers finish the
                    # same dirty set (host batch keccak when demoted)
                    h = BatchedHasher(self._batch_keccak).hash_root(self.root)
            elif getattr(self._batch_keccak, "fused", False):
                # single-dispatch commit: one transfer for the whole
                # dirty set, digests patched on-device between levels
                from .hasher import FusedHasher

                h = FusedHasher().hash_root(self.root)
            else:
                h = BatchedHasher(self._batch_keccak).hash_root(self.root)
        else:
            h, _ = Hasher().hash(self.root, True)
        self.unhashed = 0
        return bytes(h)

    def commit(self, collect_leaf: bool = False) -> Tuple[bytes, Optional[NodeSet]]:
        """Hash and collect all dirty nodes into a NodeSet.

        Returns (root_hash, nodeset); nodeset is None when nothing changed.
        The trie stays usable for reads but rejects writes afterwards
        (matching trie/trie.go:585 semantics).
        """
        root_hash = self.hash()
        self.committed = True
        if self.root is None or isinstance(self.root, HashNode):
            return root_hash, None
        if self.root.flags.hash is not None and not self.root.flags.dirty:
            self.root = HashNode(root_hash)
            return root_hash, None
        nodeset = NodeSet()
        _Committer(nodeset, collect_leaf).commit(b"", self.root)
        self.root = HashNode(root_hash)
        return root_hash, nodeset


class _Committer:
    """Commit walk (semantics of /root/reference/trie/committer.go:60-160):
    collapse the hashed dirty tree into (path -> blob) entries; nodes whose
    RLP stayed <32 bytes are embedded in their parent, not stored."""

    def __init__(self, nodeset: NodeSet, collect_leaf: bool):
        self._set = nodeset
        self._collect_leaf = collect_leaf

    def commit(self, path: bytes, n):
        h = n.flags.hash if isinstance(n, (ShortNode, FullNode)) else None
        if h is not None and not n.flags.dirty:
            return HashNode(h)
        if isinstance(n, ShortNode):
            collapsed = ShortNode(n.key, n.val, n.flags)
            if isinstance(n.val, (ShortNode, FullNode)):
                collapsed.val = self.commit(path + n.key, n.val)
            elif isinstance(n.val, HashNode):
                collapsed.val = n.val
            return self._store(path, collapsed, n)
        if isinstance(n, FullNode):
            children = [None] * 17
            for i in range(16):
                c = n.children[i]
                if c is None:
                    continue
                if isinstance(c, (ShortNode, FullNode)):
                    children[i] = self.commit(path + bytes([i]), c)
                else:
                    children[i] = c
            children[16] = n.children[16]
            collapsed = FullNode(children, n.flags)
            return self._store(path, collapsed, n)
        raise TypeError(f"cannot commit {type(n)}")

    def _store(self, path: bytes, collapsed, orig):
        h = orig.flags.hash
        if h is None:
            # small node embedded in its parent; not stored on its own
            return collapsed
        blob = node_to_bytes(collapsed)
        self._set.add_node(path, Node(h, blob))
        orig.flags.dirty = False
        if self._collect_leaf and isinstance(collapsed, ShortNode):
            if isinstance(collapsed.val, ValueNode):
                self._set.add_leaf(h, bytes(collapsed.val))
        return HashNode(h)


def _copy_node(n):
    if isinstance(n, (ShortNode, FullNode)):
        c = n.copy()
        if isinstance(c, ShortNode):
            c.val = _copy_node(c.val)
        else:
            c.children = [_copy_node(x) for x in c.children]
        return c
    return n
