"""Range proofs (semantics of /root/reference/trie/proof.go
VerifyRangeProof/proofToPath/unsetInternal/unset/hasRightElement).

Given a contiguous, sorted slice of (key, value) leaves plus Merkle proofs
for the two range edges, verify the slice is exactly the trie's content in
[first_key, last_key] and learn whether more leaves exist to the right —
the primitive under state-sync leaf batches (sync/handlers/leafs_request.go
:374 builds these, sync/client/client.go:180 verifies them).

The algorithm: materialize both edge paths from the proof blobs into one
partial trie whose off-path children stay as opaque HashNodes; delete every
node strictly inside the range (they must be reconstructible from the
leaves alone); re-insert the leaf slice; the recomputed root must equal the
target. Completeness holds because any omitted/injected leaf changes some
node on the rebuilt fringe.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..metrics import count_drop
from ..native import keccak256
from .encoding import key_to_hex
from .node import (
    EMPTY_ROOT,
    FullNode,
    HashNode,
    MissingNodeError,
    ProofCorruptNodeError,
    ProofError,
    ProofMissingNodeError,
    ShortNode,
    ValueNode,
    must_decode_node,
    new_flag,
)
from .stacktrie import StackTrie
from .trie import NodeReader, Trie

# ProofError moved to trie/node.py (shared with proof.py) and grew typed
# subclasses; re-exported here for existing importers (sync/client.py)
__all_errors__ = (ProofError, ProofMissingNodeError, ProofCorruptNodeError)


def _resolve_from_proof(proof: dict, node_hash: bytes):
    blob = proof.get(node_hash)
    if blob is None:
        count_drop("trie/proof_range/missing_node")
        raise ProofMissingNodeError(node_hash, "range proof")
    try:
        return must_decode_node(node_hash, blob)
    except Exception as exc:
        count_drop("trie/proof_range/corrupt_node")
        raise ProofCorruptNodeError(
            node_hash, f"undecodable: {exc}") from exc


def _get(tn, key: bytes):
    """Walk to the next unresolved/terminal node (proof.go get, with
    skipResolved=False): returns (key_rest, child)."""
    while True:
        if isinstance(tn, ShortNode):
            if len(key) < len(tn.key) or tn.key != key[: len(tn.key)]:
                return None, None
            return key[len(tn.key):], tn.val
        if isinstance(tn, FullNode):
            return key[1:], tn.children[key[0]]
        if isinstance(tn, (HashNode, ValueNode)) or tn is None:
            return key, tn
        raise ProofError(f"invalid node {type(tn)}")


def proof_to_path(root_hash: bytes, root, key: bytes, proof: dict,
                  allow_non_existent: bool):
    """Materialize the path for [key] from proof blobs into [root]
    (proof.go proofToPath). Returns (root_node, value_or_None)."""
    if root is None:
        root = _resolve_from_proof(proof, root_hash)
    key = key_to_hex(key)
    parent = root
    while True:
        keyrest, child = _get(parent, key)
        if child is None:
            if keyrest is None or child is None:
                if allow_non_existent:
                    return root, None
                raise ProofError("the node is not contained in trie")
        if isinstance(child, (ShortNode, FullNode)):
            key, parent = keyrest, child
            continue
        valnode = None
        if isinstance(child, HashNode):
            child = _resolve_from_proof(proof, bytes(child))
        elif isinstance(child, ValueNode):
            valnode = bytes(child)
        # link into the parent
        if isinstance(parent, ShortNode):
            parent.val = child
        elif isinstance(parent, FullNode):
            parent.children[key[0]] = child
        if valnode is not None:
            return root, valnode
        key, parent = keyrest, child


def _unset(parent, child, key: bytes, pos: int, remove_left: bool) -> None:
    """proof.go unset: prune the in-range side of an edge path."""
    if isinstance(child, FullNode):
        if remove_left:
            for i in range(key[pos]):
                child.children[i] = None
        else:
            for i in range(key[pos] + 1, 16):
                child.children[i] = None
        child.flags = new_flag()
        _unset(child, child.children[key[pos]], key, pos + 1, remove_left)
        return
    if isinstance(child, ShortNode):
        if len(key[pos:]) < len(child.key) or child.key != key[pos: pos + len(child.key)]:
            # fork below the edge path: decide by ordering whether the
            # dangling branch is inside the range
            if remove_left:
                if child.key < key[pos:]:
                    parent.children[key[pos - 1]] = None
            else:
                if child.key > key[pos:]:
                    parent.children[key[pos - 1]] = None
            return
        if isinstance(child.val, ValueNode):
            parent.children[key[pos - 1]] = None
            return
        child.flags = new_flag()
        _unset(child, child.val, key, pos + len(child.key), remove_left)
        return
    if child is None:
        return
    raise ProofError("unexpected node in unset (hash/value)")


def _unset_internal(n, left_key: bytes, right_key: bytes) -> bool:
    """proof.go unsetInternal: remove every node strictly between the two
    edge paths. Returns True when the whole trie should be emptied."""
    left = key_to_hex(left_key)
    right = key_to_hex(right_key)
    pos = 0
    parent = None
    short_fork_left = short_fork_right = 0

    def cmp(a: bytes, b: bytes) -> int:
        return (a > b) - (a < b)

    while True:
        if isinstance(n, ShortNode):
            n.flags = new_flag()
            if len(left) - pos < len(n.key):
                short_fork_left = cmp(left[pos:], n.key)
            else:
                short_fork_left = cmp(left[pos: pos + len(n.key)], n.key)
            if len(right) - pos < len(n.key):
                short_fork_right = cmp(right[pos:], n.key)
            else:
                short_fork_right = cmp(right[pos: pos + len(n.key)], n.key)
            if short_fork_left != 0 or short_fork_right != 0:
                break
            parent = n
            n, pos = n.val, pos + len(n.key)
        elif isinstance(n, FullNode):
            n.flags = new_flag()
            leftnode = n.children[left[pos]]
            rightnode = n.children[right[pos]]
            if leftnode is None or rightnode is None or leftnode is not rightnode:
                break
            parent = n
            n, pos = n.children[left[pos]], pos + 1
        else:
            raise ProofError(f"invalid node at fork search: {type(n)}")

    if isinstance(n, ShortNode):
        if short_fork_left == -1 and short_fork_right == -1:
            raise ProofError("empty range")
        if short_fork_left == 1 and short_fork_right == 1:
            raise ProofError("empty range")
        if short_fork_left != 0 and short_fork_right != 0:
            if parent is None:
                return True
            parent.children[left[pos - 1]] = None
            return False
        if short_fork_right != 0:
            if isinstance(n.val, ValueNode):
                if parent is None:
                    return True
                parent.children[left[pos - 1]] = None
                return False
            _unset(n, n.val, left[pos:], len(n.key), False)
            return False
        if short_fork_left != 0:
            if isinstance(n.val, ValueNode):
                if parent is None:
                    return True
                parent.children[right[pos - 1]] = None
                return False
            _unset(n, n.val, right[pos:], len(n.key), True)
            return False
        return False
    if isinstance(n, FullNode):
        for i in range(left[pos] + 1, right[pos]):
            n.children[i] = None
        _unset(n, n.children[left[pos]], left[pos:], 1, False)
        _unset(n, n.children[right[pos]], right[pos:], 1, True)
        return False
    raise ProofError(f"invalid fork node {type(n)}")


def has_right_element(node, key: bytes) -> bool:
    """proof.go hasRightElement: any leaf right of [key] in the partial trie."""
    pos, key = 0, key_to_hex(key)
    while node is not None:
        if isinstance(node, FullNode):
            for i in range(key[pos] + 1, 16):
                if node.children[i] is not None:
                    return True
            node, pos = node.children[key[pos]], pos + 1
        elif isinstance(node, ShortNode):
            if len(key) - pos < len(node.key) or node.key != key[pos: pos + len(node.key)]:
                return node.key > key[pos:]
            node, pos = node.val, pos + len(node.key)
        elif isinstance(node, ValueNode):
            return False
        else:
            raise ProofError("unresolved node while checking right element")
    return False


def verify_range_proof(root_hash: bytes, first_key: bytes, last_key: bytes,
                       keys: List[bytes], values: List[bytes],
                       proof: Optional[dict]) -> bool:
    """VerifyRangeProof (proof.go): returns has_more (leaves exist right of
    the range); raises ProofError on an invalid proof.

    proof maps node hash → node blob, or None for a whole-trie proof.
    """
    if len(keys) != len(values):
        raise ProofError(f"inconsistent proof data: {len(keys)} keys, {len(values)} values")
    for i in range(len(keys) - 1):
        if keys[i] >= keys[i + 1]:
            raise ProofError("range is not monotonically increasing")
    for v in values:
        if len(v) == 0:
            raise ProofError("range contains deletion")

    # whole-trie proof: rebuild from scratch
    if proof is None:
        st = StackTrie()
        for k, v in zip(keys, values):
            st.update(k, v)
        if st.hash() != root_hash:
            raise ProofError("invalid proof: full-range root mismatch")
        return False

    # edge proof with zero keys: prove the trie has nothing at/after first
    if len(keys) == 0:
        root, val = proof_to_path(root_hash, None, first_key, proof, True)
        if val is not None or has_right_element(root, first_key):
            raise ProofError("more entries available")
        return False

    # one element, identical edges
    if len(keys) == 1 and first_key == last_key:
        root, val = proof_to_path(root_hash, None, first_key, proof, False)
        if first_key != keys[0]:
            raise ProofError("correct proof but invalid key")
        if val != values[0]:
            raise ProofError("correct proof but invalid data")
        return has_right_element(root, first_key)

    if first_key >= last_key:
        raise ProofError("invalid edge keys")
    if len(first_key) != len(last_key):
        raise ProofError("inconsistent edge key lengths")

    root, _ = proof_to_path(root_hash, None, first_key, proof, True)
    root, _ = proof_to_path(root_hash, root, last_key, proof, True)
    empty = _unset_internal(root, first_key, last_key)

    tr = Trie(EMPTY_ROOT, NodeReader({}))
    tr.root = None if empty else root
    try:
        for k, v in zip(keys, values):
            tr.update(k, v)
        got = tr.hash()
    except MissingNodeError as e:
        raise ProofError(f"invalid proof: dangling reference {e}") from e
    if got != root_hash:
        raise ProofError(
            f"invalid proof: want root {root_hash.hex()}, got {got.hex()}"
        )
    return has_right_element(tr.root, keys[-1])
