"""Branch-aware resident account mirror: drives one device-resident
IncrementalTrie through a chain's verify/accept/reject lifecycle,
including sibling competition and reorgs.

The resident executor (ops/keccak_resident.py) holds a single linear
trie history, but consensus verifies SIBLING blocks against different
parents (core/blockchain.go:1424 reorg; plugin/evm/block.go Verify/
Accept/Reject). This adapter reconciles the two:

  - the mirror keeps a LINEAR applied stack (one undo scope per applied
    block, native/mpt_inc.cpp checkpoint/rollback);
  - verifying a block whose parent is not the current head REWINDS
    (rollback scopes) to the nearest applied ancestor of the parent and
    REPLAYS the saved per-block update batches down the target branch;
  - accept finalizes: scopes (and records) of accepted blocks deeper
    than the TIP_BUFFER flush (journal memory reclaimed); the retained
    window keeps recent accepted states rewindable for reads — the
    reference's 32-root tip buffer (core/state_manager.go:189+);
  - reject drops a block (and any applied descendants, which consensus
    rejects with it) by rewinding through it.

Each verify returns the block's state root from the device (lazy handle
resolved to bytes), so the chain adapter can compare it against the
header exactly where statedb.IntermediateRoot's result is used today
(core/blockchain.go:1331 ValidateState).

Upstream integration: state/resident_trie.py (the StateDB facade that
feeds per-block account batches and reads through here),
core/state_manager.py ResidentTrieWriter (consensus lifecycle + the
interval disk export), core/blockchain.py CacheConfig.resident_account_
trie (boot + wiring).
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ..fault import FailpointError, failpoint
from ..fault import register as _register_failpoint
from ..native.mpt import IncrementalTrie

FP_SPOT_CHECK = _register_failpoint(
    "state/resident/spot_check",
    "`raise` forces the periodic mirror spot-check to report divergence "
    "(exercises the quarantine/reboot path without corrupting a trie)")


class MirrorError(Exception):
    pass


def _locked(fn):
    """Serialize public mirror ops: the chain calls verify/preview from
    the insert path (under chainmu) but accept/export ride the async
    acceptor thread (core/blockchain.py _accept_post_process)."""

    @functools.wraps(fn)
    def wrapper(self, *a, **kw):
        with self._lock:
            return fn(self, *a, **kw)

    return wrapper


class ResidentAccountMirror:
    GENESIS = b"\x00" * 32  # sentinel parent of the initial state
    # single in-flight anonymous state (a miner's block-under-construction:
    # root computed before the block hash exists; the next verify with the
    # same parent+batch adopts it, anything else rewinds it)
    ANON = b"\x01" + b"anon" * 7 + b"\x01\x01\x01"

    def __init__(self, items: Sequence[Tuple[bytes, bytes]] = (),
                 executor=None, base_key: Optional[bytes] = None,
                 device_timeout: Optional[float] = None,
                 cpu_threads: Optional[int] = None,
                 prefer_host: Optional[bool] = None,
                 pipeline_depth: int = 0,
                 template_residency: bool = False,
                 mesh_devices: int = 0,
                 lean_rows: bool = False):
        import os

        if cpu_threads is None or int(cpu_threads) <= 0:
            from ..native import default_cpu_threads

            cpu_threads = default_cpu_threads()
        self._cpu_threads = int(cpu_threads)
        # CPU fast path (VERDICT r5 #4, the config-10 regression): when
        # no TPU backend resolves, the "device" a ResidentExecutor would
        # dispatch to is XLA-CPU, whose keccak is ~150x slower than the
        # native hasher — the resident chain path ran 5.6x behind the
        # default path because of it. Unless the caller pinned the
        # device path (an explicit executor, prefer_host=False, or the
        # env override), start in host mode from construction: the
        # mirror lifecycle (verify/accept/reject/reorg, exports, reads)
        # and the roots are identical, but every commit runs the
        # threaded native incremental hasher. This is also what makes a
        # later device-wedge takeover a soft landing — takeover lands on
        # exactly this path.
        env = os.environ.get("CORETH_TPU_RESIDENT_HOST", "").lower()
        if env in ("1", "true", "yes"):
            prefer_host = True
        elif env in ("0", "false", "no"):
            prefer_host = False
        if prefer_host is None:
            if executor is not None:
                prefer_host = False
            else:
                from ..ops.keccak_planned import _tpu_backend

                prefer_host = not _tpu_backend()
        self.host_mode = bool(prefer_host)
        if self.host_mode:
            from ..metrics import default_registry

            default_registry.counter("state/resident/cpu_fastpath").inc(1)
        elif executor is None:
            if mesh_devices and int(mesh_devices) > 0:
                # mesh mode (knob resident-mesh-devices): store and
                # arena rows sharded P('batch', None) across the first
                # [mesh_devices] devices. MeshConfigError propagates —
                # an impossible width is an actionable config failure,
                # not a reason to fall back unsharded silently.
                from ..parallel import make_mesh, resident_executor_over_mesh

                executor = resident_executor_over_mesh(
                    make_mesh(int(mesh_devices)))
            else:
                from ..ops.keccak_resident import ResidentExecutor

                executor = ResidentExecutor()
        self.mesh_devices = int(mesh_devices or 0)
        self.ex = executor  # None in host mode unless the caller passed one
        # cross-commit device pipelining: up to [pipeline_depth] verified
        # commits may stay IN FLIGHT on the device, each optimistically
        # recorded under the header root it was dispatched against (the
        # chain threads it through verify/preview) and settled — device
        # root compared against that header root — at the next drain
        # point: accept, reject, reorg/branch switch, spot-check, export,
        # window refill, or host takeover. 0 = every commit synchronizes
        # before verify returns (the pre-pipelining behavior).
        self.pipeline_depth = max(0, int(pipeline_depth or 0))
        # template residency: planned-path semantics (the host digest
        # cache re-absorbs every commit's digests, so root()/export work
        # per commit and takeover needs no full rehash) at resident-path
        # transfer cost (device keeps arenas/store; uploads carry only
        # fresh leaf content). The per-commit absorb IS a device sync,
        # so it excludes pipelining.
        self.template = bool(template_residency) and not self.host_mode
        if self.template:
            self.pipeline_depth = 0
        if self.ex is not None:
            self.ex.pipeline_depth = self.pipeline_depth
        # in-flight pipelined commits, DISPATCH order — always a
        # contiguous suffix of _applied (dispatch happens only at the
        # head; every branch switch drains first).
        # guarded-by: _lock
        self._inflight: List[dict] = []
        # 1 - (time blocked at drain / wall since dispatch) of the most
        # recently drained commit — the overlap the pipeline actually
        # bought (0.0 when serial or never pipelined)
        self.last_overlap_fraction = 0.0
        # chain hook fired (under the mirror lock) when a device wedge
        # forces the one-way host takeover; receives the reason string.
        # Must not call back into mirror methods or take chainmu.
        self.on_takeover = None
        self._lock = threading.RLock()
        self.trie = IncrementalTrie(items)
        # device-failure takeover (VERDICT r4 #4): a commit the device
        # does not answer within [device_timeout] seconds triggers a
        # one-way host takeover — full host rehash, then every later
        # commit/export runs commit_cpu. None = watchdog off (tests /
        # trusted local backends); env override for ops.
        if device_timeout is None:
            raw = os.environ.get("CORETH_TPU_RESIDENT_TIMEOUT", "")
            try:
                device_timeout = float(raw) if raw else None
            except ValueError:
                from ..log import get_logger

                get_logger("state").warning(
                    "ignoring malformed CORETH_TPU_RESIDENT_TIMEOUT=%r",
                    raw)
                device_timeout = None
        if device_timeout is not None and device_timeout <= 0:
            device_timeout = None  # 0 disables the watchdog (config doc)
        self.device_timeout = device_timeout
        # storage-lean node rows (PR 18, resident_lean_rows knob): the
        # native planner ships fresh single-block rows as 80-byte wire
        # records instead of 136-byte padded rows. A no-op for host
        # commits, so it stays pinned across takeovers/demotions.
        self.lean_rows = bool(lean_rows)
        if self.lean_rows:
            self.trie.set_lean(True)
        base = base_key if base_key is not None else self.GENESIS
        # flags BEFORE the genesis commit: a takeover during it must not
        # have its degradation markers clobbered below
        self._dirty_since_export = True  # genesis image not yet on disk
        self._export_degraded = False    # failed write -> next export full
        # the genesis commit (everything is dirty after construction)
        self._roots: Dict[bytes, bytes] = {base: self._commit_root()}
        self._by_root: Dict[bytes, List[bytes]] = {
            self._roots[base]: [base]
        }
        self._parent: Dict[bytes, bytes] = {}
        self._batch: Dict[bytes, List[Tuple[bytes, bytes]]] = {}
        self._batch_keys: Dict[bytes, frozenset] = {}  # lazy overlay index
        self._applied: List[bytes] = [base]
        self._accepted: set = {base}

    # ---- device-failure takeover (VERDICT r4 #4) -------------------------

    def _commit_root(self) -> bytes:  # guarded-by: _lock
        """Settle the trie's current state and return the 32-byte root —
        on the device while healthy, on the host after takeover. The
        device path runs under the watchdog; a wedge triggers the
        takeover and the SAME commit completes on the CPU, so callers
        never see the failure (the chain does not stall)."""
        from ..metrics import phase_timer
        from ..metrics.spans import span
        from ..native.mpt import DeviceWedgedError

        with span("resident/commit", host_mode=self.host_mode):
            with phase_timer("resident/phase/commit"):
                if self.host_mode:
                    if self.ex is not None:
                        # host commits move no bytes; a stale device-era
                        # value here would be re-counted per commit by
                        # anything summing ex.h2d_bytes across commits
                        self.ex.h2d_bytes = 0
                    return self.trie.commit_cpu(threads=self._cpu_threads)
                try:
                    if self.template:
                        return self.trie.commit_template(
                            self.ex, self.device_timeout)
                    return self.trie.commit_resident_timed(
                        self.ex, self.device_timeout)
                except DeviceWedgedError as e:
                    # degradation left the trie settled at the same
                    # state; re-enter to return its root from whichever
                    # rung we landed on. Bounded: each _degrade moves
                    # strictly down (mesh -> single device -> host) and
                    # the host path cannot wedge.
                    self._degrade(str(e))
                    return self._commit_root()

    def _degrade(self, why: str) -> None:  # guarded-by: _lock
        """Walk ONE rung down the device degradation ladder:
        mesh-sharded resident -> single-device resident -> host. Each
        step is bit-exact — the mesh rung re-proves its image against
        the host oracle root before keeping commits on the device, and
        the host rung IS the oracle."""
        if not self._demote_mesh(why):
            self._take_over_host(why)

    def _demote_mesh(self, why: str) -> bool:  # guarded-by: _lock
        """Mesh ladder rung: a wedge on a >1-shard executor first tries
        to rebuild residency on a SINGLE device before abandoning the
        device path entirely. Sequence: host-oracle rehash (also the
        warm digest cache later exports/spot-checks read), then abandon
        every device-side row/slot assignment (rebase_residency), then
        a full recommit on a fresh unsharded executor, bit-exact
        against the oracle root. Returns False — caller escalates to
        the host takeover, which is safe from any pinned mode — when
        already at the bottom device rung or when the rebuild itself
        fails or diverges."""
        if self.host_mode or self.ex is None:
            return False
        if int(getattr(self.ex, "shards", 1)) <= 1:
            return False  # bottom device rung: only the host is left
        from ..log import get_logger
        from ..metrics import default_registry

        if bool(getattr(self.ex, "spans_processes", False)):
            # multi-process mesh (PR 18): the single-device rung is a
            # UNILATERAL local rebuild — on a mesh spanning jax
            # processes it would desync the SPMD program on every other
            # process. Skip straight to the host rung, which is local
            # by construction.
            default_registry.counter(
                "state/resident/mesh_demotion_cross_process_skips").inc(1)
            get_logger("state").error(
                "mesh resident backend wedged (%s) on a mesh spanning "
                "multiple processes — the single-device rung is "
                "unavailable (local rebuild would desync SPMD peers); "
                "escalating straight to the host takeover", why)
            return False

        get_logger("state").error(
            "mesh resident backend wedged (%s) — demoting %d-shard mesh "
            "to a single device: host oracle rehash, fresh residency, "
            "bit-exact recommit of %d nodes",
            why, int(getattr(self.ex, "shards", 1)), self.trie.num_nodes)
        try:
            host_root = self.trie.rehash_host(threads=self._cpu_threads)
            from ..ops.keccak_resident import ResidentExecutor

            ex = ResidentExecutor()
            ex.pipeline_depth = self.pipeline_depth
            self.trie.rebase_residency()
            self.ex = ex
            if self.template:
                root = self.trie.commit_template(ex, self.device_timeout)
            else:
                root = self.trie.commit_resident_timed(
                    ex, self.device_timeout)
            if root != host_root:
                raise MirrorError(
                    "single-device recommit root does not match the "
                    "host oracle")
        except BaseException as rebuild_err:
            # wedged again or diverged mid-rebuild: hand the SAME wedge
            # to the host takeover (its rehash works from any mode the
            # failed rebuild left pinned)
            default_registry.counter(
                "state/resident/mesh_demotion_failures").inc(1)
            get_logger("state").error(
                "single-device rebuild failed (%s) — escalating the "
                "wedge to the host takeover", rebuild_err)
            return False
        default_registry.counter("state/resident/mesh_demotions").inc(1)
        # device-era delta marks predate the demotion — same full-image
        # discipline as the host takeover
        self._export_degraded = True
        self._dirty_since_export = True
        return True

    def _take_over_host(self, why: str) -> None:  # guarded-by: _lock
        """One-way device -> host switch: rebuild the full host digest
        cache (the device store is unreachable) and degrade the next
        export to a full image. The mirror keeps ALL state — records,
        journal, branch logic — so verify/accept/reject/reorg continue
        with identical roots; only the hashing runs on the CPU. The
        reference analog is the lifecycle assumption around
        core/blockchain.go:1361-1365 that the state backend never
        vanishes — here it can, and the chain must not stall."""
        from ..log import get_logger
        from ..metrics import default_registry

        default_registry.counter("state/resident/device_takeovers").inc(1)
        get_logger("state").error(
            "resident device backend wedged (%s) — taking over on the "
            "host: full rehash of %d nodes, then CPU-resident commits",
            why, self.trie.num_nodes)
        self.host_mode = True
        self.template = False  # host commits absorb by construction
        if self.ex is not None:
            self.ex.h2d_bytes = 0  # no further uploads after takeover
        self.trie.rehash_host(threads=self._cpu_threads)
        # the export delta marks predate the takeover; write a full
        # image at the next interval so disk supersedes any device-era
        # uncertainty
        self._export_degraded = True
        self._dirty_since_export = True
        if self.on_takeover is not None:
            try:
                self.on_takeover(why)
            except Exception:
                from ..metrics import count_drop

                count_drop("state/resident/takeover_hook_error")

    @property
    def shards(self) -> int:
        """Mesh shards behind the CURRENT ladder rung (1 on the host,
        on a single device, or after a mesh demotion) — the flight
        record's un-ragged `resident/shards`."""
        if self.host_mode or self.ex is None:
            return 1
        return int(getattr(self.ex, "shards", 1))

    # ---- cross-commit device pipelining ----------------------------------

    def _pipelining(self) -> bool:
        return (self.pipeline_depth > 0 and not self.host_mode
                and not self.template and self.ex is not None)

    def _pipeline_gauge(self) -> None:  # guarded-by: _lock
        # current window occupancy, exported so an operator can tell a
        # saturated pipeline (depth pinned at max) from an idle one
        from ..metrics import default_registry

        default_registry.gauge("resident/pipeline/depth").update(
            len(self._inflight))

    def _commit_dispatch(self, key: bytes, expected: bytes,  # guarded-by: _lock
                         updates) -> bytes:
        """Dispatch this commit's device program WITHOUT waiting for its
        root; the entry settles at the next drain point. The caller has
        already opened the scope and applied [updates]; [expected] is
        the header root this commit is optimistically recorded under."""
        from ..native.mpt import DeviceWedgedError

        try:
            resolve = self.trie.commit_resident_dispatch(
                self.ex, self.device_timeout)
        except DeviceWedgedError as e:
            # wedge at dispatch: the current block's open scope sits on
            # top of the window's scopes — fold it out of the way, land
            # the window on the host, then re-apply and commit serially
            self.trie.rollback()
            self._drain_on_host(str(e))
            self.trie.checkpoint()
            self.trie.update(updates)
            return self._commit_root()  # whichever rung the drain landed on
        self._inflight.append({
            "key": key, "expected": expected, "resolve": resolve,
            "t_dispatch": time.monotonic()})
        self._pipeline_gauge()
        return expected

    def _drain_pipeline(self, leave: int = 0,  # guarded-by: _lock
                        upto: Optional[bytes] = None) -> None:
        """Resolve in-flight pipelined commits in dispatch order,
        comparing each device root against the header root it was
        recorded under. leave: stop once at most this many entries
        remain (window refill before the next dispatch); upto: stop
        once this block's entry has settled (accept only needs its own
        prefix). A device wedge mid-drain lands the WHOLE window on the
        host bit-exactly; a root mismatch rewinds the offending commit
        and raises MirrorError."""
        from ..native.mpt import DeviceWedgedError

        if upto is not None and not any(
                e["key"] == upto for e in self._inflight):
            return
        try:
            while len(self._inflight) > max(0, leave):
                ent = self._inflight.pop(0)
                t0 = time.monotonic()
                try:
                    root = ent["resolve"]()
                except DeviceWedgedError as e:
                    self._inflight.insert(0, ent)
                    self._drain_on_host(str(e))
                    return
                self._note_overlap(ent, t0)
                if root != ent["expected"]:
                    self._pipeline_diverged(ent, root)
                if upto is not None and ent["key"] == upto:
                    return
        finally:
            self._pipeline_gauge()

    def _note_overlap(self, ent: dict, t0: float) -> None:  # guarded-by: _lock
        """Record how much of this commit's device time the pipeline hid
        (1 = the drain found it already finished; 0 = fully serial)."""
        from ..metrics import default_registry

        now = time.monotonic()
        wall = now - ent["t_dispatch"]
        blocked = now - t0
        frac = 0.0 if wall <= 0 else max(0.0, 1.0 - blocked / wall)
        self.last_overlap_fraction = frac
        default_registry.gauge("resident/overlap_fraction").update(frac)

    def _drain_on_host(self, why: str) -> None:  # guarded-by: _lock
        """A device wedge surfaced while the pipeline window was
        non-empty: degrade one ladder rung (mesh -> single device, or
        device -> host — the name predates the mesh rung; either way
        the HOST oracle root anchors the landing), then recompute every
        in-flight commit's root serially on the landing rung — rewind
        through the window's scopes and replay each batch, comparing
        against the header root it was recorded under. Bit-exact: the
        mesh demotion re-proved its image against the host oracle, and
        the host hasher is the oracle the device was checked against
        all along (the PR 6 soft landing, now window-deep)."""
        window, self._inflight = list(self._inflight), []
        self._pipeline_gauge()
        self._degrade(why)
        for _ in window:
            self._applied.pop()
            self.trie.rollback()
            self._dirty_since_export = True
        for i, ent in enumerate(window):
            self.trie.checkpoint()
            self.trie.update(self._batch[ent["key"]])
            self._dirty_since_export = True
            root = self._commit_root()
            if root != ent["expected"]:
                # the host oracle disagrees with the recorded header
                # root: the BLOCK was wrong, not the device — drop it
                # and everything stacked on it
                self.trie.rollback()
                for e in window[i:]:
                    self._forget(e["key"])
                self._prune_orphans()
                from ..metrics import default_registry

                default_registry.counter(
                    "state/resident/pipeline_divergences").inc(1)
                raise MirrorError(
                    "host recompute of in-flight block "
                    f"{ent['key'].hex()[:8]} does not match its header "
                    "root")
            self._applied.append(ent["key"])

    def _pipeline_diverged(self, ent: dict, got: bytes) -> None:  # guarded-by: _lock
        """A drained pipelined commit's device root differs from the
        header root it was optimistically recorded under. Rewind the
        offending commit and every applied descendant (they built on a
        wrong state), forget the rest of the window, and raise — the
        chain adapter's fallback recomputes TRUE roots on the disk
        path, so a bad block still fails consensus and the periodic
        spot-check quarantines a genuinely corrupt device."""
        from ..log import get_logger
        from ..metrics import default_registry

        default_registry.counter(
            "state/resident/pipeline_divergences").inc(1)
        stale, self._inflight = list(self._inflight), []
        self._pipeline_gauge()
        key = ent["key"]
        if key in self._applied:
            idx = self._applied.index(key)
            while len(self._applied) > idx:
                dropped = self._applied.pop()
                self.trie.rollback()
                self._dirty_since_export = True
                self._forget(dropped)
        else:
            self._forget(key)
        for e in stale:
            self._forget(e["key"])
        self._prune_orphans()
        get_logger("state").error(
            "pipelined resident commit diverged at %s: device %s != "
            "header %s — rewound %d in-flight block(s)",
            key.hex()[:8], got.hex()[:16], ent["expected"].hex()[:16],
            1 + len(stale))
        raise MirrorError(
            f"pipelined commit root mismatch at {key.hex()[:8]}")

    @_locked  # guarded-by: _lock
    def spot_check(self) -> bool:
        """Periodic device-vs-host cross-check (chain knob
        resident_spot_check_interval): verify the device-resident image
        against the host keccak oracle WITHOUT ending residency. Returns
        False on divergence — the chain quarantines via reboot_mirror()
        instead of letting a silently-corrupt mirror feed consensus.

        rehash_host would be the obvious oracle but it one-way pins the
        trie to host mode, so a PASSING check would still end residency.
        Instead: settle + read back the device store (watchdogged, like
        export_to), then export the full node image and check
        keccak256(node_rlp) == claimed digest for every node on the host,
        plus the cached applied root appearing in the digest set. Node
        RLP embeds children digests from the same store, so this
        transitively verifies the whole device digest chain down from
        the root. The full export consumes the delta marks, so the next
        interval flush is degraded to a full image."""
        from ..metrics import default_registry
        from ..native import keccak256_batch
        from ..native.mpt import DeviceWedgedError

        default_registry.counter("state/resident/spot_checks").inc(1)
        try:
            failpoint("state/resident/spot_check")
        except FailpointError:
            return False  # chaos-forced divergence
        if self.host_mode or self.trie.num_nodes == 0:
            return True  # the host oracle already computed these roots
        # the check must not race an in-flight pipelined window: its
        # store readback would observe commits whose roots were never
        # compared, mis-attributing a divergence to "the device" when a
        # specific block was wrong. Settle the window first (per-block
        # attribution), then cross-check the settled image.
        # guarded-by: _lock (the decorator serializes against dispatch)
        try:
            self._drain_pipeline()
        except MirrorError:
            default_registry.counter(
                "state/resident/spot_check_failures").inc(1)
            return False
        if self.host_mode:
            return True  # the drain wedged and took over on the host
        try:
            if self.template:
                # template commits absorb every digest as they go — the
                # host cache is already the device image; just settle
                dev_root = self.trie.commit_template(
                    self.ex, self.device_timeout)
            else:
                dev_root = self.trie.commit_resident_timed(
                    self.ex, self.device_timeout)
                self._absorb_device_store("spot-check store readback")
        except DeviceWedgedError as e:
            # not a divergence: the ladder's failure mode. Degrade like
            # any wedged commit; a mesh demotion already verified the
            # rebuilt image against the host oracle root, and the host
            # rung IS the oracle.
            self._degrade(str(e))
            if self.host_mode:
                self.trie.commit_cpu(threads=self._cpu_threads)
            return True
        digs, blob, off = self.trie.export_nodes(delta=False)
        self._export_degraded = True
        self._dirty_since_export = True
        n = int(digs.shape[0])
        msgs = [bytes(blob[int(off[i]):int(off[i + 1])]) for i in range(n)]
        host = keccak256_batch(msgs, threads=self._cpu_threads)
        claimed = {digs[i].tobytes() for i in range(n)}
        ok = all(digs[i].tobytes() == host[i] for i in range(n))
        cached = self._roots.get(self._applied[-1])
        ok = ok and dev_root in claimed and (
            cached is None or cached == dev_root)
        if not ok:
            default_registry.counter(
                "state/resident/spot_check_failures").inc(1)
        return ok

    def _absorb_device_store(self, what: str) -> None:
        """Sync the device store into the host digest cache before an
        export/spot-check read. Per-shard readback when the executor
        speaks it (PR 18: shard-local store partitions, no replicated
        host-side gather); executors exposing only `.store` (wrappers,
        stubs) keep the legacy full readback. The watchdog wraps only
        the d2h; absorb mutates the trie on THIS thread, so an
        abandoned worker can't race it."""
        import numpy as np

        from ..native.mpt import _run_with_watchdog

        reader = getattr(self.ex, "store_parts", None)
        if reader is not None:
            work, absorb = (lambda: list(reader())), \
                self.trie.absorb_store_parts
        else:
            work, absorb = (lambda: np.asarray(self.ex.store)), \
                self.trie.absorb_store
        if self.device_timeout is None:
            absorb(work())
        else:
            absorb(_run_with_watchdog(work, self.device_timeout, what))

    # ---- lifecycle -------------------------------------------------------

    @_locked  # guarded-by: _lock
    def verify(self, parent_hash: bytes, block_hash: bytes,
               updates: Sequence[Tuple[bytes, bytes]],
               expected_root: Optional[bytes] = None) -> bytes:
        """Apply [updates] on top of [parent_hash]'s state and return the
        resulting state root. Saves the batch so later branch switches
        can replay it.

        When [expected_root] (the header root) is given and pipelining
        is on, the commit is DISPATCHED but not synchronized: the
        expected root is recorded and returned optimistically, and the
        device root is compared against it at the next drain point —
        host planning of the next block overlaps this block's device
        execution."""
        if parent_hash == self.ANON:
            parent_hash = self._promote_anon()
        if parent_hash not in self._roots:
            raise MirrorError(f"unknown parent {parent_hash.hex()[:8]}")
        if block_hash in self._roots:
            # re-verify of a known block: the root is cached, but the
            # mirror must still LAND on that block's state (callers read
            # intermediate state through the head)
            if self._applied[-1] != block_hash:
                self._switch_to(block_hash)
            return self._roots[block_hash]
        updates = list(updates)
        # a matching anonymous preview (the miner's block-under-
        # construction) is this block's state already applied: adopt it
        # (an in-flight ANON dispatch is adopted with it — the entry is
        # renamed and settles under the block's name)
        if (self.ANON in self._roots
                and self._parent.get(self.ANON) == parent_hash
                and self._batch.get(self.ANON) == updates
                and self._applied and self._applied[-1] == self.ANON):
            root = self._roots[self.ANON]
            self._rename_anon(block_hash)
            return root
        self._drop_anon()
        if self._applied[-1] != parent_hash:
            self._switch_to(parent_hash)
        if expected_root is not None and self._pipelining():
            # refill the bounded window, then dispatch without waiting
            self._drain_pipeline(leave=self.pipeline_depth - 1)
        if expected_root is not None and self._pipelining():
            # (re-checked: a wedge mid-drain may have landed us on host)
            self.trie.checkpoint()
            self.trie.update(updates)
            root = self._commit_dispatch(block_hash, expected_root,
                                         updates)
            self._dirty_since_export = True
            self._record(block_hash, parent_hash, updates, root)
            return root
        self._drain_pipeline()
        self.trie.checkpoint()
        self.trie.update(updates)
        root = self._commit_root()
        self._dirty_since_export = True
        self._record(block_hash, parent_hash, updates, root)
        return root

    @_locked  # guarded-by: _lock
    def preview(self, parent_hash: bytes,
                updates: Sequence[Tuple[bytes, bytes]],
                expected_root: Optional[bytes] = None) -> bytes:
        """Compute the root [updates] would produce on top of
        [parent_hash] WITHOUT naming a block — the miner's path, where
        the block hash depends on this root. The state stays applied as
        the single anonymous head; the next verify with the same
        parent+batch adopts it for free, anything else rewinds it.

        [expected_root] pipelines exactly like verify(): the chain's
        validate phase previews with the header root in hand, the later
        verify adopts the in-flight dispatch — one device program per
        block, settled at the next drain point."""
        if parent_hash == self.ANON:
            parent_hash = self._promote_anon()
        if parent_hash not in self._roots:
            raise MirrorError(f"unknown parent {parent_hash.hex()[:8]}")
        updates = list(updates)
        if (self.ANON in self._roots
                and self._parent.get(self.ANON) == parent_hash
                and self._batch.get(self.ANON) == updates):
            if self._applied and self._applied[-1] != self.ANON:
                self._switch_to(self.ANON)
            return self._roots[self.ANON]
        self._drop_anon()
        if self._applied[-1] != parent_hash:
            self._switch_to(parent_hash)
        if expected_root is not None and self._pipelining():
            self._drain_pipeline(leave=self.pipeline_depth - 1)
        if expected_root is not None and self._pipelining():
            self.trie.checkpoint()
            self.trie.update(updates)
            root = self._commit_dispatch(self.ANON, expected_root,
                                         updates)
            self._dirty_since_export = True
            self._record(self.ANON, parent_hash, updates, root)
            return root
        self._drain_pipeline()
        self.trie.checkpoint()
        self.trie.update(updates)
        root = self._commit_root()
        self._dirty_since_export = True
        self._record(self.ANON, parent_hash, updates, root)
        return root

    # side-branch records (phantom previews, losing forks) kept replayable
    # before GC reclaims the oldest — generous: consensus only builds on
    # recent blocks (the reference's dirty forest is similarly bounded)
    MAX_SIDE_RECORDS = 512

    def _record(self, key: bytes, parent: bytes,  # guarded-by: _lock
                batch: List[Tuple[bytes, bytes]], root: bytes) -> None:
        self._parent[key] = parent
        self._batch[key] = batch
        self._roots[key] = root
        self._by_root.setdefault(root, []).append(key)
        self._applied.append(key)
        extra = len(self._roots) - len(self._applied)
        if extra > self.MAX_SIDE_RECORDS:
            applied = set(self._applied)
            for k in list(self._roots):
                if extra <= self.MAX_SIDE_RECORDS:
                    break
                if k in applied or k in self._accepted:
                    continue
                self._forget(k)
                extra -= 1
            # descendants of a collected record have dangling parents and
            # can never replay — collect them now (matching reject()'s
            # cleanup) instead of surfacing later as a "no path" error in
            # _switch_to
            self._prune_orphans()

    def _promote_anon(self) -> bytes:
        """Name the anonymous head by its ROOT so new work can build on
        top of it — chain generation commits block k+1's state before
        block k has a hash. When the real block arrives, verify() records
        it under its hash; the promoted record ages out via the
        side-record GC."""
        if self.ANON not in self._roots:
            raise MirrorError("no anonymous state to build on")
        root = self._roots[self.ANON]
        if root in self._roots:
            # an identically-rooted record already exists (e.g. an empty
            # batch on a promoted parent): collapse onto it
            self._drop_anon()
            return root
        self._rename_anon(root)
        return root

    def _rename_anon(self, block_hash: bytes) -> None:  # guarded-by: _lock
        # an in-flight ANON dispatch is adopted with the record: rename
        # its entry BEFORE _forget (which drops entries by key) so it
        # settles under the block's name at the next drain
        for e in self._inflight:
            if e["key"] == self.ANON:
                e["key"] = block_hash
        root = self._roots[self.ANON]
        parent = self._parent[self.ANON]
        batch = self._batch[self.ANON]
        # the anon may have been rewound off the stack by an intervening
        # read/switch — its record is still renameable
        idx = (self._applied.index(self.ANON)
               if self.ANON in self._applied else None)
        self._forget(self.ANON)
        if idx is not None:
            self._applied[idx] = block_hash
        self._parent[block_hash] = parent
        self._batch[block_hash] = batch
        self._roots[block_hash] = root
        self._by_root.setdefault(root, []).append(block_hash)

    def _drop_anon(self) -> None:  # guarded-by: _lock
        if self.ANON not in self._roots:
            return
        if self.ANON in self._applied:
            idx = self._applied.index(self.ANON)
            while len(self._applied) > idx:
                dropped = self._applied.pop()
                self.trie.rollback()
                self._dirty_since_export = True
                if dropped != self.ANON:
                    self._forget(dropped)
        self._forget(self.ANON)

    @_locked  # guarded-by: _lock
    def accept(self, block_hash: bytes) -> None:
        """Finalize a block. Scopes of finalized history deeper than the
        tip buffer flush (the common linear-chain steady state keeps a
        rolling TIP_BUFFER-deep readable window)."""
        if block_hash not in self._roots:
            raise MirrorError("accepting a block the mirror never saw")
        # settle the accepted block's dispatch (and everything before
        # it) BEFORE finality marks it: a root that never matched its
        # header must not finalize. Later in-flight siblings keep
        # overlapping.
        self._drain_pipeline(upto=block_hash)
        self._accepted.add(block_hash)
        self._maybe_flush()

    # finalized blocks whose undo scopes (and records) stay retained so
    # recent-state reads keep working — the reference's 32-root tip
    # buffer (core/state_manager.go:189+ / TIP_BUFFER_SIZE)
    TIP_BUFFER = 32

    def _maybe_flush(self) -> None:  # guarded-by: _lock
        # the finalized PREFIX of the stack (base + contiguous accepted
        # blocks; anything above can still be rejected and must stay
        # rewindable). Scopes deeper than the tip buffer flush; history
        # below the new base stops being rewindable, so a sibling
        # branching there can never apply again and its parent lookup
        # failing is the correct refusal
        m = 0
        while (m + 1 < len(self._applied)
               and self._applied[m + 1] in self._accepted):
            m += 1
        n_flush = m - self.TIP_BUFFER
        if n_flush <= 0:
            return
        self.trie.flush_oldest_checkpoints(n_flush)
        evicted, self._applied = (
            self._applied[:n_flush], self._applied[n_flush:])
        for h in evicted:
            self._forget(h)
            self._accepted.discard(h)
        # the new base is the tree's floor: drop its parent link so
        # orphan pruning never mistakes it for unreachable
        self._parent.pop(self._applied[0], None)
        # side records that branched below the new base (stale promoted
        # previews, losing siblings) lost their replay path
        self._prune_orphans()

    def _prune_orphans(self) -> None:
        """Forget every record whose parent record is gone (no replay
        path can reach it anymore), to a fixpoint."""
        changed = True
        while changed:
            changed = False
            for h, p in list(self._parent.items()):
                if p not in self._roots:
                    self._forget(h)
                    changed = True

    @_locked  # guarded-by: _lock
    def reject(self, block_hash: bytes) -> None:
        """Drop a block. If it is applied, rewind through it (consensus
        rejects its applied descendants with it)."""
        if block_hash in self._accepted:
            # with the tip buffer, accepted blocks stay on the stack for
            # TIP_BUFFER blocks — a duplicate/out-of-order Reject must
            # not rewind finalized state through them
            raise MirrorError(
                f"rejecting an ACCEPTED block ({block_hash.hex()[:8]})")
        # settle any in-flight window before rewinding through it (a
        # reject mid-pipeline is a reorg: the drain keeps divergence
        # attribution per-block before scopes are torn down)
        self._drain_pipeline()
        if block_hash in self._applied:
            idx = self._applied.index(block_hash)
            while len(self._applied) > idx:
                dropped = self._applied.pop()
                self.trie.rollback()
                self._dirty_since_export = True
                if dropped != block_hash:
                    # descendant of the rejected block: gone with it
                    self._forget(dropped)
        self._forget(block_hash)
        # unapplied descendants lost their replay path with the rejected
        # block (consensus rejects them too, but their Reject may never
        # reach us once the parent is gone)
        self._prune_orphans()
        # dropping the last unaccepted block can make the stack final
        self._maybe_flush()

    @property
    def head(self) -> bytes:
        with self._lock:
            return self._applied[-1]

    @_locked
    def root_of(self, block_hash: bytes) -> Optional[bytes]:
        return self._roots.get(block_hash)

    @_locked
    def has_root(self, root: bytes) -> bool:
        return root in self._by_root

    @_locked
    def key_for_root(self, root: bytes) -> Optional[bytes]:
        """A block key whose state has [root]. Prefers a key on the
        applied stack (always reachable); identical-root records off the
        stack (stale promoted previews) may sit beyond the rewind
        horizon."""
        keys = self._by_root.get(root)
        if not keys:
            return None
        applied = set(self._applied)
        for k in reversed(keys):
            if k in applied:
                return k
        return keys[-1]

    # ---- reads (chain adapter state reads at a resident root) ------------

    @_locked
    def read(self, root: bytes, key32: bytes) -> Optional[bytes]:
        """Value of [key32] in the state identified by [root]. Positions
        the trie at a block with that root (identical-root blocks have
        identical state, so any is correct). Raises MirrorError when the
        root is not resident or no longer reachable (accepted history —
        serve those from the exported disk image instead)."""
        keys = self._by_root.get(root)
        if not keys:
            raise MirrorError("root not resident")
        if self._roots.get(self._applied[-1]) == root:
            return self.trie.get(key32)
        # overlay shortcut: if [key32] is untouched by every batch on
        # both legs of the path between a target block and the head, the
        # head's value IS the target's value — serve it without
        # repositioning (an RPC StateDB at block N-1 interleaved with
        # processing at N would otherwise pay two branch switches, each
        # a device commit, per account read)
        for k in keys:
            if self._untouched_between(k, key32):
                return self.trie.get(key32)
        last_err: Optional[MirrorError] = None
        for k in list(keys):
            try:
                self._switch_to(k)
                return self.trie.get(key32)
            except MirrorError as e:
                last_err = e
        raise last_err if last_err is not None else MirrorError(
            "root unreachable")

    def _batch_keys_of(self, k: bytes):  # guarded-by: _lock
        s = self._batch_keys.get(k)
        if s is None:
            b = self._batch.get(k)
            if b is None:
                return None
            s = self._batch_keys[k] = frozenset(kk for kk, _ in b)
        return s

    def _untouched_between(self, target: bytes, key32: bytes) -> bool:
        """True iff no batch on target->ancestor or ancestor->head
        touches [key32], where ancestor is target's nearest applied
        ancestor — then the value at the head equals the value at
        target's state."""
        applied_idx = {k: i for i, k in enumerate(self._applied)}
        chain: List[bytes] = []
        cur = target
        while cur not in applied_idx:
            p = self._parent.get(cur)
            if p is None:
                return False
            chain.append(cur)
            cur = p
        for k in chain:
            s = self._batch_keys_of(k)
            if s is None or key32 in s:
                return False
        for k in self._applied[applied_idx[cur] + 1:]:
            s = self._batch_keys_of(k)
            if s is None or key32 in s:
                return False
        return True

    # ---- interval persistence (disk flush of changed nodes) --------------

    @_locked  # guarded-by: _lock
    def export_to(self, diskdb, at_block: Optional[bytes] = None,
                  pre_write=None) -> int:
        """Durably write every account-trie node changed since the
        previous export into [diskdb] — the commit-interval disk flush
        (reference trie/triedb/hashdb Commit via
        core/state_manager.go:153). Positions the trie at [at_block]
        (typically the just-accepted block) first so the on-disk image is
        complete for that block's root; [pre_write] (e.g. the storage-
        forest cap) runs after the batch is staged but before it commits,
        preserving children-first crash ordering. Returns nodes written.

        Durability: the native export clears its changed-node marks as it
        walks, so a FAILED disk write would silently drop those nodes
        from every later delta. On any write failure the next export
        degrades to a FULL image (which supersedes all lost deltas)
        before the marks are trusted again.

        Content-addressed writes make sibling/abandoned-branch nodes
        harmless on disk: they are unreachable garbage the offline
        pruner sweeps, exactly like the reference's stale hashdb nodes."""
        if not self._dirty_since_export and not self._export_degraded and (
            at_block is None or self._applied[-1] == at_block
        ):
            # nothing re-hashed since the last export at this position:
            # skip the store readback + full-trie walk (an RPC client
            # polling eth_getProof per block would otherwise make every
            # call O(total nodes))
            return 0
        # the on-disk image must only ever contain SETTLED state: drain
        # the pipeline window before reading the store back
        self._drain_pipeline()
        if at_block is not None and self._applied[-1] != at_block:
            self._switch_to(at_block)
        if self.trie.num_nodes == 0:
            return 0
        # a rewind-only switch leaves the reverted paths dirty (rollback
        # replays through the updater, native/mpt.py rollback): re-commit
        # so digests are settled before the export reads them. A clean
        # trie plans nothing, so this is free in the common case. On the
        # device path the store readback runs under the watchdog too — a
        # wedge MID-EXPORT takes over exactly like a wedge mid-commit
        # (the worker only syncs device state; absorb mutates the trie
        # on THIS thread, so an abandoned worker can't race it).
        if self.host_mode:
            self.trie.commit_cpu(threads=self._cpu_threads)
        else:
            from ..native.mpt import DeviceWedgedError

            try:
                if self.template:
                    # template commits absorb as they go — no store
                    # readback, the host cache is already current
                    self.trie.commit_template(self.ex,
                                              self.device_timeout)
                else:
                    self.trie.commit_resident_timed(
                        self.ex, self.device_timeout)
                    self._absorb_device_store("store readback")
            except DeviceWedgedError as e:
                self._degrade(str(e))
                if self.host_mode:
                    self.trie.commit_cpu(threads=self._cpu_threads)
                # else: the mesh demotion's host-oracle rehash left the
                # digest cache current for this settled state — the
                # export below reads it directly
        try:
            digs, blob, off = self.trie.export_nodes(
                delta=not self._export_degraded)
        except RuntimeError as e:  # dirty-trie guard: surface as ours
            raise MirrorError(f"export on unsettled trie: {e}")
        try:
            batch = diskdb.new_batch()
            for i in range(digs.shape[0]):
                batch.put(digs[i].tobytes(), blob[int(off[i]):int(off[i + 1])])
            if pre_write is not None:
                pre_write()
            batch.write()
        except BaseException:
            self._export_degraded = True
            self._dirty_since_export = True
            raise
        self._export_degraded = False
        self._dirty_since_export = False
        return int(digs.shape[0])

    # ---- branch switching ------------------------------------------------

    def _forget(self, block_hash: bytes) -> None:
        # a forgotten block's in-flight dispatch has nothing left to
        # settle against (rollback already re-dirtied its paths; the
        # device program is harmless — the delta-patch scheme tolerates
        # rolled-back dispatched commits)   # guarded-by: _lock
        if self._inflight:
            self._inflight = [e for e in self._inflight
                              if e["key"] != block_hash]
            self._pipeline_gauge()
        root = self._roots.pop(block_hash, None)
        if root is not None:
            keys = self._by_root.get(root)
            if keys is not None:
                try:
                    keys.remove(block_hash)
                except ValueError:
                    pass
                if not keys:
                    del self._by_root[root]
        self._parent.pop(block_hash, None)
        self._batch.pop(block_hash, None)
        self._batch_keys.pop(block_hash, None)
        self._accepted.discard(block_hash)

    def _switch_to(self, target: bytes) -> None:  # guarded-by: _lock
        """Rewind to the nearest applied ancestor of [target], then
        replay the saved batches down to it."""
        # a branch switch is the pipeline's hard barrier: settle every
        # in-flight commit before tearing scopes down (the replay-root
        # compare below would otherwise race unverified dispatches)
        # guarded-by: _lock (every caller holds it)
        self._drain_pipeline()
        # ancestry chain of target up to something applied
        chain: List[bytes] = []
        cur = target
        applied_set = set(self._applied)
        while cur not in applied_set:
            chain.append(cur)
            nxt = self._parent.get(cur)
            if nxt is None:
                raise MirrorError(
                    f"no path from {target.hex()[:8]} to the mirror")
            cur = nxt
        # rewind to the common ancestor `cur`. Accepted blocks within the
        # tip buffer rewind like any other (recent-state reads position
        # here); their records are retained, so the canonical path
        # replays back on the next forward switch. True finality is the
        # flushed base: anything below it has no record and the ancestry
        # walk above already refused it.
        while self._applied[-1] != cur:
            self._applied.pop()
            self.trie.rollback()
            self._dirty_since_export = True
        # replay down the target branch (deepest ancestor first)
        for h in reversed(chain):
            self.trie.checkpoint()
            self.trie.update(self._batch[h])
            self._dirty_since_export = True
            root = self._commit_root()
            if root != self._roots[h]:
                self.trie.rollback()  # close the scope we just opened
                raise MirrorError(
                    f"replay of {h.hex()[:8]} produced a different root")
            self._applied.append(h)
