"""Branch-aware resident account mirror: drives one device-resident
IncrementalTrie through a chain's verify/accept/reject lifecycle,
including sibling competition and reorgs.

The resident executor (ops/keccak_resident.py) holds a single linear
trie history, but consensus verifies SIBLING blocks against different
parents (core/blockchain.go:1424 reorg; plugin/evm/block.go Verify/
Accept/Reject). This adapter reconciles the two:

  - the mirror keeps a LINEAR applied stack (one undo scope per applied
    block, native/mpt_inc.cpp checkpoint/rollback);
  - verifying a block whose parent is not the current head REWINDS
    (rollback scopes) to the nearest applied ancestor of the parent and
    REPLAYS the saved per-block update batches down the target branch;
  - accept finalizes: when every applied block is accepted, all undo
    scopes flush (journal memory reclaimed);
  - reject drops a block (and any applied descendants, which consensus
    rejects with it) by rewinding through it.

Each verify returns the block's state root from the device (lazy handle
resolved to bytes), so the chain adapter can compare it against the
header exactly where statedb.IntermediateRoot's result is used today
(core/blockchain.go:1331 ValidateState).

This is the round-5 chain-integration building block: what remains
upstream is feeding it StateDB's per-block account updates and routing
intermediate state reads through the mirror.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

from ..native.mpt import IncrementalTrie


class MirrorError(Exception):
    pass


class ResidentAccountMirror:
    GENESIS = b"\x00" * 32  # sentinel parent of the initial state

    def __init__(self, items: Sequence[Tuple[bytes, bytes]] = (),
                 executor=None):
        if executor is None:
            from ..ops.keccak_resident import ResidentExecutor

            executor = ResidentExecutor()
        self.ex = executor
        self.trie = IncrementalTrie(items)
        # the genesis commit (everything is dirty after construction)
        self._roots: Dict[bytes, bytes] = {
            self.GENESIS: self.ex.root_bytes(
                self.trie.commit_resident(self.ex))
        }
        self._parent: Dict[bytes, bytes] = {}
        self._batch: Dict[bytes, List[Tuple[bytes, bytes]]] = {}
        self._applied: List[bytes] = [self.GENESIS]
        self._accepted: set = {self.GENESIS}

    # ---- lifecycle -------------------------------------------------------

    def verify(self, parent_hash: bytes, block_hash: bytes,
               updates: Sequence[Tuple[bytes, bytes]]) -> bytes:
        """Apply [updates] on top of [parent_hash]'s state and return the
        resulting state root. Saves the batch so later branch switches
        can replay it."""
        if parent_hash not in self._roots:
            raise MirrorError(f"unknown parent {parent_hash.hex()[:8]}")
        if block_hash in self._roots:
            # re-verify of a known block: the root is cached, but the
            # mirror must still LAND on that block's state (callers read
            # intermediate state through the head)
            if self._applied[-1] != block_hash:
                self._switch_to(block_hash)
            return self._roots[block_hash]
        if self._applied[-1] != parent_hash:
            self._switch_to(parent_hash)
        self.trie.checkpoint()
        self.trie.update(list(updates))
        root = self.ex.root_bytes(self.trie.commit_resident(self.ex))
        self._parent[block_hash] = parent_hash
        self._batch[block_hash] = list(updates)
        self._roots[block_hash] = root
        self._applied.append(block_hash)
        return root

    def accept(self, block_hash: bytes) -> None:
        """Finalize a block. When the whole applied stack is final, the
        undo journal flushes (the common linear-chain steady state)."""
        if block_hash not in self._roots:
            raise MirrorError("accepting a block the mirror never saw")
        self._accepted.add(block_hash)
        self._maybe_flush()

    def _maybe_flush(self) -> None:
        if all(h in self._accepted for h in self._applied):
            # every open scope is final: merge+clear the journal, and
            # prune finalized records — a sibling branching below the
            # finalized head can never apply again, so its parent lookup
            # failing with "unknown parent" is the correct refusal
            for _ in range(len(self._applied) - 1):
                self.trie.discard_checkpoint()
            head = self._applied[-1]
            for h in self._applied[:-1]:
                self._forget(h)
            # the head is now the tree's root: drop its parent link so
            # orphan pruning never mistakes it for unreachable
            self._parent.pop(head, None)
            self._applied = [head]
            self._accepted = {head}

    def reject(self, block_hash: bytes) -> None:
        """Drop a block. If it is applied, rewind through it (consensus
        rejects its applied descendants with it)."""
        if block_hash in self._applied:
            idx = self._applied.index(block_hash)
            while len(self._applied) > idx:
                dropped = self._applied.pop()
                self.trie.rollback()
                if dropped != block_hash:
                    # descendant of the rejected block: gone with it
                    self._forget(dropped)
        self._forget(block_hash)
        # unapplied descendants lost their replay path with the rejected
        # block: prune orphans to a fixpoint (consensus rejects them too,
        # but their Reject may never reach us once the parent is gone)
        changed = True
        while changed:
            changed = False
            for h, p in list(self._parent.items()):
                if p not in self._roots:
                    self._forget(h)
                    changed = True
        # dropping the last unaccepted block can make the stack final
        self._maybe_flush()

    @property
    def head(self) -> bytes:
        return self._applied[-1]

    def root_of(self, block_hash: bytes) -> Optional[bytes]:
        return self._roots.get(block_hash)

    # ---- branch switching ------------------------------------------------

    def _forget(self, block_hash: bytes) -> None:
        self._roots.pop(block_hash, None)
        self._parent.pop(block_hash, None)
        self._batch.pop(block_hash, None)
        self._accepted.discard(block_hash)

    def _switch_to(self, target: bytes) -> None:
        """Rewind to the nearest applied ancestor of [target], then
        replay the saved batches down to it."""
        # ancestry chain of target up to something applied
        chain: List[bytes] = []
        cur = target
        applied_set = set(self._applied)
        while cur not in applied_set:
            chain.append(cur)
            nxt = self._parent.get(cur)
            if nxt is None:
                raise MirrorError(
                    f"no path from {target.hex()[:8]} to the mirror")
            cur = nxt
        # rewind to the common ancestor `cur` — check BEFORE popping so
        # an error leaves the scope stack and _applied consistent
        while self._applied[-1] != cur:
            top = self._applied[-1]
            if top in self._accepted:
                raise MirrorError(
                    "branch switch would rewind an ACCEPTED block "
                    f"({top.hex()[:8]}) — finality violation")
            self._applied.pop()
            self.trie.rollback()
        # replay down the target branch (deepest ancestor first)
        for h in reversed(chain):
            self.trie.checkpoint()
            self.trie.update(self._batch[h])
            root = self.ex.root_bytes(self.trie.commit_resident(self.ex))
            if root != self._roots[h]:
                self.trie.rollback()  # close the scope we just opened
                raise MirrorError(
                    f"replay of {h.hex()[:8]} produced a different root")
            self._applied.append(h)
