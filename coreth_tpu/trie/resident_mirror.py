"""Branch-aware resident account mirror: drives one device-resident
IncrementalTrie through a chain's verify/accept/reject lifecycle,
including sibling competition and reorgs.

The resident executor (ops/keccak_resident.py) holds a single linear
trie history, but consensus verifies SIBLING blocks against different
parents (core/blockchain.go:1424 reorg; plugin/evm/block.go Verify/
Accept/Reject). This adapter reconciles the two:

  - the mirror keeps a LINEAR applied stack (one undo scope per applied
    block, native/mpt_inc.cpp checkpoint/rollback);
  - verifying a block whose parent is not the current head REWINDS
    (rollback scopes) to the nearest applied ancestor of the parent and
    REPLAYS the saved per-block update batches down the target branch;
  - accept finalizes: scopes (and records) of accepted blocks deeper
    than the TIP_BUFFER flush (journal memory reclaimed); the retained
    window keeps recent accepted states rewindable for reads — the
    reference's 32-root tip buffer (core/state_manager.go:189+);
  - reject drops a block (and any applied descendants, which consensus
    rejects with it) by rewinding through it.

Each verify returns the block's state root from the device (lazy handle
resolved to bytes), so the chain adapter can compare it against the
header exactly where statedb.IntermediateRoot's result is used today
(core/blockchain.go:1331 ValidateState).

Upstream integration: state/resident_trie.py (the StateDB facade that
feeds per-block account batches and reads through here),
core/state_manager.py ResidentTrieWriter (consensus lifecycle + the
interval disk export), core/blockchain.py CacheConfig.resident_account_
trie (boot + wiring).
"""

from __future__ import annotations

import functools
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from ..fault import FailpointError, failpoint
from ..fault import register as _register_failpoint
from ..native.mpt import IncrementalTrie

FP_SPOT_CHECK = _register_failpoint(
    "state/resident/spot_check",
    "`raise` forces the periodic mirror spot-check to report divergence "
    "(exercises the quarantine/reboot path without corrupting a trie)")


class MirrorError(Exception):
    pass


def _locked(fn):
    """Serialize public mirror ops: the chain calls verify/preview from
    the insert path (under chainmu) but accept/export ride the async
    acceptor thread (core/blockchain.py _accept_post_process)."""

    @functools.wraps(fn)
    def wrapper(self, *a, **kw):
        with self._lock:
            return fn(self, *a, **kw)

    return wrapper


class ResidentAccountMirror:
    GENESIS = b"\x00" * 32  # sentinel parent of the initial state
    # single in-flight anonymous state (a miner's block-under-construction:
    # root computed before the block hash exists; the next verify with the
    # same parent+batch adopts it, anything else rewinds it)
    ANON = b"\x01" + b"anon" * 7 + b"\x01\x01\x01"

    def __init__(self, items: Sequence[Tuple[bytes, bytes]] = (),
                 executor=None, base_key: Optional[bytes] = None,
                 device_timeout: Optional[float] = None,
                 cpu_threads: Optional[int] = None,
                 prefer_host: Optional[bool] = None):
        import os

        if cpu_threads is None or int(cpu_threads) <= 0:
            from ..native import default_cpu_threads

            cpu_threads = default_cpu_threads()
        self._cpu_threads = int(cpu_threads)
        # CPU fast path (VERDICT r5 #4, the config-10 regression): when
        # no TPU backend resolves, the "device" a ResidentExecutor would
        # dispatch to is XLA-CPU, whose keccak is ~150x slower than the
        # native hasher — the resident chain path ran 5.6x behind the
        # default path because of it. Unless the caller pinned the
        # device path (an explicit executor, prefer_host=False, or the
        # env override), start in host mode from construction: the
        # mirror lifecycle (verify/accept/reject/reorg, exports, reads)
        # and the roots are identical, but every commit runs the
        # threaded native incremental hasher. This is also what makes a
        # later device-wedge takeover a soft landing — takeover lands on
        # exactly this path.
        env = os.environ.get("CORETH_TPU_RESIDENT_HOST", "").lower()
        if env in ("1", "true", "yes"):
            prefer_host = True
        elif env in ("0", "false", "no"):
            prefer_host = False
        if prefer_host is None:
            if executor is not None:
                prefer_host = False
            else:
                from ..ops.keccak_planned import _tpu_backend

                prefer_host = not _tpu_backend()
        self.host_mode = bool(prefer_host)
        if self.host_mode:
            from ..metrics import default_registry

            default_registry.counter("state/resident/cpu_fastpath").inc(1)
        elif executor is None:
            from ..ops.keccak_resident import ResidentExecutor

            executor = ResidentExecutor()
        self.ex = executor  # None in host mode unless the caller passed one
        # chain hook fired (under the mirror lock) when a device wedge
        # forces the one-way host takeover; receives the reason string.
        # Must not call back into mirror methods or take chainmu.
        self.on_takeover = None
        self._lock = threading.RLock()
        self.trie = IncrementalTrie(items)
        # device-failure takeover (VERDICT r4 #4): a commit the device
        # does not answer within [device_timeout] seconds triggers a
        # one-way host takeover — full host rehash, then every later
        # commit/export runs commit_cpu. None = watchdog off (tests /
        # trusted local backends); env override for ops.
        if device_timeout is None:
            raw = os.environ.get("CORETH_TPU_RESIDENT_TIMEOUT", "")
            try:
                device_timeout = float(raw) if raw else None
            except ValueError:
                from ..log import get_logger

                get_logger("state").warning(
                    "ignoring malformed CORETH_TPU_RESIDENT_TIMEOUT=%r",
                    raw)
                device_timeout = None
        if device_timeout is not None and device_timeout <= 0:
            device_timeout = None  # 0 disables the watchdog (config doc)
        self.device_timeout = device_timeout
        base = base_key if base_key is not None else self.GENESIS
        # flags BEFORE the genesis commit: a takeover during it must not
        # have its degradation markers clobbered below
        self._dirty_since_export = True  # genesis image not yet on disk
        self._export_degraded = False    # failed write -> next export full
        # the genesis commit (everything is dirty after construction)
        self._roots: Dict[bytes, bytes] = {base: self._commit_root()}
        self._by_root: Dict[bytes, List[bytes]] = {
            self._roots[base]: [base]
        }
        self._parent: Dict[bytes, bytes] = {}
        self._batch: Dict[bytes, List[Tuple[bytes, bytes]]] = {}
        self._batch_keys: Dict[bytes, frozenset] = {}  # lazy overlay index
        self._applied: List[bytes] = [base]
        self._accepted: set = {base}

    # ---- device-failure takeover (VERDICT r4 #4) -------------------------

    def _commit_root(self) -> bytes:
        """Settle the trie's current state and return the 32-byte root —
        on the device while healthy, on the host after takeover. The
        device path runs under the watchdog; a wedge triggers the
        takeover and the SAME commit completes on the CPU, so callers
        never see the failure (the chain does not stall)."""
        from ..metrics import phase_timer
        from ..metrics.spans import span
        from ..native.mpt import DeviceWedgedError

        with span("resident/commit", host_mode=self.host_mode):
            with phase_timer("resident/phase/commit"):
                if self.host_mode:
                    return self.trie.commit_cpu(threads=self._cpu_threads)
                try:
                    return self.trie.commit_resident_timed(
                        self.ex, self.device_timeout)
                except DeviceWedgedError as e:
                    self._take_over_host(str(e))
                    return self.trie.commit_cpu(threads=self._cpu_threads)

    def _take_over_host(self, why: str) -> None:
        """One-way device -> host switch: rebuild the full host digest
        cache (the device store is unreachable) and degrade the next
        export to a full image. The mirror keeps ALL state — records,
        journal, branch logic — so verify/accept/reject/reorg continue
        with identical roots; only the hashing runs on the CPU. The
        reference analog is the lifecycle assumption around
        core/blockchain.go:1361-1365 that the state backend never
        vanishes — here it can, and the chain must not stall."""
        from ..log import get_logger
        from ..metrics import default_registry

        default_registry.counter("state/resident/device_takeovers").inc(1)
        get_logger("state").error(
            "resident device backend wedged (%s) — taking over on the "
            "host: full rehash of %d nodes, then CPU-resident commits",
            why, self.trie.num_nodes)
        self.host_mode = True
        self.trie.rehash_host(threads=self._cpu_threads)
        # the export delta marks predate the takeover; write a full
        # image at the next interval so disk supersedes any device-era
        # uncertainty
        self._export_degraded = True
        self._dirty_since_export = True
        if self.on_takeover is not None:
            try:
                self.on_takeover(why)
            except Exception:
                from ..metrics import count_drop

                count_drop("state/resident/takeover_hook_error")

    @_locked
    def spot_check(self) -> bool:
        """Periodic device-vs-host cross-check (chain knob
        resident_spot_check_interval): verify the device-resident image
        against the host keccak oracle WITHOUT ending residency. Returns
        False on divergence — the chain quarantines via reboot_mirror()
        instead of letting a silently-corrupt mirror feed consensus.

        rehash_host would be the obvious oracle but it one-way pins the
        trie to host mode, so a PASSING check would still end residency.
        Instead: settle + read back the device store (watchdogged, like
        export_to), then export the full node image and check
        keccak256(node_rlp) == claimed digest for every node on the host,
        plus the cached applied root appearing in the digest set. Node
        RLP embeds children digests from the same store, so this
        transitively verifies the whole device digest chain down from
        the root. The full export consumes the delta marks, so the next
        interval flush is degraded to a full image."""
        import numpy as np

        from ..metrics import default_registry
        from ..native import keccak256_batch
        from ..native.mpt import DeviceWedgedError, _run_with_watchdog

        default_registry.counter("state/resident/spot_checks").inc(1)
        try:
            failpoint("state/resident/spot_check")
        except FailpointError:
            return False  # chaos-forced divergence
        if self.host_mode or self.trie.num_nodes == 0:
            return True  # the host oracle already computed these roots
        try:
            dev_root = self.trie.commit_resident_timed(
                self.ex, self.device_timeout)
            if self.device_timeout is None:
                store_np = np.asarray(self.ex.store)
            else:
                store_np = _run_with_watchdog(
                    lambda: np.asarray(self.ex.store),
                    self.device_timeout, "spot-check store readback")
            self.trie.absorb_store(store_np)
        except DeviceWedgedError as e:
            # not a divergence: the ladder's failure mode. Take over like
            # any wedged commit; the host root is authoritative now.
            self._take_over_host(str(e))
            self.trie.commit_cpu(threads=self._cpu_threads)
            return True
        digs, blob, off = self.trie.export_nodes(delta=False)
        self._export_degraded = True
        self._dirty_since_export = True
        n = int(digs.shape[0])
        msgs = [bytes(blob[int(off[i]):int(off[i + 1])]) for i in range(n)]
        host = keccak256_batch(msgs, threads=self._cpu_threads)
        claimed = {digs[i].tobytes() for i in range(n)}
        ok = all(digs[i].tobytes() == host[i] for i in range(n))
        cached = self._roots.get(self._applied[-1])
        ok = ok and dev_root in claimed and (
            cached is None or cached == dev_root)
        if not ok:
            default_registry.counter(
                "state/resident/spot_check_failures").inc(1)
        return ok

    # ---- lifecycle -------------------------------------------------------

    @_locked
    def verify(self, parent_hash: bytes, block_hash: bytes,
               updates: Sequence[Tuple[bytes, bytes]]) -> bytes:
        """Apply [updates] on top of [parent_hash]'s state and return the
        resulting state root. Saves the batch so later branch switches
        can replay it."""
        if parent_hash == self.ANON:
            parent_hash = self._promote_anon()
        if parent_hash not in self._roots:
            raise MirrorError(f"unknown parent {parent_hash.hex()[:8]}")
        if block_hash in self._roots:
            # re-verify of a known block: the root is cached, but the
            # mirror must still LAND on that block's state (callers read
            # intermediate state through the head)
            if self._applied[-1] != block_hash:
                self._switch_to(block_hash)
            return self._roots[block_hash]
        updates = list(updates)
        # a matching anonymous preview (the miner's block-under-
        # construction) is this block's state already applied: adopt it
        if (self.ANON in self._roots
                and self._parent.get(self.ANON) == parent_hash
                and self._batch.get(self.ANON) == updates
                and self._applied and self._applied[-1] == self.ANON):
            root = self._roots[self.ANON]
            self._rename_anon(block_hash)
            return root
        self._drop_anon()
        if self._applied[-1] != parent_hash:
            self._switch_to(parent_hash)
        self.trie.checkpoint()
        self.trie.update(updates)
        root = self._commit_root()
        self._dirty_since_export = True
        self._record(block_hash, parent_hash, updates, root)
        return root

    @_locked
    def preview(self, parent_hash: bytes,
                updates: Sequence[Tuple[bytes, bytes]]) -> bytes:
        """Compute the root [updates] would produce on top of
        [parent_hash] WITHOUT naming a block — the miner's path, where
        the block hash depends on this root. The state stays applied as
        the single anonymous head; the next verify with the same
        parent+batch adopts it for free, anything else rewinds it."""
        if parent_hash == self.ANON:
            parent_hash = self._promote_anon()
        if parent_hash not in self._roots:
            raise MirrorError(f"unknown parent {parent_hash.hex()[:8]}")
        updates = list(updates)
        if (self.ANON in self._roots
                and self._parent.get(self.ANON) == parent_hash
                and self._batch.get(self.ANON) == updates):
            if self._applied and self._applied[-1] != self.ANON:
                self._switch_to(self.ANON)
            return self._roots[self.ANON]
        self._drop_anon()
        if self._applied[-1] != parent_hash:
            self._switch_to(parent_hash)
        self.trie.checkpoint()
        self.trie.update(updates)
        root = self._commit_root()
        self._dirty_since_export = True
        self._record(self.ANON, parent_hash, updates, root)
        return root

    # side-branch records (phantom previews, losing forks) kept replayable
    # before GC reclaims the oldest — generous: consensus only builds on
    # recent blocks (the reference's dirty forest is similarly bounded)
    MAX_SIDE_RECORDS = 512

    def _record(self, key: bytes, parent: bytes,
                batch: List[Tuple[bytes, bytes]], root: bytes) -> None:
        self._parent[key] = parent
        self._batch[key] = batch
        self._roots[key] = root
        self._by_root.setdefault(root, []).append(key)
        self._applied.append(key)
        extra = len(self._roots) - len(self._applied)
        if extra > self.MAX_SIDE_RECORDS:
            applied = set(self._applied)
            for k in list(self._roots):
                if extra <= self.MAX_SIDE_RECORDS:
                    break
                if k in applied or k in self._accepted:
                    continue
                self._forget(k)
                extra -= 1
            # descendants of a collected record have dangling parents and
            # can never replay — collect them now (matching reject()'s
            # cleanup) instead of surfacing later as a "no path" error in
            # _switch_to
            self._prune_orphans()

    def _promote_anon(self) -> bytes:
        """Name the anonymous head by its ROOT so new work can build on
        top of it — chain generation commits block k+1's state before
        block k has a hash. When the real block arrives, verify() records
        it under its hash; the promoted record ages out via the
        side-record GC."""
        if self.ANON not in self._roots:
            raise MirrorError("no anonymous state to build on")
        root = self._roots[self.ANON]
        if root in self._roots:
            # an identically-rooted record already exists (e.g. an empty
            # batch on a promoted parent): collapse onto it
            self._drop_anon()
            return root
        self._rename_anon(root)
        return root

    def _rename_anon(self, block_hash: bytes) -> None:
        root = self._roots[self.ANON]
        parent = self._parent[self.ANON]
        batch = self._batch[self.ANON]
        # the anon may have been rewound off the stack by an intervening
        # read/switch — its record is still renameable
        idx = (self._applied.index(self.ANON)
               if self.ANON in self._applied else None)
        self._forget(self.ANON)
        if idx is not None:
            self._applied[idx] = block_hash
        self._parent[block_hash] = parent
        self._batch[block_hash] = batch
        self._roots[block_hash] = root
        self._by_root.setdefault(root, []).append(block_hash)

    def _drop_anon(self) -> None:
        if self.ANON not in self._roots:
            return
        if self.ANON in self._applied:
            idx = self._applied.index(self.ANON)
            while len(self._applied) > idx:
                dropped = self._applied.pop()
                self.trie.rollback()
                self._dirty_since_export = True
                if dropped != self.ANON:
                    self._forget(dropped)
        self._forget(self.ANON)

    @_locked
    def accept(self, block_hash: bytes) -> None:
        """Finalize a block. Scopes of finalized history deeper than the
        tip buffer flush (the common linear-chain steady state keeps a
        rolling TIP_BUFFER-deep readable window)."""
        if block_hash not in self._roots:
            raise MirrorError("accepting a block the mirror never saw")
        self._accepted.add(block_hash)
        self._maybe_flush()

    # finalized blocks whose undo scopes (and records) stay retained so
    # recent-state reads keep working — the reference's 32-root tip
    # buffer (core/state_manager.go:189+ / TIP_BUFFER_SIZE)
    TIP_BUFFER = 32

    def _maybe_flush(self) -> None:
        # the finalized PREFIX of the stack (base + contiguous accepted
        # blocks; anything above can still be rejected and must stay
        # rewindable). Scopes deeper than the tip buffer flush; history
        # below the new base stops being rewindable, so a sibling
        # branching there can never apply again and its parent lookup
        # failing is the correct refusal
        m = 0
        while (m + 1 < len(self._applied)
               and self._applied[m + 1] in self._accepted):
            m += 1
        n_flush = m - self.TIP_BUFFER
        if n_flush <= 0:
            return
        self.trie.flush_oldest_checkpoints(n_flush)
        evicted, self._applied = (
            self._applied[:n_flush], self._applied[n_flush:])
        for h in evicted:
            self._forget(h)
            self._accepted.discard(h)
        # the new base is the tree's floor: drop its parent link so
        # orphan pruning never mistakes it for unreachable
        self._parent.pop(self._applied[0], None)
        # side records that branched below the new base (stale promoted
        # previews, losing siblings) lost their replay path
        self._prune_orphans()

    def _prune_orphans(self) -> None:
        """Forget every record whose parent record is gone (no replay
        path can reach it anymore), to a fixpoint."""
        changed = True
        while changed:
            changed = False
            for h, p in list(self._parent.items()):
                if p not in self._roots:
                    self._forget(h)
                    changed = True

    @_locked
    def reject(self, block_hash: bytes) -> None:
        """Drop a block. If it is applied, rewind through it (consensus
        rejects its applied descendants with it)."""
        if block_hash in self._accepted:
            # with the tip buffer, accepted blocks stay on the stack for
            # TIP_BUFFER blocks — a duplicate/out-of-order Reject must
            # not rewind finalized state through them
            raise MirrorError(
                f"rejecting an ACCEPTED block ({block_hash.hex()[:8]})")
        if block_hash in self._applied:
            idx = self._applied.index(block_hash)
            while len(self._applied) > idx:
                dropped = self._applied.pop()
                self.trie.rollback()
                self._dirty_since_export = True
                if dropped != block_hash:
                    # descendant of the rejected block: gone with it
                    self._forget(dropped)
        self._forget(block_hash)
        # unapplied descendants lost their replay path with the rejected
        # block (consensus rejects them too, but their Reject may never
        # reach us once the parent is gone)
        self._prune_orphans()
        # dropping the last unaccepted block can make the stack final
        self._maybe_flush()

    @property
    def head(self) -> bytes:
        with self._lock:
            return self._applied[-1]

    @_locked
    def root_of(self, block_hash: bytes) -> Optional[bytes]:
        return self._roots.get(block_hash)

    @_locked
    def has_root(self, root: bytes) -> bool:
        return root in self._by_root

    @_locked
    def key_for_root(self, root: bytes) -> Optional[bytes]:
        """A block key whose state has [root]. Prefers a key on the
        applied stack (always reachable); identical-root records off the
        stack (stale promoted previews) may sit beyond the rewind
        horizon."""
        keys = self._by_root.get(root)
        if not keys:
            return None
        applied = set(self._applied)
        for k in reversed(keys):
            if k in applied:
                return k
        return keys[-1]

    # ---- reads (chain adapter state reads at a resident root) ------------

    @_locked
    def read(self, root: bytes, key32: bytes) -> Optional[bytes]:
        """Value of [key32] in the state identified by [root]. Positions
        the trie at a block with that root (identical-root blocks have
        identical state, so any is correct). Raises MirrorError when the
        root is not resident or no longer reachable (accepted history —
        serve those from the exported disk image instead)."""
        keys = self._by_root.get(root)
        if not keys:
            raise MirrorError("root not resident")
        if self._roots.get(self._applied[-1]) == root:
            return self.trie.get(key32)
        # overlay shortcut: if [key32] is untouched by every batch on
        # both legs of the path between a target block and the head, the
        # head's value IS the target's value — serve it without
        # repositioning (an RPC StateDB at block N-1 interleaved with
        # processing at N would otherwise pay two branch switches, each
        # a device commit, per account read)
        for k in keys:
            if self._untouched_between(k, key32):
                return self.trie.get(key32)
        last_err: Optional[MirrorError] = None
        for k in list(keys):
            try:
                self._switch_to(k)
                return self.trie.get(key32)
            except MirrorError as e:
                last_err = e
        raise last_err if last_err is not None else MirrorError(
            "root unreachable")

    def _batch_keys_of(self, k: bytes):
        s = self._batch_keys.get(k)
        if s is None:
            b = self._batch.get(k)
            if b is None:
                return None
            s = self._batch_keys[k] = frozenset(kk for kk, _ in b)
        return s

    def _untouched_between(self, target: bytes, key32: bytes) -> bool:
        """True iff no batch on target->ancestor or ancestor->head
        touches [key32], where ancestor is target's nearest applied
        ancestor — then the value at the head equals the value at
        target's state."""
        applied_idx = {k: i for i, k in enumerate(self._applied)}
        chain: List[bytes] = []
        cur = target
        while cur not in applied_idx:
            p = self._parent.get(cur)
            if p is None:
                return False
            chain.append(cur)
            cur = p
        for k in chain:
            s = self._batch_keys_of(k)
            if s is None or key32 in s:
                return False
        for k in self._applied[applied_idx[cur] + 1:]:
            s = self._batch_keys_of(k)
            if s is None or key32 in s:
                return False
        return True

    # ---- interval persistence (disk flush of changed nodes) --------------

    @_locked
    def export_to(self, diskdb, at_block: Optional[bytes] = None,
                  pre_write=None) -> int:
        """Durably write every account-trie node changed since the
        previous export into [diskdb] — the commit-interval disk flush
        (reference trie/triedb/hashdb Commit via
        core/state_manager.go:153). Positions the trie at [at_block]
        (typically the just-accepted block) first so the on-disk image is
        complete for that block's root; [pre_write] (e.g. the storage-
        forest cap) runs after the batch is staged but before it commits,
        preserving children-first crash ordering. Returns nodes written.

        Durability: the native export clears its changed-node marks as it
        walks, so a FAILED disk write would silently drop those nodes
        from every later delta. On any write failure the next export
        degrades to a FULL image (which supersedes all lost deltas)
        before the marks are trusted again.

        Content-addressed writes make sibling/abandoned-branch nodes
        harmless on disk: they are unreachable garbage the offline
        pruner sweeps, exactly like the reference's stale hashdb nodes."""
        import numpy as np

        if not self._dirty_since_export and not self._export_degraded and (
            at_block is None or self._applied[-1] == at_block
        ):
            # nothing re-hashed since the last export at this position:
            # skip the store readback + full-trie walk (an RPC client
            # polling eth_getProof per block would otherwise make every
            # call O(total nodes))
            return 0
        if at_block is not None and self._applied[-1] != at_block:
            self._switch_to(at_block)
        if self.trie.num_nodes == 0:
            return 0
        # a rewind-only switch leaves the reverted paths dirty (rollback
        # replays through the updater, native/mpt.py rollback): re-commit
        # so digests are settled before the export reads them. A clean
        # trie plans nothing, so this is free in the common case. On the
        # device path the store readback runs under the watchdog too — a
        # wedge MID-EXPORT takes over exactly like a wedge mid-commit
        # (the worker only syncs device state; absorb mutates the trie
        # on THIS thread, so an abandoned worker can't race it).
        if self.host_mode:
            self.trie.commit_cpu(threads=self._cpu_threads)
        else:
            from ..native.mpt import DeviceWedgedError, _run_with_watchdog

            try:
                self.trie.commit_resident_timed(self.ex, self.device_timeout)
                if self.device_timeout is None:
                    store_np = np.asarray(self.ex.store)
                else:
                    store_np = _run_with_watchdog(
                        lambda: np.asarray(self.ex.store),
                        self.device_timeout, "store readback")
                self.trie.absorb_store(store_np)
            except DeviceWedgedError as e:
                self._take_over_host(str(e))
                self.trie.commit_cpu(threads=self._cpu_threads)
        try:
            digs, blob, off = self.trie.export_nodes(
                delta=not self._export_degraded)
        except RuntimeError as e:  # dirty-trie guard: surface as ours
            raise MirrorError(f"export on unsettled trie: {e}")
        try:
            batch = diskdb.new_batch()
            for i in range(digs.shape[0]):
                batch.put(digs[i].tobytes(), blob[int(off[i]):int(off[i + 1])])
            if pre_write is not None:
                pre_write()
            batch.write()
        except BaseException:
            self._export_degraded = True
            self._dirty_since_export = True
            raise
        self._export_degraded = False
        self._dirty_since_export = False
        return int(digs.shape[0])

    # ---- branch switching ------------------------------------------------

    def _forget(self, block_hash: bytes) -> None:
        root = self._roots.pop(block_hash, None)
        if root is not None:
            keys = self._by_root.get(root)
            if keys is not None:
                try:
                    keys.remove(block_hash)
                except ValueError:
                    pass
                if not keys:
                    del self._by_root[root]
        self._parent.pop(block_hash, None)
        self._batch.pop(block_hash, None)
        self._batch_keys.pop(block_hash, None)
        self._accepted.discard(block_hash)

    def _switch_to(self, target: bytes) -> None:
        """Rewind to the nearest applied ancestor of [target], then
        replay the saved batches down to it."""
        # ancestry chain of target up to something applied
        chain: List[bytes] = []
        cur = target
        applied_set = set(self._applied)
        while cur not in applied_set:
            chain.append(cur)
            nxt = self._parent.get(cur)
            if nxt is None:
                raise MirrorError(
                    f"no path from {target.hex()[:8]} to the mirror")
            cur = nxt
        # rewind to the common ancestor `cur`. Accepted blocks within the
        # tip buffer rewind like any other (recent-state reads position
        # here); their records are retained, so the canonical path
        # replays back on the next forward switch. True finality is the
        # flushed base: anything below it has no record and the ancestry
        # walk above already refused it.
        while self._applied[-1] != cur:
            self._applied.pop()
            self.trie.rollback()
            self._dirty_since_export = True
        # replay down the target branch (deepest ancestor first)
        for h in reversed(chain):
            self.trie.checkpoint()
            self.trie.update(self._batch[h])
            self._dirty_since_export = True
            root = self._commit_root()
            if root != self._roots[h]:
                self.trie.rollback()  # close the scope we just opened
                raise MirrorError(
                    f"replay of {h.hex()[:8]} produced a different root")
            self._applied.append(h)
