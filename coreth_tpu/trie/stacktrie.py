"""StackTrie — streaming trie for sorted-key insertion.

Semantics of /root/reference/trie/stacktrie.go:69-94: keys must arrive in
strictly increasing order; subtrees left of the insertion path are complete
and get hashed (and handed to ``write_fn``) immediately, so memory stays
O(depth). Used for DeriveSha (tx/receipt roots), state sync leaf streaming,
and range-proof verification.

``write_fn(path, hash, blob)`` is the NodeWriteFunc seam
(trie/stacktrie.go:52) that lets sync persist nodes as they complete.
"""

from __future__ import annotations

from typing import Callable, List, Optional

from .. import rlp
from ..native import keccak256
from .encoding import hex_to_compact
from .node import EMPTY_ROOT

_EMPTY, _LEAF, _EXT, _BRANCH, _HASHED = range(5)


def _key_nibbles(key: bytes) -> bytes:
    out = bytearray(len(key) * 2)
    for i, b in enumerate(key):
        out[2 * i] = b >> 4
        out[2 * i + 1] = b & 0x0F
    return bytes(out)


def _common(a: bytes, b: bytes) -> int:
    n = min(len(a), len(b))
    for i in range(n):
        if a[i] != b[i]:
            return i
    return n


class _Node:
    __slots__ = ("typ", "key", "val", "children")

    def __init__(self, typ: int, key: bytes = b"", val: bytes = b""):
        self.typ = typ
        self.key = key  # nibbles, no terminator
        self.val = val  # leaf value; after hashing: 32B hash or <32B raw rlp
        self.children: List[Optional["_Node"]] = [None] * 16


class StackTrie:
    def __init__(
        self,
        write_fn: Optional[Callable[[bytes, bytes, bytes], None]] = None,
        keccak: Callable[[bytes], bytes] = keccak256,
    ):
        self._root = _Node(_EMPTY)
        self._write = write_fn
        self._keccak = keccak
        self._last_key: Optional[bytes] = None

    def update(self, key: bytes, value: bytes) -> None:
        if not value:
            raise ValueError("stacktrie cannot store empty values")
        if self._last_key is not None and key <= self._last_key:
            raise ValueError("stacktrie keys must be strictly increasing")
        self._last_key = key
        self._insert(self._root, _key_nibbles(key), value, b"")

    def _insert(self, st: _Node, key: bytes, value: bytes, path: bytes) -> None:
        if st.typ == _EMPTY:
            st.typ = _LEAF
            st.key = key
            st.val = value
            return

        if st.typ == _BRANCH:
            idx = key[0]
            # children left of the insertion point are complete; only the
            # rightmost existing one can still be unhashed
            for i in range(idx - 1, -1, -1):
                if st.children[i] is not None:
                    if st.children[i].typ != _HASHED:
                        self._hash_node(st.children[i], path + bytes([i]))
                    break
            child = st.children[idx]
            if child is None:
                st.children[idx] = _Node(_LEAF, key[1:], value)
            else:
                self._insert(child, key[1:], value, path + key[:1])
            return

        if st.typ == _EXT:
            diff = _common(st.key, key)
            if diff == len(st.key):
                self._insert(st.children[0], key[diff:], value, path + key[:diff])
                return
            # split: the existing subtree below the divergence is complete
            if diff < len(st.key) - 1:
                n = _Node(_EXT, st.key[diff + 1:])
                n.children[0] = st.children[0]
            else:
                n = st.children[0]
            self._hash_node(n, path + st.key[: diff + 1])
            o = _Node(_LEAF, key[diff + 1:], value)
            old_nib, new_nib = st.key[diff], key[diff]
            if diff == 0:
                st.typ = _BRANCH
                st.key = b""
                st.children = [None] * 16
                branch = st
            else:
                branch = _Node(_BRANCH)
                st.key = st.key[:diff]
                st.children = [None] * 16
                st.children[0] = branch
            branch.children[old_nib] = n
            branch.children[new_nib] = o
            return

        if st.typ == _LEAF:
            diff = _common(st.key, key)
            if diff == len(st.key):
                raise ValueError("duplicate key in stacktrie")
            # freeze the old leaf below the split point
            n = _Node(_LEAF, st.key[diff + 1:], st.val)
            self._hash_node(n, path + st.key[: diff + 1])
            o = _Node(_LEAF, key[diff + 1:], value)
            old_nib, new_nib = st.key[diff], key[diff]
            if diff == 0:
                st.typ = _BRANCH
                st.key = b""
                st.val = b""
                st.children = [None] * 16
                branch = st
            else:
                branch = _Node(_BRANCH)
                st.typ = _EXT
                st.key = st.key[:diff]
                st.val = b""
                st.children = [None] * 16
                st.children[0] = branch
            branch.children[old_nib] = n
            branch.children[new_nib] = o
            return

        raise ValueError("insert into hashed subtree")

    def _hash_node(self, st: _Node, path: bytes) -> None:
        """Encode st (whose children are complete), hash if >=32B."""
        if st.typ == _HASHED:
            return
        if st.typ == _BRANCH:
            items = []
            for i in range(16):
                c = st.children[i]
                if c is None:
                    items.append(b"")
                    continue
                if c.typ != _HASHED:
                    self._hash_node(c, path + bytes([i]))
                items.append(c.val if len(c.val) == 32 else rlp.decode(c.val))
            items.append(b"")
            enc = rlp.encode(items)
        elif st.typ == _EXT:
            c = st.children[0]
            if c.typ != _HASHED:
                self._hash_node(c, path + st.key)
            ref = c.val if len(c.val) == 32 else rlp.decode(c.val)
            enc = rlp.encode([hex_to_compact(st.key), ref])
        elif st.typ == _LEAF:
            enc = rlp.encode([hex_to_compact(st.key + b"\x10"), st.val])
        else:
            raise ValueError("cannot hash empty node")
        st.typ = _HASHED
        st.children = [None] * 16
        st.key = b""
        if len(enc) < 32:
            st.val = enc  # embedded in the parent
        else:
            h = self._keccak(enc)
            st.val = h
            if self._write is not None:
                self._write(path, h, enc)

    def hash(self) -> bytes:
        """Finalize and return the root hash (root is always hashed)."""
        if self._root.typ == _EMPTY:
            return EMPTY_ROOT
        self._hash_node(self._root, b"")
        val = self._root.val
        if len(val) < 32:
            h = self._keccak(val)
            if self._write is not None:
                self._write(b"", h, val)
            self._root.val = h
            return h
        return val
