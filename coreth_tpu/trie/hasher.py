"""Trie node hashing — the north-star seam.

The reference hashes nodes with a recursive CPU walk, fanning out 16
goroutines per branch when >=100 nodes are unhashed (/root/reference/trie/
hasher.go:57,124-139; trie/trie.go:618-619). Here the same factory seam
exposes two backends:

  Hasher         — recursive CPU hasher over the C++ keccak (the fallback
                   for small dirty sets, where kernel-launch latency would
                   dominate).
  BatchedHasher  — level-synchronized data-parallel hashing: the dirty
                   subtree is grouped by height, each level's node RLP is
                   hashed as ONE batch on the TPU keccak kernel, and
                   digests feed the next level's RLP. This is the TPU-native
                   replacement for the goroutine fan-out.

Both are bit-exact: node RLP < 32 bytes is embedded in the parent instead of
hashed (trie/hasher.go:160-175 semantics), and the root is always hashed.

new_hasher() picks a backend by dirty-node count, mirroring the reference's
parallel threshold.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

from .. import rlp
from ..native import keccak256 as _cpu_keccak
from .encoding import hex_to_compact
from .node import FullNode, HashNode, ShortNode, ValueNode

# Below this many dirty nodes the CPU hasher wins (kernel launch + transfer
# latency); mirrors the reference's >=100-unhashed parallel threshold.
BATCH_THRESHOLD = 100


def node_items(n, child_repr: Callable = None):
    """Collapsed node -> python RLP structure (lists/bytes).

    child_repr maps a child node to its reference representation; by default
    children must already be HashNode/ValueNode/None/embedded Short/Full.
    """
    if isinstance(n, ShortNode):
        return [hex_to_compact(n.key), _ref_item(n.val, child_repr)]
    if isinstance(n, FullNode):
        items = [_ref_item(c, child_repr) for c in n.children[:16]]
        v = n.children[16]
        items.append(bytes(v) if isinstance(v, ValueNode) else b"")
        return items
    raise TypeError(f"cannot encode {type(n)}")


def _ref_item(child, child_repr):
    if child is None:
        return b""
    if isinstance(child, (HashNode, ValueNode)):
        return bytes(child)
    if child_repr is not None:
        rep = child_repr(child)
        if rep is not None:
            return rep
    # embedded small node
    return node_items(child, child_repr)


def node_to_bytes(n) -> bytes:
    return rlp.encode(node_items(n))


class Hasher:
    """Recursive CPU hasher: hash(n, force) -> (hashed_ref, n).

    hashed_ref is a HashNode when the encoding is >=32 bytes (or force),
    else the collapsed node itself for embedding in the parent. Hashes are
    cached in node flags; clean nodes short-circuit.
    """

    def __init__(self, keccak: Callable[[bytes], bytes] = _cpu_keccak):
        self._keccak = keccak

    def hash(self, n, force: bool):
        if isinstance(n, (ShortNode, FullNode)):
            cached = n.flags.hash
            if cached is not None:
                return HashNode(cached), n
            collapsed = self._collapse(n)
            return self._store(collapsed, n, force), n
        return n, n  # HashNode / ValueNode pass through

    def _collapse(self, n):
        if isinstance(n, ShortNode):
            val = n.val
            if isinstance(val, (ShortNode, FullNode)):
                val, _ = self.hash(val, False)
            return ShortNode(n.key, val)
        children = [None] * 17
        for i in range(16):
            c = n.children[i]
            if c is not None:
                children[i], _ = self.hash(c, False) if isinstance(
                    c, (ShortNode, FullNode)
                ) else (c, c)
        children[16] = n.children[16]
        return FullNode(children)

    def _store(self, collapsed, orig, force: bool):
        enc = node_to_bytes(collapsed)
        if len(enc) < 32 and not force:
            return collapsed
        h = HashNode(self._keccak(enc))
        orig.flags.hash = bytes(h)
        orig.flags.dirty = True
        return h


class BatchedHasher:
    """Level-synchronized batched hasher for large dirty sets.

    Walk once to group dirty nodes by height (leaves-first); per level,
    build every node's RLP with children resolved to digests (or embedded
    items), then hash the whole level in one device batch. The <32-byte
    embed rule is resolved on host between levels, as SURVEY.md §7 "hard
    part 1" requires.
    """

    def __init__(self, batch_keccak: Callable[[Sequence[bytes]], List[bytes]]):
        self._batch = batch_keccak

    def hash_root(self, root) -> HashNode:
        if not isinstance(root, (ShortNode, FullNode)):
            raise TypeError("batched hasher needs a Short/Full root")
        levels = self._collect_levels(root)
        reprs: dict = {}  # id(node) -> RLP item (bytes digest or embedded list)
        encs: dict = {}
        for depth, level in enumerate(levels):
            pending_nodes = []
            pending_rlp = []
            for n in level:
                items = node_items(n, child_repr=lambda c: self._child_repr(c, reprs))
                enc = rlp.encode(items)
                is_root = n is root
                if len(enc) < 32 and not is_root:
                    reprs[id(n)] = items  # embed in parent
                else:
                    pending_nodes.append(n)
                    pending_rlp.append(enc)
            if pending_rlp:
                digests = self._batch(pending_rlp)
                for n, d in zip(pending_nodes, digests):
                    n.flags.hash = d
                    reprs[id(n)] = d
                    encs[id(n)] = True
        return HashNode(root.flags.hash)

    @staticmethod
    def _child_repr(child, reprs):
        if isinstance(child, (ShortNode, FullNode)):
            if child.flags.hash is not None:
                return child.flags.hash
            rep = reprs.get(id(child))
            if rep is None:
                raise RuntimeError("child hashed out of order")
            return rep if isinstance(rep, list) else rep
        return None  # default handling (HashNode/ValueNode/None)

    @staticmethod
    def _collect_levels(root):
        """Group dirty (unhashed) Short/Full nodes by height, leaves first."""
        levels: List[list] = []

        def visit(n) -> int:
            # returns height of n within the dirty subtree; -1 for non-nodes
            if not isinstance(n, (ShortNode, FullNode)) or n.flags.hash is not None:
                return -1
            h = -1
            if isinstance(n, ShortNode):
                h = max(h, visit(n.val))
            else:
                for c in n.children[:16]:
                    h = max(h, visit(c))
            h += 1
            while len(levels) <= h:
                levels.append([])
            levels[h].append(n)
            return h

        visit(root)
        return levels


def new_hasher(dirty_estimate: int = 0, batch_keccak=None):
    """Factory seam (trie/hasher.go:57 newHasher equivalent).

    Returns a BatchedHasher when the dirty set is large and a device batch
    fn is available, else the recursive CPU hasher.
    """
    if batch_keccak is not None and dirty_estimate >= BATCH_THRESHOLD:
        return BatchedHasher(batch_keccak)
    return Hasher()
