"""Trie node hashing — the north-star seam.

The reference hashes nodes with a recursive CPU walk, fanning out 16
goroutines per branch when >=100 nodes are unhashed (/root/reference/trie/
hasher.go:57,124-139; trie/trie.go:618-619). Here the same factory seam
exposes two backends:

  Hasher         — recursive CPU hasher over the C++ keccak (the fallback
                   for small dirty sets, where kernel-launch latency would
                   dominate).
  BatchedHasher  — level-synchronized data-parallel hashing: the dirty
                   subtree is grouped by height, each level's node RLP is
                   hashed as ONE batch on the TPU keccak kernel, and
                   digests feed the next level's RLP. This is the TPU-native
                   replacement for the goroutine fan-out.

Both are bit-exact: node RLP < 32 bytes is embedded in the parent instead of
hashed (trie/hasher.go:160-175 semantics), and the root is always hashed.

new_hasher() picks a backend by dirty-node count, mirroring the reference's
parallel threshold.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence, Tuple

from .. import rlp
from ..metrics import default_registry as _metrics
from ..native import default_cpu_threads  # noqa: F401  (re-export: one policy)
from ..native import keccak256 as _cpu_keccak
from ..native import keccak256_batch as _cpu_keccak_batch
from .encoding import hex_to_compact
from .node import FullNode, HashNode, ShortNode, ValueNode

# Below this many dirty nodes the CPU hasher wins (kernel launch + transfer
# latency); mirrors the reference's >=100-unhashed parallel threshold.
BATCH_THRESHOLD = 100

# batch-keccak attribution across every seam (host pool + device
# dispatch): calls, messages, and a size distribution. A handful of
# updates per block level — noise next to the hashing itself. The
# flight recorder diffs the counters per block.
_keccak_batches = _metrics.counter("trie/keccak/batches")
_keccak_batch_msgs = _metrics.counter("trie/keccak/batch_msgs")
_keccak_batch_hist = _metrics.histogram("trie/keccak/batch_size")


def count_keccak_batch(n_msgs: int) -> None:
    """One batch of [n_msgs] messages hit a batch-keccak seam."""
    _keccak_batches.inc()
    _keccak_batch_msgs.inc(n_msgs)
    _keccak_batch_hist.update(n_msgs)  # int sample: SA004 scope (trie/)


def cpu_batch_keccak(threads: int = 0):
    """Threaded-native batch keccak usable as new_hasher's batch_keccak seam.

    The reference fans out 16 goroutines per branch when >=100 nodes are
    unhashed (trie/hasher.go:124-139); this is the same lever on the native
    C++ keccak — one call, the level's messages striped across a parked
    worker pool. threads<=0 resolves to default_cpu_threads().
    """
    t = threads if threads > 0 else default_cpu_threads()

    def batch(msgs: Sequence[bytes]) -> List[bytes]:
        count_keccak_batch(len(msgs))
        return _cpu_keccak_batch(msgs, threads=t)

    return batch


def node_items(n, child_repr: Callable = None):
    """Collapsed node -> python RLP structure (lists/bytes).

    child_repr maps a child node to its reference representation; by default
    children must already be HashNode/ValueNode/None/embedded Short/Full.
    """
    if isinstance(n, ShortNode):
        return [hex_to_compact(n.key), _ref_item(n.val, child_repr)]
    if isinstance(n, FullNode):
        items = [_ref_item(c, child_repr) for c in n.children[:16]]
        v = n.children[16]
        items.append(bytes(v) if isinstance(v, ValueNode) else b"")
        return items
    raise TypeError(f"cannot encode {type(n)}")


def _ref_item(child, child_repr):
    if child is None:
        return b""
    if isinstance(child, (HashNode, ValueNode)):
        return bytes(child)
    if child_repr is not None:
        rep = child_repr(child)
        if rep is not None:
            return rep
    # embedded small node
    return node_items(child, child_repr)


def node_to_bytes(n) -> bytes:
    return rlp.encode(node_items(n))


class Hasher:
    """Recursive CPU hasher: hash(n, force) -> (hashed_ref, n).

    hashed_ref is a HashNode when the encoding is >=32 bytes (or force),
    else the collapsed node itself for embedding in the parent. Hashes are
    cached in node flags; clean nodes short-circuit.
    """

    def __init__(self, keccak: Callable[[bytes], bytes] = _cpu_keccak):
        self._keccak = keccak

    def hash(self, n, force: bool):
        if isinstance(n, (ShortNode, FullNode)):
            cached = n.flags.hash
            if cached is not None:
                return HashNode(cached), n
            collapsed = self._collapse(n)
            return self._store(collapsed, n, force), n
        return n, n  # HashNode / ValueNode pass through

    def _collapse(self, n):
        if isinstance(n, ShortNode):
            val = n.val
            if isinstance(val, (ShortNode, FullNode)):
                val, _ = self.hash(val, False)
            return ShortNode(n.key, val)
        children = [None] * 17
        for i in range(16):
            c = n.children[i]
            if c is not None:
                children[i], _ = self.hash(c, False) if isinstance(
                    c, (ShortNode, FullNode)
                ) else (c, c)
        children[16] = n.children[16]
        return FullNode(children)

    def _store(self, collapsed, orig, force: bool):
        enc = node_to_bytes(collapsed)
        if len(enc) < 32 and not force:
            return collapsed
        h = HashNode(self._keccak(enc))
        orig.flags.hash = bytes(h)
        orig.flags.dirty = True
        return h


class BatchedHasher:
    """Level-synchronized batched hasher for large dirty sets.

    Walk once to group dirty nodes by height (leaves-first); per level,
    build every node's RLP with children resolved to digests (or embedded
    items), then hash the whole level in one device batch. The <32-byte
    embed rule is resolved on host between levels, as SURVEY.md §7 "hard
    part 1" requires.
    """

    def __init__(self, batch_keccak: Callable[[Sequence[bytes]], List[bytes]]):
        self._batch = batch_keccak

    def hash_root(self, root) -> HashNode:
        if not isinstance(root, (ShortNode, FullNode)):
            raise TypeError("batched hasher needs a Short/Full root")
        levels = self._collect_levels(root)
        reprs: dict = {}  # id(node) -> RLP item (bytes digest or embedded list)
        encs: dict = {}
        for depth, level in enumerate(levels):
            pending_nodes = []
            pending_rlp = []
            for n in level:
                items = node_items(n, child_repr=lambda c: self._child_repr(c, reprs))
                enc = rlp.encode(items)
                is_root = n is root
                if len(enc) < 32 and not is_root:
                    reprs[id(n)] = items  # embed in parent
                else:
                    pending_nodes.append(n)
                    pending_rlp.append(enc)
            if pending_rlp:
                digests = self._batch(pending_rlp)
                for n, d in zip(pending_nodes, digests):
                    n.flags.hash = d
                    reprs[id(n)] = d
                    encs[id(n)] = True
        return HashNode(root.flags.hash)

    @staticmethod
    def _child_repr(child, reprs):
        if isinstance(child, (ShortNode, FullNode)):
            if child.flags.hash is not None:
                return child.flags.hash
            rep = reprs.get(id(child))
            if rep is None:
                raise RuntimeError("child hashed out of order")
            return rep if isinstance(rep, list) else rep
        return None  # default handling (HashNode/ValueNode/None)

    @staticmethod
    def _collect_levels(root):
        """Group dirty (unhashed) Short/Full nodes by height, leaves first."""
        return [
            [n for n, _path in lvl] for lvl in collect_levels_with_paths(root)
        ]


def collect_levels_with_paths(root):
    """Group dirty (unhashed) Short/Full nodes by height with their full hex
    paths, leaves first. Shared by the level-batched, fused, and planned
    hashers so the height/dirtiness rules live in exactly one place."""
    levels: List[list] = []

    def visit(n, path: bytes) -> int:
        # returns height of n within the dirty subtree; -1 for non-nodes
        if not isinstance(n, (ShortNode, FullNode)) or n.flags.hash is not None:
            return -1
        if isinstance(n, ShortNode):
            h = visit(n.val, path + n.key)
        else:
            h = -1
            for i in range(16):
                c = n.children[i]
                if c is not None:
                    h = max(h, visit(c, path + bytes([i])))
        h += 1
        while len(levels) <= h:
            levels.append([])
        levels[h].append((n, path))
        return h

    visit(root, b"")
    return levels


def new_hasher(dirty_estimate: int = 0, batch_keccak=None):
    """Factory seam (trie/hasher.go:57 newHasher equivalent).

    Returns a BatchedHasher when the dirty set is large and a device batch
    fn is available, else the recursive CPU hasher.
    """
    if batch_keccak is not None and dirty_estimate >= BATCH_THRESHOLD:
        return BatchedHasher(batch_keccak)
    return Hasher()


# ---------------------------------------------------------------------------
# Fused hasher: the whole commit in ONE device dispatch
# ---------------------------------------------------------------------------


class _Slot:
    """Placeholder for a not-yet-computed child digest in a parent's RLP."""

    __slots__ = ("gid",)

    def __init__(self, gid: int):
        self.gid = gid


def _item_len(item) -> int:
    """Encoded RLP length; Slot counts as a 32-byte string (33 encoded)."""
    if isinstance(item, _Slot):
        return 33
    if isinstance(item, (bytes, bytearray)):
        n = len(item)
        if n == 1 and item[0] < 0x80:
            return 1
        if n < 56:
            return 1 + n
        ll = (n.bit_length() + 7) // 8
        return 1 + ll + n
    if isinstance(item, list):
        payload = sum(_item_len(i) for i in item)
        if payload < 56:
            return 1 + payload
        ll = (payload.bit_length() + 7) // 8
        return 1 + ll + payload
    raise TypeError(f"cannot size {type(item)}")


def _write_item(item, out: bytearray, patches: list) -> None:
    """Serialize with zeroed digest slots, recording (offset, gid) patches."""
    if isinstance(item, _Slot):
        out.append(0xA0)
        patches.append((len(out), item.gid))
        out.extend(b"\x00" * 32)
        return
    if isinstance(item, (bytes, bytearray)):
        n = len(item)
        if n == 1 and item[0] < 0x80:
            out.append(item[0])
        elif n < 56:
            out.append(0x80 + n)
            out.extend(item)
        else:
            lb = n.to_bytes((n.bit_length() + 7) // 8, "big")
            out.append(0xB7 + len(lb))
            out.extend(lb)
            out.extend(item)
        return
    if isinstance(item, list):
        payload = sum(_item_len(i) for i in item)
        if payload < 56:
            out.append(0xC0 + payload)
        else:
            lb = payload.to_bytes((payload.bit_length() + 7) // 8, "big")
            out.append(0xF7 + len(lb))
            out.extend(lb)
        for i in item:
            _write_item(i, out, patches)
        return
    raise TypeError(f"cannot write {type(item)}")


_KECCAK_RATE = 136


def _keccak_pad(msg: bytes) -> Tuple[bytes, int]:
    """Keccak-256 pad10*1; returns (padded bytes, block count)."""
    n = len(msg)
    blocks = n // _KECCAK_RATE + 1
    padded = bytearray(blocks * _KECCAK_RATE)
    padded[:n] = msg
    padded[n] ^= 0x01
    padded[-1] ^= 0x80
    return bytes(padded), blocks


class FusedHasher:
    """One-dispatch commit hashing (ops/keccak_fused.py consumer).

    The entire dirty set — every level, every size bucket — ships to the
    device as one transfer; child digests are patched into parent messages
    on-device between levels. Bit-exact with Hasher/BatchedHasher.

    The builder is a single-pass writer: each node's encoded length is
    cached when it is processed, so parents compute their RLP headers
    arithmetically and write their body exactly once (no separate
    node_items/_item_len/_write_item traversals).
    """

    def __init__(self, fused_impl=None):
        from ..ops.keccak_fused import FusedBatch, fused_commit

        self._FusedBatch = FusedBatch
        self._impl = fused_impl if fused_impl is not None else fused_commit

    def hash_root(self, root) -> HashNode:
        if not isinstance(root, (ShortNode, FullNode)):
            raise TypeError("fused hasher needs a Short/Full root")
        levels = BatchedHasher._collect_levels(root)
        batch = self._FusedBatch()

        # per-node info: (kind, payload) where kind is one of
        #   "gid"   — hashed; payload = global digest index (33 enc bytes)
        #   "embed" — embedded; payload = raw encoded bytes (with no slots)
        info: dict = {}
        hashed_nodes: list = []

        def child_len(c) -> int:
            """Encoded length of a child reference."""
            if c is None:
                return 1
            if isinstance(c, (HashNode, ValueNode)):
                return _bytes_enc_len(bytes(c))
            if c.flags.hash is not None:
                return 33
            kind, payload = info[id(c)]
            return 33 if kind == "gid" else len(payload)

        def write_child(c, out: bytearray, patches: list) -> None:
            if c is None:
                out.append(0x80)
                return
            if isinstance(c, (HashNode, ValueNode)):
                _write_bytes(bytes(c), out)
                return
            if c.flags.hash is not None:
                _write_bytes(c.flags.hash, out)
                return
            kind, payload = info[id(c)]
            if kind == "gid":
                out.append(0xA0)
                patches.append((len(out), payload))
                out.extend(b"\x00" * 32)
            else:
                out.extend(payload)

        for level in levels:
            msgs, nblocks, patches, nodes_here = [], [], [], []
            for n in level:
                # payload length from cached child lengths
                if isinstance(n, ShortNode):
                    key_enc = hex_to_compact(n.key)
                    payload_len = _bytes_enc_len(key_enc) + child_len(n.val)
                else:
                    payload_len = 0
                    for i in range(16):
                        payload_len += child_len(n.children[i])
                    v = n.children[16]
                    payload_len += (
                        _bytes_enc_len(bytes(v)) if isinstance(v, ValueNode) else 1
                    )
                total_len = _list_hdr_len(payload_len) + payload_len

                buf = bytearray()
                node_patches: list = []
                _write_list_hdr(payload_len, buf)
                if isinstance(n, ShortNode):
                    _write_bytes(key_enc, buf)
                    write_child(n.val, buf, node_patches)
                else:
                    for i in range(16):
                        write_child(n.children[i], buf, node_patches)
                    v = n.children[16]
                    if isinstance(v, ValueNode):
                        _write_bytes(bytes(v), buf)
                    else:
                        buf.append(0x80)

                if total_len < 32 and n is not root:
                    info[id(n)] = ("embed", bytes(buf))
                    continue
                padded, blocks = _keccak_pad(bytes(buf))
                mi = len(msgs)
                msgs.append(padded)
                nblocks.append(blocks)
                # patch offsets recorded during this node's write
                for off, gid in node_patches:
                    patches.append((mi, off, gid))
                nodes_here.append(n)
            level_gids = batch.add_level(msgs, nblocks, patches)
            for n, g in zip(nodes_here, level_gids):
                info[id(n)] = ("gid", g)
                hashed_nodes.append((n, g))

        digests = batch.run(self._impl)
        for n, g in hashed_nodes:
            n.flags.hash = digests[g]
            n.flags.dirty = True
        return HashNode(root.flags.hash)


def _bytes_enc_len(b: bytes) -> int:
    n = len(b)
    if n == 1 and b[0] < 0x80:
        return 1
    if n < 56:
        return 1 + n
    return 1 + (n.bit_length() + 7) // 8 + n


def _write_bytes(b: bytes, out: bytearray) -> None:
    n = len(b)
    if n == 1 and b[0] < 0x80:
        out.append(b[0])
    elif n < 56:
        out.append(0x80 + n)
        out.extend(b)
    else:
        lb = n.to_bytes((n.bit_length() + 7) // 8, "big")
        out.append(0xB7 + len(lb))
        out.extend(lb)
        out.extend(b)


def _list_hdr_len(payload: int) -> int:
    if payload < 56:
        return 1
    return 1 + (payload.bit_length() + 7) // 8


def _write_list_hdr(payload: int, out: bytearray) -> None:
    if payload < 56:
        out.append(0xC0 + payload)
    else:
        lb = payload.to_bytes((payload.bit_length() + 7) // 8, "big")
        out.append(0xF7 + len(lb))
        out.extend(lb)
