"""Merkle proofs (semantics of /root/reference/trie/proof.go).

prove() collects the node blobs along a key's path; verify_proof() walks
them from the root hash. Range proofs (VerifyRangeProof, used by state
sync) live in proof_range.py.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..metrics import count_drop
from ..native import keccak256
from .encoding import key_to_hex
from .hasher import Hasher, node_to_bytes
from .node import (
    FullNode,
    HashNode,
    ProofCorruptNodeError,
    ProofMissingNodeError,
    ShortNode,
    ValueNode,
    must_decode_node,
)
from .trie import Trie


def prove(trie: Trie, key: bytes) -> List[bytes]:
    """Return the list of node blobs proving ``key`` (inclusion or absence)."""
    # ensure hashes are computed
    trie.hash()
    hexkey = key_to_hex(key)
    nodes = []
    n = trie.root
    prefix = b""
    while n is not None:
        if isinstance(n, HashNode):
            n = trie._resolve(n, prefix)
        if isinstance(n, ValueNode):
            break
        nodes.append(n)
        if isinstance(n, ShortNode):
            if len(hexkey) < len(n.key) or hexkey[: len(n.key)] != n.key:
                n = None
            else:
                prefix += n.key
                hexkey = hexkey[len(n.key):]
                n = n.val if not isinstance(n.val, ValueNode) else None
        elif isinstance(n, FullNode):
            if not hexkey:
                break
            prefix += hexkey[:1]
            n, hexkey = n.children[hexkey[0]], hexkey[1:]
    hasher = Hasher()
    proof = []
    for n in nodes:
        hashed, _ = hasher.hash(n, False)
        if isinstance(hashed, HashNode):
            # collapse with hashed children for the canonical blob
            proof.append(_encoded(n, hasher))
    return proof


def _encoded(n, hasher: Hasher) -> bytes:
    collapsed = hasher._collapse(n)
    return node_to_bytes(collapsed)


def verify_proof(root_hash: bytes, key: bytes, proof: Dict[bytes, bytes]) -> Optional[bytes]:
    """Verify a proof (dict hash->blob). Returns the value or None (proved
    absent). Raises ValueError on an invalid proof."""
    hexkey = key_to_hex(key)
    want = root_hash
    n = None
    while True:
        blob = proof.get(want)
        if blob is None:
            # typed absent-vs-corrupt split (ISSUE 8 satellite): an
            # incomplete proof set is refetch territory, a bad blob is
            # peer misbehavior — triage needs to tell them apart
            count_drop("trie/proof/missing_node")
            raise ProofMissingNodeError(want, "verify_proof")
        if keccak256(blob) != want:
            count_drop("trie/proof/corrupt_node")
            raise ProofCorruptNodeError(want, "hash mismatch")
        try:
            n = must_decode_node(want, blob)
        except Exception as exc:
            count_drop("trie/proof/corrupt_node")
            raise ProofCorruptNodeError(want, f"undecodable: {exc}") from exc
        value, rest = _walk(n, hexkey, proof)
        if isinstance(rest, HashNode):
            want = bytes(rest)
            hexkey = value  # remaining key returned alongside
            continue
        return rest


def _walk(n, hexkey: bytes, proof) -> Tuple[Optional[bytes], object]:
    """Descend embedded nodes; returns (remaining_key, HashNode) to continue
    in the next proof blob, or (None, value_bytes|None) when resolved."""
    while True:
        if n is None:
            return None, None
        if isinstance(n, ValueNode):
            return None, bytes(n)
        if isinstance(n, HashNode):
            return hexkey, n
        if isinstance(n, ShortNode):
            if len(hexkey) < len(n.key) or hexkey[: len(n.key)] != n.key:
                return None, None
            hexkey = hexkey[len(n.key):]
            n = n.val
            continue
        if isinstance(n, FullNode):
            if not hexkey:
                n = n.children[16]
                continue
            n, hexkey = n.children[hexkey[0]], hexkey[1:]
            continue
        raise ValueError(f"invalid node {type(n)}")
