"""Trie iteration in key order (role of /root/reference/trie/iterator.go).

`iterate_leaves` yields (key_bytes, value) pairs in ascending key order,
resolving nodes lazily; `iterate_nodes` yields every resolved node with its
path (used by sync handlers and dumps).
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

from .encoding import hex_to_keybytes, key_to_hex
from .node import FullNode, HashNode, ShortNode, ValueNode
from .trie import Trie


def _strip_term(hexkey: bytes) -> bytes:
    return hexkey[:-1] if hexkey and hexkey[-1] == 16 else hexkey


def diff_leaves(trie_a: Trie, trie_b: Trie):
    """Yield (key_bytes, val_a, val_b) for every leaf whose value differs
    between the two tries, PRUNING shared subtrees by node hash — the
    role of the reference's trie.NewDifferenceIterator
    (trie/iterator.go): cost is O(changed subtrees), not O(total
    leaves). Either val may be None (key only on one side)."""

    def expand(trie, node, path):
        """-> (terminal value | None, {nibble: child}) one level down.
        ShortNodes are consumed one nibble at a time so both sides stay
        aligned on the SAME path regardless of structural shape."""
        if isinstance(node, HashNode):
            node = trie._resolve(node, path)
        if node is None:
            return None, {}
        if isinstance(node, ValueNode):
            return bytes(node), {}
        if isinstance(node, ShortNode):
            k = node.key
            if len(k) == 1 and k[0] == 16:  # terminator only: a value
                v = node.val
                return (bytes(v) if isinstance(v, ValueNode) else None), {}
            child = (ShortNode(k[1:], node.val) if len(k) > 1
                     else node.val)
            return None, {k[0]: child}
        if isinstance(node, FullNode):
            v = node.children[16]
            kids = {i: c for i, c in enumerate(node.children[:16])
                    if c is not None}
            return (bytes(v) if v is not None else None), kids
        raise TypeError(f"unexpected node {type(node)}")

    def walk(na, nb, path):
        if na is None and nb is None:
            return
        if (isinstance(na, HashNode) and isinstance(nb, HashNode)
                and bytes(na) == bytes(nb)):
            return  # identical subtree: the whole point of the pruning
        va, ca = expand(trie_a, na, path)
        vb, cb = expand(trie_b, nb, path)
        if va != vb:
            yield hex_to_keybytes(path), va, vb
        for nib in sorted(set(ca) | set(cb)):
            yield from walk(ca.get(nib), cb.get(nib), path + bytes([nib]))

    yield from walk(trie_a.root, trie_b.root, b"")


def iterate_leaves(
    trie: Trie, start: Optional[bytes] = None
) -> Iterator[Tuple[bytes, bytes]]:
    """Yield (key_bytes, value) in key order, keys >= ``start``.

    Hex paths compare lexicographically in the same order as keys, so a
    subtree rooted at path P can be pruned iff P < start_hex[:len(P)]
    (i.e. every key below it sorts before start).
    """
    start_hex = _strip_term(key_to_hex(start)) if start else b""

    def before_start(path: bytes) -> bool:
        return path < start_hex[: len(path)]

    def walk(n, path: bytes):
        if isinstance(n, HashNode):
            n = trie._resolve(n, path)
        if n is None:
            return
        if isinstance(n, ValueNode):
            if path >= start_hex:
                yield hex_to_keybytes(path), bytes(n)
            return
        if isinstance(n, ShortNode):
            child_path = path + _strip_term(n.key)
            if isinstance(n.val, ValueNode):
                if child_path >= start_hex:
                    yield hex_to_keybytes(child_path), bytes(n.val)
            elif not before_start(child_path):
                yield from walk(n.val, child_path)
            return
        if isinstance(n, FullNode):
            if n.children[16] is not None and path >= start_hex:
                yield hex_to_keybytes(path), bytes(n.children[16])
            for i in range(16):
                c = n.children[i]
                if c is None:
                    continue
                child_path = path + bytes([i])
                if not before_start(child_path):
                    yield from walk(c, child_path)
            return
        raise TypeError(f"invalid node {type(n)}")

    yield from walk(trie.root, b"")


def iterate_nodes(trie: Trie) -> Iterator[Tuple[bytes, object]]:
    """Yield (path, node) for every resolved node, preorder."""

    def walk(n, path: bytes):
        if isinstance(n, HashNode):
            n = trie._resolve(n, path)
        if n is None:
            return
        yield path, n
        if isinstance(n, ShortNode):
            if not isinstance(n.val, ValueNode):
                yield from walk(n.val, path + n.key)
        elif isinstance(n, FullNode):
            for i in range(16):
                if n.children[i] is not None:
                    yield from walk(n.children[i], path + bytes([i]))

    yield from walk(trie.root, b"")
