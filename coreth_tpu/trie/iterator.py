"""Trie iteration in key order (role of /root/reference/trie/iterator.go).

`iterate_leaves` yields (key_bytes, value) pairs in ascending key order,
resolving nodes lazily; `iterate_nodes` yields every resolved node with its
path (used by sync handlers and dumps).
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

from .encoding import hex_to_keybytes, key_to_hex
from .node import FullNode, HashNode, ShortNode, ValueNode
from .trie import Trie


def _strip_term(hexkey: bytes) -> bytes:
    return hexkey[:-1] if hexkey and hexkey[-1] == 16 else hexkey


def iterate_leaves(
    trie: Trie, start: Optional[bytes] = None
) -> Iterator[Tuple[bytes, bytes]]:
    """Yield (key_bytes, value) in key order, keys >= ``start``.

    Hex paths compare lexicographically in the same order as keys, so a
    subtree rooted at path P can be pruned iff P < start_hex[:len(P)]
    (i.e. every key below it sorts before start).
    """
    start_hex = _strip_term(key_to_hex(start)) if start else b""

    def before_start(path: bytes) -> bool:
        return path < start_hex[: len(path)]

    def walk(n, path: bytes):
        if isinstance(n, HashNode):
            n = trie._resolve(n, path)
        if n is None:
            return
        if isinstance(n, ValueNode):
            if path >= start_hex:
                yield hex_to_keybytes(path), bytes(n)
            return
        if isinstance(n, ShortNode):
            child_path = path + _strip_term(n.key)
            if isinstance(n.val, ValueNode):
                if child_path >= start_hex:
                    yield hex_to_keybytes(child_path), bytes(n.val)
            elif not before_start(child_path):
                yield from walk(n.val, child_path)
            return
        if isinstance(n, FullNode):
            if n.children[16] is not None and path >= start_hex:
                yield hex_to_keybytes(path), bytes(n.children[16])
            for i in range(16):
                c = n.children[i]
                if c is None:
                    continue
                child_path = path + bytes([i])
                if not before_start(child_path):
                    yield from walk(c, child_path)
            return
        raise TypeError(f"invalid node {type(n)}")

    yield from walk(trie.root, b"")


def iterate_nodes(trie: Trie) -> Iterator[Tuple[bytes, object]]:
    """Yield (path, node) for every resolved node, preorder."""

    def walk(n, path: bytes):
        if isinstance(n, HashNode):
            n = trie._resolve(n, path)
        if n is None:
            return
        yield path, n
        if isinstance(n, ShortNode):
            if not isinstance(n.val, ValueNode):
                yield from walk(n.val, path + n.key)
        elif isinstance(n, FullNode):
            for i in range(16):
                if n.children[i] is not None:
                    yield from walk(n.children[i], path + bytes([i]))

    yield from walk(trie.root, b"")
