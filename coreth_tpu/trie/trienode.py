"""Dirty-node transfer types between Trie.commit and the trie database.

Semantics of /root/reference/trie/trienode/node.go: a NodeSet carries the
nodes produced by one trie commit, keyed by path, for merging into the
in-memory dirty forest.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class Node:
    __slots__ = ("hash", "blob")

    def __init__(self, hash: bytes, blob: bytes):
        self.hash = hash
        self.blob = blob

    @property
    def is_deleted(self) -> bool:
        return len(self.blob) == 0


class NodeSet:
    """Nodes from a single commit, keyed by hex path (no terminator)."""

    def __init__(self, owner: bytes = b""):
        self.owner = owner  # b"" for the account trie, storage root otherwise
        self.nodes: Dict[bytes, Node] = {}
        self.leaves: List[Tuple[bytes, bytes]] = []  # (parent hash, blob)
        self.updates = 0
        self.deletes = 0

    def add_node(self, path: bytes, node: Node) -> None:
        if node.is_deleted:
            self.deletes += 1
        else:
            self.updates += 1
        self.nodes[path] = node

    def add_leaf(self, parent: bytes, blob: bytes) -> None:
        self.leaves.append((parent, blob))

    def __len__(self) -> int:
        return len(self.nodes)


class MergedNodeSet:
    """NodeSets from many tries (account + storages), keyed by owner."""

    def __init__(self):
        self.sets: Dict[bytes, NodeSet] = {}

    def merge(self, other: Optional[NodeSet]) -> None:
        if other is None:
            return
        existing = self.sets.get(other.owner)
        if existing is None:
            self.sets[other.owner] = other
            return
        for path, node in other.nodes.items():
            existing.add_node(path, node)
        existing.leaves.extend(other.leaves)

    def flatten(self) -> Dict[bytes, Dict[bytes, Node]]:
        return {owner: s.nodes for owner, s in self.sets.items()}
