"""In-memory dirty-node forest with ref-counting GC.

Semantics of /root/reference/trie/triedb/hashdb/database.go (dirties map,
reference/dereference, Cap, Commit) plus the trie/database_wrap.go:82-277
wrapper: Update merges a commit's NodeSets, UpdateAndReferenceRoot pins the
accepted chain's roots, Cap flushes oldest-first when over the memory limit,
Commit(root) persists a root's whole subtree to disk.

Nodes are stored on disk keyed by their hash (legacy hashdb scheme the
reference uses). The database also owns the device keccak-batch handle so
every trie it opens hashes through the TPU seam.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ..ethdb import KeyValueStore
from .node import (
    EMPTY_ROOT,
    FullNode,
    HashNode,
    ShortNode,
    ValueNode,
    must_decode_node,
)
from .secure import StateTrie
from .trie import Trie
from .trienode import MergedNodeSet, NodeSet
from .. import rlp


class _CachedNode:
    __slots__ = ("blob", "parents", "external")

    def __init__(self, blob: bytes):
        self.blob = blob
        self.parents = 0
        self.external = 0  # root pins from the chain (versiondb analog)


def _child_hashes(blob: bytes):
    """Walk a node blob for 32-byte child references (incl. embedded)."""
    out = []

    def walk(n):
        if isinstance(n, HashNode):
            out.append(bytes(n))
        elif isinstance(n, ShortNode):
            walk(n.val)
        elif isinstance(n, FullNode):
            for c in n.children[:16]:
                if c is not None:
                    walk(c)

    walk(must_decode_node(None, blob))
    return out


class TrieDatabase:
    def __init__(
        self,
        diskdb: KeyValueStore,
        batch_keccak: Optional[Callable] = None,
        dirty_limit_bytes: int = 512 * 1024 * 1024,
    ):
        self.diskdb = diskdb
        self.batch_keccak = batch_keccak
        self.dirty_limit = dirty_limit_bytes
        self._dirties: Dict[bytes, _CachedNode] = {}  # insertion-ordered
        self._dirty_size = 0
        self._cleans: Dict[bytes, bytes] = {}  # simple clean cache
        self._clean_limit = 64 * 1024 * 1024
        self._clean_size = 0

    # ----------------------------------------------------------- node reads

    def node(self, path: bytes, node_hash: bytes) -> Optional[bytes]:
        c = self._dirties.get(node_hash)
        if c is not None:
            return c.blob
        blob = self._cleans.get(node_hash)
        if blob is not None:
            return blob
        blob = self.diskdb.get(node_hash)
        if blob is not None and self._clean_size < self._clean_limit:
            self._cleans[node_hash] = blob
            self._clean_size += len(blob)
        return blob

    def open_trie(self, root: bytes = EMPTY_ROOT) -> Trie:
        return Trie(root, self, self.batch_keccak)

    def open_state_trie(self, root: bytes = EMPTY_ROOT, **kw) -> StateTrie:
        return StateTrie(root, self, self.batch_keccak, **kw)

    # --------------------------------------------------------------- update

    def _insert(self, node_hash: bytes, blob: bytes) -> None:
        if node_hash in self._dirties:
            return
        entry = _CachedNode(blob)
        self._dirties[node_hash] = entry
        self._dirty_size += len(blob) + 32
        for child in _child_hashes(blob):
            c = self._dirties.get(child)
            if c is not None:
                c.parents += 1

    def update(self, root: bytes, parent: bytes, nodes: MergedNodeSet) -> None:
        """Merge one block's commit into the forest (database_wrap Update)."""
        # insert storage tries first, then the account trie, so children
        # exist when parent references are counted
        account_set = nodes.sets.get(b"")
        for owner, ns in nodes.sets.items():
            if owner != b"":
                self._insert_set(ns)
        if account_set is not None:
            self._insert_set(account_set)
            # reference storage roots held by committed account leaves
            for _parent_hash, account_blob in account_set.leaves:
                try:
                    fields = rlp.decode(account_blob)
                    storage_root = fields[2] if isinstance(fields, list) and len(fields) >= 3 else None
                except rlp.DecodeError:
                    storage_root = None
                if storage_root and storage_root != EMPTY_ROOT:
                    c = self._dirties.get(bytes(storage_root))
                    if c is not None:
                        c.parents += 1

    def _insert_set(self, ns: NodeSet) -> None:
        # children-first: longer paths are deeper
        for path in sorted(ns.nodes, key=len, reverse=True):
            node = ns.nodes[path]
            if not node.is_deleted:
                self._insert(node.hash, node.blob)

    def update_and_reference_root(self, root: bytes, parent: bytes, nodes: MergedNodeSet) -> None:
        """Coreth's accepted-chain pinning (database_wrap.go:141)."""
        self.update(root, parent, nodes)
        self.reference(root)

    # ----------------------------------------------------- refcounting / GC

    def reference(self, root: bytes) -> None:
        c = self._dirties.get(root)
        if c is not None:
            c.external += 1

    def dereference(self, root: bytes) -> None:
        """Drop an external pin; GC any now-unreachable subtree."""
        c = self._dirties.get(root)
        if c is None:
            return
        if c.external > 0:
            c.external -= 1
        self._maybe_gc(root)

    def _maybe_gc(self, node_hash: bytes) -> None:
        c = self._dirties.get(node_hash)
        if c is None or c.parents > 0 or c.external > 0:
            return
        del self._dirties[node_hash]
        self._dirty_size -= len(c.blob) + 32
        for child in _child_hashes(c.blob):
            cc = self._dirties.get(child)
            if cc is not None and cc.parents > 0:
                cc.parents -= 1
                self._maybe_gc(child)

    # ------------------------------------------------------- commit / flush

    def commit(self, root: bytes) -> None:
        """Persist root's subtree to disk, children first; drop from dirties."""
        if root == EMPTY_ROOT or root not in self._dirties:
            return
        batch = self.diskdb.new_batch()
        self._commit_walk(root, batch, set())
        batch.write()

    def _commit_walk(self, node_hash: bytes, batch, seen: set) -> None:
        if node_hash in seen:
            return
        seen.add(node_hash)
        c = self._dirties.get(node_hash)
        if c is None:
            return
        for child in _child_hashes(c.blob):
            self._commit_walk(child, batch, seen)
        batch.put(node_hash, c.blob)
        # committed nodes leave the dirty set (refs from remaining dirty
        # parents no longer matter: reads fall through to disk)
        del self._dirties[node_hash]
        self._dirty_size -= len(c.blob) + 32
        if self._clean_size < self._clean_limit:
            self._cleans[node_hash] = c.blob
            self._clean_size += len(c.blob)

    def save_clean_cache(self, path: str) -> int:
        """Journal the clean cache to disk (trie/database_wrap.go:195-236
        saveCache): a warm restart skips re-reading hot nodes from the KV
        store. Atomic (tmp+rename); returns entries written."""
        import os
        import tempfile

        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path) or ".")
        n = 0
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(b"CTCJ\x01")  # magic + version
                for h, blob in self._cleans.items():
                    f.write(h)
                    f.write(len(blob).to_bytes(4, "big"))
                    f.write(blob)
                    n += 1
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return n

    def load_clean_cache(self, path: str) -> int:
        """Restore a journaled clean cache; entries are verified by hash
        (a corrupt/stale journal can never poison reads). Returns entries
        loaded; 0 for missing/invalid journals."""
        import os

        from ..crypto import keccak256

        if not os.path.exists(path):
            return 0
        with open(path, "rb") as f:
            blob = f.read()
        if blob[:5] != b"CTCJ\x01":
            return 0
        n = 0
        pos = 5
        while pos + 36 <= len(blob):
            h = blob[pos:pos + 32]
            ln = int.from_bytes(blob[pos + 32:pos + 36], "big")
            pos += 36
            if pos + ln > len(blob):
                break  # torn tail
            node = blob[pos:pos + ln]
            pos += ln
            if keccak256(node) != h:
                continue  # verify-or-skip, never trust the file
            if h in self._cleans:
                continue  # already resident: size must not double-count
            if self._clean_size + ln > self._clean_limit:
                break
            self._cleans[h] = node
            self._clean_size += ln
            n += 1
        return n

    def cap(self, limit_bytes: int) -> None:
        """Flush oldest nodes to disk until memory usage <= limit."""
        if self._dirty_size <= limit_bytes:
            return
        batch = self.diskdb.new_batch()
        for node_hash in list(self._dirties):
            if self._dirty_size <= limit_bytes:
                break
            c = self._dirties.pop(node_hash)
            self._dirty_size -= len(c.blob) + 32
            batch.put(node_hash, c.blob)
            # Deliberately do NOT decrement refcounts of still-dirty
            # children: a re-inserted child can sit later in FIFO than a
            # flushed parent, and dropping its pin would let a future GC
            # delete it before it is ever written — leaving the on-disk
            # parent pointing at a missing node. Retaining the count leaks
            # (node stays dirty until a later cap/commit writes it) but can
            # never lose data — the same trade the reference hashdb makes.
        batch.write()

    @property
    def dirty_size(self) -> int:
        return self._dirty_size

    def __contains__(self, node_hash: bytes) -> bool:
        return node_hash in self._dirties
