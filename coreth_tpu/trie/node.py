"""Merkle-Patricia-Trie node model (semantics of /root/reference/trie/node.go).

Node kinds:
  FullNode  — 17-way branch: 16 nibble children + value slot.
  ShortNode — extension (val is a node) or leaf (val is ValueNode),
              key stored in HEX form.
  HashNode  — 32-byte reference to a node stored elsewhere.
  ValueNode — leaf payload bytes.
  None      — empty slot.
"""

from __future__ import annotations

from typing import List, Optional

from .. import rlp
from .encoding import compact_to_hex, has_term

EMPTY_ROOT = bytes.fromhex(
    "56e81f171bcc55a6ff8345e692c0f86e5b48e01b996cadc001622fb5e363b421"
)


class NodeFlags:
    __slots__ = ("hash", "dirty")

    def __init__(self, hash: Optional[bytes] = None, dirty: bool = False):
        self.hash = hash
        self.dirty = dirty

    def copy(self) -> "NodeFlags":
        return NodeFlags(self.hash, self.dirty)


class FullNode:
    __slots__ = ("children", "flags")

    def __init__(self, children: Optional[List] = None, flags: Optional[NodeFlags] = None):
        self.children: List = children if children is not None else [None] * 17
        self.flags = flags or NodeFlags()

    def copy(self) -> "FullNode":
        return FullNode(list(self.children), self.flags.copy())

    def cached_hash(self):
        return self.flags.hash


class ShortNode:
    __slots__ = ("key", "val", "flags")

    def __init__(self, key: bytes, val, flags: Optional[NodeFlags] = None):
        self.key = key  # HEX form
        self.val = val
        self.flags = flags or NodeFlags()

    def copy(self) -> "ShortNode":
        return ShortNode(self.key, self.val, self.flags.copy())

    def cached_hash(self):
        return self.flags.hash


class HashNode(bytes):
    __slots__ = ()


class ValueNode(bytes):
    __slots__ = ()


def new_flag() -> NodeFlags:
    """Flags for a freshly modified (dirty, unhashed) node."""
    return NodeFlags(hash=None, dirty=True)


class MissingNodeError(Exception):
    def __init__(self, node_hash: bytes, path: bytes):
        super().__init__(f"missing trie node {node_hash.hex()} (path {path.hex()})")
        self.node_hash = node_hash
        self.path = path


class ProofError(ValueError):
    """Invalid merkle proof. Subclasses ValueError so pre-typed callers
    (everything caught `except ValueError` before proof errors were
    typed) keep working; new triage code catches the subclasses to tell
    an incomplete proof set from a corrupt one."""


class ProofMissingNodeError(ProofError):
    """The proof set never supplied a referenced node blob — the proof
    is INCOMPLETE (retry / refetch territory), not corrupt."""

    def __init__(self, node_hash: bytes, context: str = ""):
        self.node_hash = node_hash
        self.context = context
        suffix = f" ({context})" if context else ""
        super().__init__(f"proof node missing: {node_hash.hex()}{suffix}")


class ProofCorruptNodeError(ProofError):
    """A supplied proof blob fails its hash check or does not decode —
    the DATA is bad (peer misbehavior / bitrot), not merely absent."""

    def __init__(self, node_hash: bytes, context: str = ""):
        self.node_hash = node_hash
        self.context = context
        suffix = f" ({context})" if context else ""
        super().__init__(f"proof node corrupt: {node_hash.hex()}{suffix}")


def must_decode_node(node_hash: Optional[bytes], blob: bytes):
    """Decode an RLP-stored node; hash is cached into flags if given."""
    items = rlp.decode(blob)
    return _decode_from_items(node_hash, items)


def _decode_from_items(node_hash, items):
    if not isinstance(items, list):
        raise rlp.DecodeError("trie node must be an RLP list")
    if len(items) == 2:
        key = compact_to_hex(items[0])
        if has_term(key):
            return ShortNode(key, ValueNode(items[1]), NodeFlags(hash=node_hash))
        return ShortNode(key, _decode_ref(items[1]), NodeFlags(hash=node_hash))
    if len(items) == 17:
        n = FullNode(flags=NodeFlags(hash=node_hash))
        for i in range(16):
            n.children[i] = _decode_ref(items[i])
        if items[16] != b"" and not isinstance(items[16], list):
            n.children[16] = ValueNode(items[16])
        return n
    raise rlp.DecodeError(f"invalid number of list elements: {len(items)}")


def _decode_ref(item):
    if isinstance(item, list):
        # embedded node (total RLP < 32 bytes)
        return _decode_from_items(None, item)
    if item == b"":
        return None
    if len(item) == 32:
        return HashNode(item)
    raise rlp.DecodeError(f"invalid RLP reference, {len(item)} bytes")
