"""Secure (keccak-keyed) trie — semantics of /root/reference/trie/secure_trie.go.

All application keys are keccak256-hashed before hitting the trie, bounding
path depth to 64 nibbles and preventing DoS via deep keys. Preimages are
recorded optionally.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional

from ..native import keccak256
from .node import EMPTY_ROOT
from .trie import NodeReader, Trie


class StateTrie:
    def __init__(
        self,
        root: bytes = EMPTY_ROOT,
        reader: Optional[NodeReader] = None,
        batch_keccak: Optional[Callable] = None,
        record_preimages: bool = False,
    ):
        self.trie = Trie(root, reader, batch_keccak)
        self._preimages: Dict[bytes, bytes] = {}
        self._record = record_preimages

    def hash_key(self, key: bytes) -> bytes:
        return keccak256(key)

    def get(self, key: bytes) -> Optional[bytes]:
        return self.trie.get(self.hash_key(key))

    def update(self, key: bytes, value: bytes) -> None:
        hk = self.hash_key(key)
        if self._record:
            self._preimages[hk] = key
        self.trie.update(hk, value)

    def delete(self, key: bytes) -> None:
        self.trie.delete(self.hash_key(key))

    def get_key(self, hashed: bytes) -> Optional[bytes]:
        return self._preimages.get(hashed)

    @property
    def preimages(self) -> Dict[bytes, bytes]:
        return self._preimages

    def hash(self) -> bytes:
        return self.trie.hash()

    def commit(self, collect_leaf: bool = False):
        return self.trie.commit(collect_leaf)

    def copy(self) -> "StateTrie":
        t = StateTrie.__new__(StateTrie)
        t.trie = self.trie.copy()
        t._preimages = dict(self._preimages)
        t._record = self._record
        return t
