"""Merkle-Patricia-Trie package — the hot component (SURVEY.md §2.1).

Rebuilds the capabilities of /root/reference/trie/: the MPT itself, the
streaming StackTrie, secure (keccak-keyed) tries, proofs, iteration, and the
pluggable hasher seam where the TPU keccak batch plugs in.
"""

from .encoding import (
    compact_to_hex,
    hex_to_compact,
    hex_to_keybytes,
    key_to_hex,
    prefix_len,
)
from .hasher import BATCH_THRESHOLD, BatchedHasher, Hasher, new_hasher, node_to_bytes
from .iterator import iterate_leaves, iterate_nodes
from .node import (
    EMPTY_ROOT,
    FullNode,
    HashNode,
    MissingNodeError,
    ShortNode,
    ValueNode,
    must_decode_node,
)
from .proof import prove, verify_proof
from .secure import StateTrie
from .stacktrie import StackTrie
from .trie import NodeReader, Trie
from .triedb import TrieDatabase
from .trienode import MergedNodeSet, Node, NodeSet

__all__ = [
    "Trie", "StateTrie", "StackTrie", "NodeReader", "TrieDatabase",
    "EMPTY_ROOT", "FullNode", "ShortNode", "HashNode", "ValueNode",
    "MissingNodeError", "must_decode_node",
    "Hasher", "BatchedHasher", "new_hasher", "node_to_bytes", "BATCH_THRESHOLD",
    "NodeSet", "MergedNodeSet", "Node",
    "prove", "verify_proof",
    "iterate_leaves", "iterate_nodes",
    "key_to_hex", "hex_to_compact", "compact_to_hex", "hex_to_keybytes", "prefix_len",
]
