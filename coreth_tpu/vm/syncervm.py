"""State-sync VM orchestration (role of /root/reference/plugin/evm/
{syncervm_client,syncervm_server}.go).

Server side: serve state summaries at commit-interval heights from
committed roots (syncervm_server.go). Client side: accept a summary →
fetch 256 parent blocks → sync the state trie (+ snapshot population) →
reset the chain to the synced block (syncervm_client.go:148-330,
blockchain.go:2051 ResetToStateSyncedBlock)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, List, Optional

from ..core import rawdb
from ..core.types import Block as EthBlock
from ..fault import Backoff
from ..metrics import count_drop
from ..sync.client import RootUnavailableError, SyncClient
from ..sync.messages import SyncSummary
from ..sync.statesync import StateSyncer, StateSyncError

PARENTS_TO_FETCH = 256  # syncervm_client.go:237 parentsToGet
SYNCABLE_INTERVAL = 16384  # state sync summary cadence (sync README)

# resume marker (syncervm_client.go:111-140 summary persistence)
SYNC_SUMMARY_KEY = b"stateSyncSummary"

MAX_PIVOTS = 4       # re-targets before the sync gives up
MAX_SELF_HEALS = 3   # rebuild-mismatch resets before the sync gives up


class StateSyncServer:
    """GetLastStateSummary/GetStateSummaryByHeight (syncervm_server.go)."""

    def __init__(self, chain, syncable_interval: int = SYNCABLE_INTERVAL,
                 vm=None):
        self.chain = chain
        self.syncable_interval = syncable_interval
        self.vm = vm

    def get_last_state_summary(self) -> Optional[SyncSummary]:
        h = self.chain.last_accepted.number
        height = (h // self.syncable_interval) * self.syncable_interval
        return self.get_state_summary(height)

    def get_state_summary(self, height: int) -> Optional[SyncSummary]:
        if height % self.syncable_interval != 0:
            return None
        blk = self.chain.get_block_by_number(height)
        if blk is None or not self.chain.has_state(blk.root):
            return None
        atomic_root = b"\x00" * 32
        if self.vm is not None and getattr(self.vm, "atomic_trie", None) is not None:
            atomic_root, _ = self.vm.atomic_trie.root_at()
        return SyncSummary(blk.number, blk.hash(), blk.root, atomic_root)


class StateSyncClient:
    """stateSyncerClient orchestration (syncervm_client.go:148-330).

    [summary_provider] supplies the freshest syncable summary on demand
    (typically a closure over the peer set); when the in-flight root
    goes stale (RootUnavailableError), the sync PIVOTS to it instead of
    failing — segment markers and buffered leaves carry forward."""

    def __init__(self, vm, client: SyncClient,
                 summary_provider: Optional[Callable[[], Optional[SyncSummary]]] = None,
                 max_pivots: int = MAX_PIVOTS):
        self.vm = vm
        self.client = client
        self.summary_provider = summary_provider
        self.max_pivots = max_pivots
        self.state_syncer: Optional[StateSyncer] = None
        self.pivot_history: List[dict] = []
        # the debug_syncStatus RPC finds us through the VM
        vm.state_sync_client = self

    def _flight_note(self):
        chain = getattr(self.vm, "blockchain", None)
        rec = getattr(chain, "flight_recorder", None)
        return rec.note_event if rec is not None else None

    def status(self) -> dict:
        """debug_syncStatus payload: peers by ladder state, segment
        progress, pivot history."""
        network = getattr(self.client, "network", None)
        peers = network.tracker.status() if network is not None else {}
        by_state: dict = {}
        for info in peers.values():
            by_state[info["state"]] = by_state.get(info["state"], 0) + 1
        out = {
            "peers": peers,
            "peersByState": by_state,
            "pivots": list(self.pivot_history),
        }
        if self.state_syncer is not None:
            out["trie"] = self.state_syncer.status()
        return out

    def accept_summary(self, summary: SyncSummary) -> None:
        """acceptSyncSummary (:164): persist for resume, then run the sync
        to completion (the reference does this on a goroutine; callers may
        wrap this in a thread)."""
        diskdb = self.vm.blockchain.diskdb
        if diskdb.get(SYNC_SUMMARY_KEY) is None:
            # FRESH sync (not a resume — a resume's markered ranges wrote
            # their snapshot entries already and must keep them): wipe
            # pre-sync flat-snapshot entries so keys that exist locally
            # but not in the synced state can never survive as phantoms
            # (the reference resets snapshot generation on sync start)
            from ..state.snapshot import (
                SNAPSHOT_ACCOUNT_PREFIX,
                SNAPSHOT_STORAGE_PREFIX,
            )

            batch = diskdb.new_batch()
            # exact schema lengths only: hash-keyed trie nodes (32 B) and
            # other rawdb keys can share a first byte with these prefixes
            for prefix, klen in ((SNAPSHOT_ACCOUNT_PREFIX, 33),
                                 (SNAPSHOT_STORAGE_PREFIX, 65)):
                for k, _v in diskdb.iterate(prefix):
                    if len(k) == klen:
                        batch.delete(k)
            batch.write()
        diskdb.put(SYNC_SUMMARY_KEY, summary.encode())
        self.state_sync(summary)
        diskdb.delete(SYNC_SUMMARY_KEY)

    def ongoing_summary(self) -> Optional[SyncSummary]:
        """Resume support: a persisted summary means a sync was interrupted."""
        blob = self.vm.blockchain.diskdb.get(SYNC_SUMMARY_KEY)
        return SyncSummary.decode(blob) if blob else None

    def state_sync(self, summary: SyncSummary) -> None:
        summary = self._sync_until_complete(summary)
        self._sync_atomic_trie(summary)
        self._finish(summary)

    def _sync_until_complete(self, summary: SyncSummary) -> SyncSummary:
        """Blocks + state trie with pivot/self-heal orchestration; returns
        the summary the sync actually completed at (it moves on pivot)."""
        diskdb = self.vm.blockchain.diskdb
        syncer = self._make_syncer(summary.block_root)
        self.state_syncer = syncer
        backoff = Backoff(base=0.05, cap=2.0)
        pivots = heals = 0
        fetch_blocks = True
        try:
            while True:
                if fetch_blocks:
                    self._sync_blocks(summary)
                    fetch_blocks = False
                try:
                    syncer.sync()
                    return summary
                except RootUnavailableError:
                    newer = self._next_summary(summary)
                    if newer is None or pivots >= self.max_pivots:
                        raise
                    pivots += 1
                    syncer.pivot(newer.block_root)
                    # the resume marker must follow the pivot: a crash
                    # after this point resumes against the NEW summary,
                    # whose markers/buffer the pivot just carried over
                    diskdb.put(SYNC_SUMMARY_KEY, newer.encode())
                    self.pivot_history.append({
                        "fromHeight": summary.block_number,
                        "toHeight": newer.block_number,
                        "toRoot": newer.block_root.hex()[:16],
                    })
                    summary = newer
                    fetch_blocks = True
                except StateSyncError:
                    # rebuild mismatch reset its own segment state; a
                    # bounded retry against (now re-ranked) peers heals it
                    heals += 1
                    if heals > MAX_SELF_HEALS:
                        raise
                    backoff.sleep()
        finally:
            syncer.close()  # the pre-fix executor leak

    def _make_syncer(self, root: bytes) -> StateSyncer:
        return StateSyncer(
            self.client, self.vm.blockchain.diskdb, root,
            note_event=self._flight_note(),
        )

    def _next_summary(self, current: SyncSummary) -> Optional[SyncSummary]:
        """A STRICTLY newer summary from the provider, or None."""
        if self.summary_provider is None:
            return None
        try:
            cand = self.summary_provider()
        except Exception:
            count_drop("sync/drops/summary_provider_error")
            return None
        if (cand is None or cand.block_number <= current.block_number
                or cand.block_root == current.block_root):
            return None
        return cand

    def _sync_atomic_trie(self, summary: SyncSummary) -> None:
        """syncAtomicTrie (:284): rebuild the indexed atomic ops and replay
        them into this node's shared memory."""
        from ..trie.node import EMPTY_ROOT
        from .atomic_trie import AtomicSyncer

        if summary.atomic_root in (b"\x00" * 32, EMPTY_ROOT):
            return
        syncer = AtomicSyncer(
            self.client, self.vm.blockchain.diskdb,
            summary.atomic_root, summary.block_number,
        )
        syncer.sync()
        self.vm.atomic_trie = syncer.trie
        syncer.trie.apply_to_shared_memory(
            self.vm.shared_memory, summary.block_number
        )

    def _sync_blocks(self, summary: SyncSummary) -> None:
        """syncBlocks (:237): fetch 256 parents so the chain can verify
        descendants without gaps."""
        blobs = self.client.get_blocks(
            summary.block_hash, summary.block_number, PARENTS_TO_FETCH
        )
        diskdb = self.vm.blockchain.diskdb
        for blob in blobs:
            blk = EthBlock.decode(blob)
            h, n = blk.hash(), blk.number
            rawdb.write_header_number(diskdb, h, n)
            rawdb.write_header_rlp(diskdb, n, h, blk.header.encode())
            from .. import rlp

            body_items = [
                [rlp.decode(t.encode()) if t.type == 0 else t.encode()
                 for t in blk.transactions],
                [u.rlp_items() for u in blk.uncles],
                blk.version,
                blk.ext_data if blk.ext_data is not None else b"",
            ]
            rawdb.write_body_rlp(diskdb, n, h, rlp.encode(body_items))
            rawdb.write_canonical_hash(diskdb, h, n)

    def _sync_state_trie(self, summary: SyncSummary) -> None:
        """Single-shot trie sync (no pivot orchestration) — kept for
        callers that manage their own retry policy."""
        syncer = self._make_syncer(summary.block_root)
        self.state_syncer = syncer
        try:
            syncer.sync()
        finally:
            syncer.close()

    def _finish(self, summary: SyncSummary) -> None:
        """ResetToStateSyncedBlock (blockchain.go:2051): move chain pointers
        to the synced block and mark it accepted."""
        chain = self.vm.blockchain
        blk = chain.get_block(summary.block_hash)
        if blk is None:
            raise RuntimeError("synced block missing after block sync")
        if not chain.has_state(blk.root):
            raise RuntimeError("synced state missing after trie sync")
        rawdb.write_head_block_hash(chain.diskdb, blk.hash())
        chain._canonical[blk.number] = blk.hash()
        chain.current_block = blk
        chain.last_accepted = blk
        # resident mode: the mirror's base is the pre-sync state and can
        # never reach the synced root by replay — reboot it over the
        # freshly synced account trie so post-sync blocks verify through
        # the device-resident path
        chain.reboot_mirror()
        # the flat snapshot was populated leaf by leaf during the trie
        # sync; stamp the disk markers and re-anchor the layer tree at
        # the synced block so post-sync commits build diff layers on it
        # (the pre-sync tree is anchored at genesis — its layers can
        # never parent a post-sync block's diff)
        if chain.snaps is not None:
            from ..state.snapshot import (
                SNAPSHOT_BLOCK_HASH_KEY,
                SNAPSHOT_ROOT_KEY,
                Tree as SnapshotTree,
            )

            chain.diskdb.put(SNAPSHOT_ROOT_KEY, blk.root)
            chain.diskdb.put(SNAPSHOT_BLOCK_HASH_KEY, blk.hash())
            chain.snaps = SnapshotTree(
                chain.diskdb, chain.state_database.triedb,
                blk.root, block_hash=blk.hash(),
            )
        # the head pointers moved out of band: re-publish the read view
        # so lock-free readers land on the synced block (and the rebuilt
        # snapshot tree) rather than the pre-sync heads
        chain._publish_read_view()
        from .block import BlockStatus, VMBlock

        vmb = VMBlock(self.vm, blk)
        vmb.status = BlockStatus.ACCEPTED
        self.vm.last_accepted_vm_block = vmb
        self.vm.preferred_block = vmb
