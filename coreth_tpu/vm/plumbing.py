"""Small VM plumbing: fork-scheduled gas-price floors, the static
genesis-builder service, banned ext-data hashes, and the VM factory
(roles of /root/reference/plugin/evm/{gasprice_update,static_service,
ext_data_hashes,factory}.go).
"""

from __future__ import annotations

import json
import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

from .. import params


class GasPriceUpdater:
    """gasprice_update.go: set the tx pool's gas-price floor to the
    launch minimum, then step it at each fork activation — immediately
    for forks already active, via a timer for future ones. stop() cancels
    pending timers (the shutdownChan analog)."""

    def __init__(self, txpool, chain_config, clock: Callable[[], float] = time.time):
        self.txpool = txpool
        self.config = chain_config
        self.clock = clock
        self._timers: List[threading.Timer] = []

    def start(self) -> None:
        self.txpool.set_price_floor(params.LAUNCH_MIN_GAS_PRICE)
        steps: List[Tuple[Optional[int], str, int]] = [
            (self.config.apricot_phase1_time, "price",
             params.APRICOT_PHASE1_MIN_GAS_PRICE),
            (self.config.apricot_phase3_time, "price", 0),
            (self.config.apricot_phase3_time, "min_fee",
             params.APRICOT_PHASE3_MIN_BASE_FEE),
            (self.config.apricot_phase4_time, "min_fee",
             params.APRICOT_PHASE4_MIN_BASE_FEE),
        ]
        for ts, kind, value in steps:
            if ts is None:
                return  # later forks can't be scheduled either (gpu.start)
            self._schedule(ts, kind, value)

    def _apply(self, kind: str, value: int) -> None:
        if kind == "price":
            self.txpool.set_price_floor(value)
        else:
            self.txpool.set_min_fee_floor(value)

    def _schedule(self, ts: int, kind: str, value: int) -> None:
        delay = ts - self.clock()
        if delay <= 0:
            self._apply(kind, value)
            return
        t = threading.Timer(delay, lambda: self._apply(kind, value))
        t.daemon = True
        self._timers.append(t)
        t.start()

    def stop(self) -> None:
        for t in self._timers:
            t.cancel()
        self._timers.clear()


class StaticService:
    """static_service.go: BuildGenesis — marshal a genesis spec to the
    hex blob Initialize takes, with no chain running."""

    def buildGenesis(self, genesis_obj: dict) -> dict:
        blob = json.dumps(genesis_obj, sort_keys=True).encode()
        return {"bytes": "0x" + blob.hex(), "encoding": "hex"}


# ext_data_hashes.go: on fuji/mainnet some historical blocks carry an
# ExtDataHash that must map to a REPAIRED hash (bonus-block cleanup).
# The reference embeds network-specific JSON; networks without a list
# (test/local) ban nothing.
_ext_data_hashes: Dict[int, Dict[bytes, bytes]] = {}


def load_ext_data_hashes(network_id: int, raw_json: bytes) -> None:
    """Install a network's {extDataHash: repairedHash} map (the go:embed
    fuji/mainnet JSON analog; hex-keyed)."""
    table = {
        bytes.fromhex(k.removeprefix("0x")): bytes.fromhex(
            v.removeprefix("0x"))
        for k, v in json.loads(raw_json).items()
    }
    _ext_data_hashes[network_id] = table


def repaired_ext_data_hash(network_id: int, h: bytes) -> Optional[bytes]:
    """The repaired hash for [h] on [network_id], or None if unmapped."""
    return _ext_data_hashes.get(network_id, {}).get(h)


def factory_new(**initialize_kwargs):
    """factory.go Factory.New: construct an uninitialized VM (the node
    calls Initialize separately); kwargs pre-bind Initialize args for
    test harnesses."""
    from .vm import VM

    vm = VM()
    if initialize_kwargs:
        vm.initialize(**initialize_kwargs)
    return vm
