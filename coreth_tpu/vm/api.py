"""VM-level RPC APIs: avax namespace, admin, health (roles of
/root/reference/plugin/evm/{service,admin,health}.go).

create_handlers() assembles the full RPC surface the reference exposes at
/ext/bc/C/{rpc,avax,admin} (vm.go:1138-1186 CreateHandlers).
"""

from __future__ import annotations

from typing import Optional

from ..eth.api import EthAPI, PersonalAPI, hb, hx, parse_bytes
from .config import DEFAULT_ETH_APIS, Config
from ..eth.backend import EthBackend
from ..eth.tracers import DebugAPI
from ..rpc.admission import ServingPolicy
from ..rpc.server import RPCError, RPCServer
from .atomic_tx import Tx, decode_tx
from .vm import ATOMIC_TX_INDEX_PREFIX


class AvaxAPI:
    """avax.* handlers (service.go:89-460): issueTx/getAtomicTx/getUTXOs."""

    def __init__(self, vm):
        self.vm = vm

    def issueTx(self, tx_bytes: str) -> dict:
        tx = decode_tx(parse_bytes(tx_bytes))
        self.vm.issue_atomic_tx(tx)
        return {"txID": hb(tx.id())}

    def getAtomicTxStatus(self, tx_id: str) -> dict:
        tid = parse_bytes(tx_id)
        if self.vm.mempool.has(tid):
            return {"status": "Processing"}
        blob = self.vm.blockchain.diskdb.get(ATOMIC_TX_INDEX_PREFIX + tid)
        if blob is not None:
            height = int.from_bytes(blob[:8], "big")
            return {"status": "Accepted", "blockHeight": hx(height)}
        return {"status": "Unknown"}

    def getAtomicTx(self, tx_id: str) -> dict:
        tid = parse_bytes(tx_id)
        tx = self.vm.mempool.get(tid)
        if tx is not None:
            return {"tx": hb(tx.encode()), "blockHeight": None}
        blob = self.vm.blockchain.diskdb.get(ATOMIC_TX_INDEX_PREFIX + tid)
        if blob is not None:
            return {
                "tx": hb(blob[8:]),
                "blockHeight": hx(int.from_bytes(blob[:8], "big")),
            }
        raise RPCError(-32000, "transaction not found")

    def getUTXOs(self, addresses, source_chain: str = "", limit: int = 100) -> dict:
        """UTXOs owned by [addresses] in this chain's inbound namespace."""
        if isinstance(addresses, str):
            addresses = [addresses]
        addrs = [parse_bytes(a) for a in addresses]
        source = parse_bytes(source_chain) if source_chain else self.vm.ctx.x_chain_id
        utxos, _, last = self.vm.shared_memory.indexed(
            source, addrs, limit=limit
        )
        return {
            "numFetched": hx(len(utxos)),
            "utxos": [hb(u) for u in utxos],
            "endIndex": hb(last) if last else None,
        }

    def version(self) -> dict:
        return {"version": "coreth-tpu/0.1.0"}

    # --- key management + wallet-side atomic txs (service.go:108-460) ----
    #
    # The reference scopes keys to an avalanchego per-user keystore
    # (username+password); this framework's analog is the node's
    # directory keystore (accounts/keystore.py) with per-key passwords —
    # the password plays both roles, so the RPC shapes keep the
    # reference's field names minus `username`.

    def _keystore(self):
        from ..eth.backend import require_keystore

        return require_keystore(getattr(self.vm, "keystore", None))

    def importKey(self, password: str, privateKey: str) -> dict:
        """service.go:141 ImportKey: store a private key, return its
        EVM address."""
        priv = parse_bytes(privateKey)
        if len(priv) != 32:
            raise RPCError(-32602, "private key must be 32 bytes")
        acct = self._keystore().import_key(priv, password)
        return {"address": hb(acct.address)}

    def exportKey(self, password: str, address: str) -> dict:
        """service.go:108 ExportKey: reveal the private key for an owned
        address (password-checked)."""
        from ..accounts.keystore import KeyStoreError
        from ..eth.api import parse_addr

        try:
            priv = self._keystore().export_key(parse_addr(address), password)
        except KeyStoreError as e:
            raise RPCError(-32000, str(e))
        return {"privateKey": hb(priv)}

    def _unlocked_keys(self, password: str):
        """Decrypt every keystore key the password opens (the analog of
        the reference's per-user key list)."""
        from ..accounts.keystore import KeyStoreError

        ks = self._keystore()
        keys = []
        for acct in ks.accounts():
            try:
                keys.append(ks.export_key(acct.address, password))
            except KeyStoreError:
                continue
        if not keys:
            raise RPCError(-32000, "password unlocks no keystore keys")
        return keys

    def _import_impl(self, password: str, to: str,
                     sourceChain: str = "") -> dict:
        """service.go Import: build+sign+issue an ImportTx consuming the
        keystore's UTXOs from [sourceChain] to EVM address [to].
        Registered EXPLICITLY as wire method "avax_import" ("import" is a
        python keyword; the leading underscore keeps register_api from
        exposing a stray avax_import_ alias)."""
        from ..eth.api import parse_addr
        from .atomic_tx import AtomicTxError
        from .tx_builder import new_import_tx

        source = (parse_bytes(sourceChain) if sourceChain
                  else self.vm.ctx.x_chain_id)
        try:
            tx = new_import_tx(
                self.vm, parse_addr(to), source,
                self._unlocked_keys(password))
            self.vm.issue_atomic_tx(tx)
        except AtomicTxError as e:
            raise RPCError(-32000, str(e))
        return {"txID": hb(tx.id())}

    def export(self, password: str, amount, to: str,
               destinationChain: str = "", assetID: str = "") -> dict:
        """service.go Export/ExportAVAX: build+sign+issue an ExportTx of
        [amount] nAVAX (or [assetID] units) to [to] on the destination
        chain."""
        from ..eth.api import parse_addr
        from .atomic_tx import AtomicTxError
        from .tx_builder import new_export_tx

        dest = (parse_bytes(destinationChain) if destinationChain
                else self.vm.ctx.x_chain_id)
        asset = parse_bytes(assetID) if assetID else self.vm.avax_asset_id
        amt = amount if isinstance(amount, int) else int(amount, 0)
        try:
            tx = new_export_tx(
                self.vm, amt, asset, dest, parse_addr(to),
                self._unlocked_keys(password))
            self.vm.issue_atomic_tx(tx)
        except AtomicTxError as e:
            raise RPCError(-32000, str(e))
        return {"txID": hb(tx.id())}


class _StackSampler:
    """All-thread statistical CPU profiler: a daemon thread samples
    sys._current_frames() on an interval and aggregates hit counts per
    (file, line, function). Covers work on every thread — the property a
    deterministic per-thread profiler can't give an RPC-driven node."""

    def __init__(self, interval: float = 0.005):
        import threading

        self.interval = interval
        self.samples = 0
        self.counts: dict = {}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def start(self):
        self._thread.start()

    def _run(self):
        import sys
        import time

        me = self._thread.ident
        while not self._stop.is_set():
            for tid, frame in list(sys._current_frames().items()):
                if tid == me:
                    continue
                self.samples += 1
                while frame is not None:
                    code = frame.f_code
                    key = (code.co_filename, frame.f_lineno, code.co_name)
                    self.counts[key] = self.counts.get(key, 0) + 1
                    frame = frame.f_back
            time.sleep(self.interval)

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2)

    def dump(self, path: str):
        rows = sorted(self.counts.items(), key=lambda kv: -kv[1])
        with open(path, "w") as f:
            f.write(f"# stack samples: {self.samples}\n")
            for (fn, line, name), n in rows[:500]:
                f.write(f"{n}\t{fn}:{line}\t{name}\n")


class ContinuousProfiler:
    """startContinuousProfiler (vm.go:1642 + config.go:89-91): rolls a
    CPU stack-sample profile to disk every [freq] seconds, keeping
    [max_files] generations (cpu.profile.1 newest)."""

    def __init__(self, profile_dir: str, freq: float = 900.0,
                 max_files: int = 5):
        import threading

        self.dir = profile_dir
        self.freq = freq
        self.max_files = max_files
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._sampler = None

    def start(self):
        self._thread.start()
        return self

    def _roll(self):
        import os

        os.makedirs(self.dir, exist_ok=True)
        for i in range(self.max_files - 1, 0, -1):
            src = os.path.join(self.dir, f"cpu.profile.{i}")
            if os.path.exists(src):
                os.replace(src, os.path.join(self.dir, f"cpu.profile.{i + 1}"))
        if self._sampler is not None:
            self._sampler.stop()
            self._sampler.dump(os.path.join(self.dir, "cpu.profile.1"))
        self._sampler = _StackSampler(interval=0.01)
        self._sampler.start()

    def _run(self):
        self._roll()
        while not self._stop.wait(self.freq):
            self._roll()

    def stop(self):
        self._stop.set()
        self._thread.join(timeout=2)
        if self._sampler is not None:
            self._sampler.stop()


class AdminAPI:
    """coreth-admin (admin.go:29-62). Profiles are real artifacts written
    to [profile_dir] (admin.go performanceProfile dir): CPU via an
    all-thread stack sampler, memory via tracemalloc/gc snapshot,
    lock/stack via a faulthandler-style all-thread dump."""

    def __init__(self, vm, profile_dir: str = None):
        import tempfile

        self.vm = vm
        self.log_level = "info"
        self.profile_dir = profile_dir or tempfile.mkdtemp(prefix="coreth_tpu_prof_")
        self._cpu_profiler = None

    def _path(self, name: str) -> str:
        import os

        os.makedirs(self.profile_dir, exist_ok=True)
        return os.path.join(self.profile_dir, name)

    def setLogLevel(self, level: str) -> bool:
        from .. import log

        log.set_level(level)  # raises on unknown levels
        self.log_level = level
        return True

    # --- chain export/import (eth/api.go Admin ExportChain/ImportChain) --

    def exportChain(self, path: str, first: int = None,
                    last: int = None) -> bool:
        """admin_exportChain: write blocks [first..last] (accepted chain,
        defaults: genesis..head) as length-prefixed RLP to [path]."""
        import struct

        chain = self.vm.blockchain
        lo = int(first) if first is not None else 0
        hi = int(last) if last is not None else chain.last_accepted.number
        if lo > hi:
            raise RPCError(-32602, "first must be <= last")
        with open(path, "wb") as f:
            for n in range(lo, hi + 1):
                blk = chain.get_block_by_number(n)
                if blk is None:
                    raise RPCError(-32000, f"block {n} not found")
                raw = blk.encode()
                f.write(struct.pack(">I", len(raw)) + raw)
        return True

    def importChain(self, path: str) -> bool:
        """admin_importChain: insert + accept each block from an
        exportChain file (blocks already known are skipped, like the
        reference's hasAllBlocks fast path)."""
        import struct

        from ..core.types import Block

        chain = self.vm.blockchain
        with open(path, "rb") as f:
            while True:
                hdr = f.read(4)
                if not hdr:
                    break
                (n,) = struct.unpack(">I", hdr)
                blk = Block.decode(f.read(n))
                if chain.get_block(blk.hash()) is not None:
                    continue  # already have it
                chain.insert_block(blk)
                chain.accept(blk)
        chain.drain_acceptor_queue()
        return True

    def startCPUProfiler(self) -> bool:
        """Statistical profiler sampling ALL thread stacks (RPC handlers
        run on per-request threads, so a deterministic per-thread profiler
        would only ever see its own handler thread)."""
        if self._cpu_profiler is not None:
            raise RuntimeError("CPU profiler already running")
        self._cpu_profiler = _StackSampler(interval=0.005)
        self._cpu_profiler.start()
        return True

    def stopCPUProfiler(self) -> bool:
        if self._cpu_profiler is None:
            raise RuntimeError("CPU profiler not running")
        p, self._cpu_profiler = self._cpu_profiler, None
        p.stop()
        p.dump(self._path("cpu.profile"))
        return True

    def memoryProfile(self) -> bool:
        """Heap snapshot. Uses a tracemalloc snapshot when tracing was
        enabled externally (full alloc-site detail); otherwise a gc-walk
        summary by type — zero standing overhead either way."""
        import tracemalloc

        with open(self._path("mem.profile"), "w") as f:
            if tracemalloc.is_tracing():
                for stat in tracemalloc.take_snapshot().statistics("lineno")[:200]:
                    f.write(f"{stat}\n")
            else:
                import gc
                import sys as _sys
                from collections import Counter

                by_type: Counter = Counter()
                bytes_by_type: Counter = Counter()
                for o in gc.get_objects():
                    t = type(o).__name__
                    by_type[t] += 1
                    try:
                        bytes_by_type[t] += _sys.getsizeof(o)
                    except Exception:
                        pass
                for t, n in by_type.most_common(200):
                    f.write(f"{t}: count={n} bytes={bytes_by_type[t]}\n")
        return True

    def lockProfile(self) -> bool:
        """Per-thread stack dump (closest host analog of the mutex
        profile): which threads are parked where."""
        import sys
        import traceback

        with open(self._path("lock.profile"), "w") as f:
            for tid, frame in sys._current_frames().items():
                f.write(f"--- thread {tid}\n")
                f.write("".join(traceback.format_stack(frame)))
        return True


class TxPoolAPI:
    """txpool namespace (internal/ethapi TxPoolAPI)."""

    def __init__(self, backend):
        self.b = backend

    def status(self) -> dict:
        pending, queued = self.b.txpool.stats()
        return {"pending": hx(pending), "queued": hx(queued)}

    def content(self) -> dict:
        out = {"pending": {}, "queued": {}}
        for addr, txs in self.b.txpool.pending_txs().items():
            out["pending"][hb(addr)] = {
                str(t.nonce): hb(t.hash()) for t in txs
            }
        return out

    def contentFrom(self, address: str) -> dict:
        """txpool_contentFrom (api.go ContentFrom): one account's slice
        of content."""
        from ..eth.api import parse_addr

        addr = parse_addr(address)
        txs = self.b.txpool.pending_txs().get(addr, [])
        return {"pending": {str(t.nonce): hb(t.hash()) for t in txs},
                "queued": {}}

    def inspect(self) -> dict:
        """txpool_inspect (api.go Inspect): human-oriented one-line tx
        summaries, geth's '<to>: <value> wei + <gas> gas x <price> wei'
        format."""
        out = {"pending": {}, "queued": {}}
        for addr, txs in self.b.txpool.pending_txs().items():
            out["pending"][hb(addr)] = {
                str(t.nonce): (
                    f"{hb(t.to) if t.to else 'contract creation'}: "
                    f"{t.value} wei + {t.gas} gas x "
                    f"{t.gas_fee_cap} wei")
                for t in txs
            }
        return out


class NetAPI:
    def __init__(self, network_id: int):
        self._id = network_id

    def version(self) -> str:
        return str(self._id)

    def listening(self) -> bool:
        return True

    def peerCount(self) -> str:
        return hx(0)


class Web3API:
    def clientVersion(self) -> str:
        return "coreth-tpu/0.1.0"

    def sha3(self, data: str) -> str:
        from ..native import keccak256

        return hb(keccak256(parse_bytes(data)))


class FiltersAPI:
    """eth_newFilter family bridged onto the FilterSystem."""

    def __init__(self, backend):
        self.b = backend

    def newFilter(self, crit: dict) -> str:
        return self.b.filters.new_log_filter(crit)

    def newBlockFilter(self) -> str:
        return self.b.filters.new_block_filter()

    def newPendingTransactionFilter(self) -> str:
        return self.b.filters.new_pending_tx_filter()

    def uninstallFilter(self, fid: str) -> bool:
        return self.b.filters.uninstall(fid)

    def getFilterChanges(self, fid: str) -> list:
        items = self.b.filters.get_changes(fid)
        out = []
        api = EthAPI(self.b)
        for item in items:
            if isinstance(item, bytes):
                out.append(hb(item))
            else:
                out.append(api._marshal_log(item, 0))
        return out


def health_check(vm) -> dict:
    """health.go: the VM is healthy when the acceptor is alive AND the
    RPC front door is not mid-drain — a draining node must drop out of
    its load balancer (503) before the lanes start shedding, not
    after."""
    out = {
        "healthy": vm.blockchain.acceptor_error is None,
        "lastAcceptedHeight": vm.blockchain.last_accepted.number,
        "error": vm.blockchain.acceptor_error,
    }
    if getattr(vm.blockchain, "degraded", False):
        # degraded read-only rung (storage write failure): the node
        # still serves reads so it stays in the LB pool, but operators
        # see the rung on every health poll
        out["degraded"] = True
    server = getattr(vm, "rpc_server", None)
    if server is not None and getattr(server, "draining", False):
        out["healthy"] = False
        out["draining"] = True
    return out


class DebugMetricsAPI:
    """Observability half of the debug namespace (go-ethereum's
    debug/metrics.go Metrics + the flight-recorder/span surface this repo
    adds). Registered alongside the tracing DebugAPI under the same
    eth-apis gate."""

    def __init__(self, vm):
        self.vm = vm

    def metrics(self) -> dict:
        """debug_metrics: JSON dump of every registered metric, plus the
        device ladder's status (state, last error, knobs) and any cached
        device-resolution failure under ops/device/status."""
        from ..metrics import default_registry
        from ..ops import device

        out = default_registry.marshal()
        status = device.default_ladder().status()
        status["resolve_error"] = device.resolution_error()
        out["ops/device/status"] = status
        return out

    def blockFlightRecord(self, n: Optional[int] = None,
                          accepted_only: bool = True) -> list:
        """debug_blockFlightRecord: per-phase timings + counter deltas
        for the last N accepted blocks (accepted_only=False includes
        inserted-but-not-yet-accepted blocks)."""
        from ..metrics.flight import marshal_record

        recs = self.vm.blockchain.flight_recorder.last(
            n=n, accepted_only=accepted_only)
        return [marshal_record(r) for r in recs]

    def spanDump(self, clear: bool = False) -> dict:
        """debug_spanDump: finished spans as Chrome trace-event JSON
        (load the result straight into Perfetto)."""
        from ..metrics.spans import tracer

        return tracer.chrome_trace(clear=bool(clear))

    def profileDump(self, fmt: str = "json") -> object:
        """debug_profileDump: the sampling profiler's bounded
        collapsed-stack table. fmt="collapsed" returns flamegraph-ready
        text (`role;frame;...;frame count` lines, pipe straight into
        flamegraph.pl); anything else returns the full JSON dump
        (per-role sample counts, lock-tagged stacks, overflow count).
        Empty/running=False when profiler-hz is 0."""
        from ..metrics.profiler import profile_dump

        dump = profile_dump()
        if fmt == "collapsed":
            return dump.get("collapsed", "")
        return dump

    def lockStatus(self) -> dict:
        """debug_lockStatus: per-canonical-lock contention table (wait/
        hold counts, totals, p99s) ranked by total measured acquire-wait,
        plus the slow-hold budget and the recent budget-breach captures
        (traceback + trace id). Rows appear once a LockOrderWitness (or
        require_lock proxy) instruments the lock — the chaos conductor
        and the race-discipline tests arm one at boot."""
        from ..utils import racecheck

        return {
            "slow_hold_budget_seconds": racecheck.slow_hold_budget(),
            "contention": racecheck.contention_table(),
            "recent_slow_holds": racecheck.recent_slow_holds(),
        }

    def setSpans(self, enabled: bool) -> bool:
        """debug_setSpans: toggle span collection process-wide at
        runtime; returns the new state."""
        from ..metrics import spans

        spans.set_enabled(bool(enabled))
        return spans.enabled

    def setExpensiveMetrics(self, enabled: bool) -> bool:
        """debug_setExpensiveMetrics: flip the EnabledExpensive gate
        process-wide at runtime; returns the new state."""
        from .. import metrics as _metrics

        _metrics.enabled_expensive = bool(enabled)
        return _metrics.enabled_expensive

    def setFailpoint(self, name: str, spec: Optional[str] = None) -> list:
        """debug_setFailpoint: arm failpoint [name] with [spec]
        ("raise[:msg]" / "hang[:ms]" with optional "%prob" / "*count" —
        coreth_tpu/fault), or disarm it when spec is empty. Returns the
        currently-armed list. Unknown names error (the registry is the
        source of truth; see debug_listFailpoints)."""
        from .. import fault

        fault.set_failpoint(name, spec or None)
        return fault.list_armed()

    def listFailpoints(self) -> dict:
        """debug_listFailpoints: every registered failpoint site with its
        description, plus the currently-armed specs and fire counts."""
        from .. import fault

        return {"registered": fault.registered(),
                "armed": fault.list_armed()}

    def deviceStatus(self) -> dict:
        """debug_deviceStatus: the degradation ladder's current state
        (healthy/demoted/probation), last error, and knobs."""
        from ..ops import device

        status = device.default_ladder().status()
        status["resolve_error"] = device.resolution_error()
        return status

    def flightEvents(self, n: Optional[int] = None,
                     kind: Optional[str] = None) -> list:
        """debug_flightEvents: out-of-band lifecycle events from the
        flight recorder (device demotions/re-promotions, mirror
        takeovers/quarantines, torn-tail repairs), newest last."""
        return self.vm.blockchain.flight_recorder.events(n=n, kind=kind)

    def rpcStatus(self) -> dict:
        """debug_rpcStatus: live serving-overload state — lane queue
        depths/inflight, breaker state, drain status (ROBUSTNESS.md
        "Serving under overload")."""
        server = getattr(self.vm, "rpc_server", None)
        if server is None:
            return {"pooled": False}
        return server.serving_status()

    def traceRequest(self, trace_id: Optional[str] = None,
                     n: Optional[int] = None) -> object:
        """debug_traceRequest: span tree + admission/deadline/lane
        metadata for one captured trace id — or, with no id, the last N
        captured traces (newest last). The capture ring holds only
        interesting traces: sheds, deadline expiries, abandoned handlers,
        failed inserts, and completions slower than the SLO budget."""
        from ..metrics import tracectx

        if trace_id is None:
            return tracectx.ring.last(16 if n is None else int(n))
        rec = tracectx.ring.get(str(trace_id))
        if rec is None:
            raise RPCError(
                -32000,
                f"trace {trace_id} not captured (completed under budget, "
                "tracing disabled, or evicted from the ring)")
        return rec

    def sloStatus(self) -> dict:
        """debug_sloStatus: per-method latency percentiles from the
        slo/* histograms vs the configured budgets — the live view of
        the exposition's SLO families."""
        from ..metrics import default_registry

        server = getattr(self.vm, "rpc_server", None)
        policy = getattr(server, "policy", None)
        chain = getattr(self.vm, "blockchain", None)
        cache_cfg = getattr(chain, "cache_config", None)
        series = {}
        for name, m in default_registry.each():
            if not name.startswith("slo/") or not hasattr(m, "percentile"):
                continue
            p50, p90, p99 = m.percentiles((0.50, 0.90, 0.99))
            series[name] = {
                "count": m.count(), "p50": p50, "p90": p90, "p99": p99,
            }
        return {
            "rpcSloBudget": getattr(policy, "slo_budget", None),
            "chainInsertSloBudget": getattr(
                cache_cfg, "insert_slo_budget", None),
            "series": series,
        }

    def syncStatus(self) -> dict:
        """debug_syncStatus: bootstrap progress — peers by ladder state
        (healthy/suspect/quarantined with scores and failure kinds),
        per-segment trie progress, and the pivot history (ROBUSTNESS.md
        "Bootstrap under Byzantine peers")."""
        sync_client = getattr(self.vm, "state_sync_client", None)
        if sync_client is None:
            return {"syncing": False}
        out = sync_client.status()
        out["syncing"] = True
        return out


class DebugCommitmentAPI:
    """Commitment-backend surface of the debug namespace (COMMITMENT.md):
    both backends answer proofs through this one API — MPT node-list
    proofs via debug_getProof, binary-Merkle compact witnesses via
    debug_stateWitness — plus the dual-root shadow's live status."""

    def __init__(self, vm, eth_api):
        self.vm = vm
        self._eth = eth_api

    def _shadow(self):
        shadow = getattr(self.vm.blockchain.state_database, "shadow", None)
        if shadow is None:
            raise RPCError(
                -32000,
                "no commitment shadow mounted (state-backend is not "
                "bintrie-shadow)")
        return shadow

    def getProof(self, address: str, storage_keys: list,
                 block: str = "latest") -> dict:
        """debug_getProof: eth_getProof-shaped MPT account/storage proof
        (same marshalling, served under the debug gate so proof triage
        works even on nodes that trim the eth namespace)."""
        return self._eth.getProof(address, storage_keys, block)

    def stateWitness(self, address: str, block: str = "latest") -> dict:
        """debug_stateWitness: compact binary-Merkle witness for
        [address]'s account leaf against the shadow bintrie root of
        [block]'s state. The blob is self-contained: verify_witness
        (bintrie/witness.py) checks it against `bintrieRoot` with no
        store access, and absorbing the witnesses a block touches
        rebuilds enough tree to re-execute it statelessly."""
        from ..bintrie.witness import prove as bintrie_prove
        from ..eth.api import parse_addr
        from ..native import keccak256

        shadow = self._shadow()
        blk = self.vm.eth_backend.block_by_tag(block)
        if blk is None:
            raise RPCError(-32000, "block not found")
        broot = shadow.root_for(blk.root)
        if broot is None:
            raise RPCError(
                -32000,
                f"shadow has no bintrie root for state {blk.root.hex()} "
                "(commit predates the shadow, or it is quarantined)")
        addr = parse_addr(address)
        witness = bintrie_prove(shadow.store, broot, keccak256(addr))
        return {
            "address": hb(addr),
            "stateRoot": hb(blk.root),
            "bintrieRoot": hb(broot),
            "witness": hb(witness),
        }

    def commitmentStatus(self) -> dict:
        """debug_commitmentStatus: which backend is mounted and, in
        shadow mode, the shadow's commit/quarantine state and per-backend
        commit-timer totals (the dual-commit overhead, live)."""
        from ..metrics import default_registry

        shadow = getattr(self.vm.blockchain.state_database, "shadow", None)
        out = {
            "backend": self.vm.blockchain.cache_config.state_backend,
            "shadow": shadow.status() if shadow is not None else None,
        }
        timers = {}
        for name in ("chain/commit/mpt", "chain/commit/bintrie"):
            t = default_registry.timer(name)
            timers[name] = {"count": t.count(), "total": t.total()}
        out["commitTimers"] = timers
        return out


def create_handlers(vm, allow_unfinalized_queries: bool = False) -> RPCServer:
    """CreateHandlers (vm.go:1138): the full RPC surface on one server,
    namespace-gated by the eth-apis config list (config.go eth-apis,
    vm.go:1140) plus the admin/health enable flags."""
    cfg = getattr(vm, "full_config", None)
    apis = set(cfg.eth_apis) if cfg is not None else set(DEFAULT_ETH_APIS)
    allow_unfinalized = allow_unfinalized_queries or (
        cfg.allow_unfinalized_queries if cfg is not None else False)

    backend = EthBackend(vm.blockchain, vm.txpool, allow_unfinalized,
                         keystore=getattr(vm, "keystore", None),
                         external_signer=getattr(vm, "external_signer",
                                                 None),
                         api_max_blocks=(cfg.api_max_blocks_per_request
                                         if cfg is not None else 0),
                         gasprice_cache_size=(cfg.gasprice_cache_size
                                              if cfg is not None else 8),
                         logs_cache_size=(cfg.logs_cache_size
                                          if cfg is not None else 64))
    vm.eth_backend = backend
    server = RPCServer(
        policy=ServingPolicy.from_config(cfg if cfg is not None
                                         else Config()))
    vm.rpc_server = server
    eth = EthAPI(backend)
    if apis & {"eth", "internal-eth", "internal-blockchain",
               "internal-transaction"}:
        server.register_api("eth", eth)
        if not apis & {"personal", "internal-account"}:
            # account-signing methods ride the internal-account gate in
            # the reference (off by default); plain eth-apis keep the
            # read/submit surface only
            for m in ("accounts", "sign", "signTransaction",
                      "sendTransaction"):
                server.unregister("eth", m)
    if "eth-filter" in apis:
        filters_api = FiltersAPI(backend)
        server.register("eth", "newFilter", filters_api.newFilter)
        server.register("eth", "newBlockFilter", filters_api.newBlockFilter)
        server.register("eth", "newPendingTransactionFilter",
                        filters_api.newPendingTransactionFilter)
        server.register("eth", "uninstallFilter", filters_api.uninstallFilter)
        server.register("eth", "getFilterChanges",
                        filters_api.getFilterChanges)
    if apis & {"personal", "internal-account", "internal-personal"}:
        server.register_api("personal", PersonalAPI(backend))
    if apis & {"debug", "internal-debug", "debug-tracer"}:
        server.register_api("debug", DebugAPI(backend))
        server.register_api("debug", DebugMetricsAPI(vm))
        server.register_api("debug", DebugCommitmentAPI(vm, eth))
    if apis & {"txpool", "internal-tx-pool"}:
        server.register_api("txpool", TxPoolAPI(backend))
    if "net" in apis:
        server.register_api("net", NetAPI(vm.network_id))
    if "web3" in apis:
        server.register_api("web3", Web3API())
    # the avax handler is its own endpoint in the reference (vm.go:1160),
    # not gated by eth-apis
    avax_api = AvaxAPI(vm)
    server.register_api("avax", avax_api)
    # "import" is a python keyword; the wire name must match
    # service.go's avax.import
    server.register("avax", "import", avax_api._import_impl)
    if cfg is None or cfg.admin_api_enabled or cfg.coreth_admin_api_enabled:
        server.register_api("admin", AdminAPI(vm))
    if cfg is None or cfg.health_api_enabled:
        server.register("health", "check", lambda: health_check(vm))

    # eth_subscribe kinds (WS push; filter_system.go subscription feeds +
    # vm.go:1178-1186 WS handler registration)
    def new_heads_factory(notify):
        return backend.filters.subscribe_push(
            "newHeads", None, lambda blk: notify(eth._marshal_block(blk, False))
        )

    def logs_factory(notify, crit=None):
        return backend.filters.subscribe_push(
            "logs", crit or {}, lambda l: notify(eth._marshal_log(l, 0))
        )

    def pending_factory(notify):
        return backend.filters.subscribe_push(
            "newPendingTransactions", None, lambda h: notify(hb(h))
        )

    server.register_subscription("eth", "newHeads", new_heads_factory)
    server.register_subscription("eth", "logs", logs_factory)
    server.register_subscription("eth", "newPendingTransactions",
                                 pending_factory)
    return server


def serve_ws(vm, host: str = "127.0.0.1", port: int = 0,
             rpc_server: Optional[RPCServer] = None):
    """WS endpoint over the VM's RPC surface (vm.go:1178-1186: the /ws
    handler with per-connection CPU limits from config). Returns
    (WSServer, bound_port).

    Pass the node's existing RPCServer (from create_handlers) to share
    ONE backend/filter system between HTTP and WS — building a second
    stack would double per-block filter work and split filter state."""
    from ..rpc.websocket import WSServer

    server = rpc_server if rpc_server is not None else create_handlers(vm)
    cfg = vm.full_config
    body_limit = server.policy.body_limit if server.policy is not None else 0
    ws = WSServer(server, refill_rate=cfg.ws_cpu_refill_rate,
                  max_stored=cfg.ws_cpu_max_stored,
                  notify_queue_size=cfg.ws_notify_queue_size,
                  max_payload=body_limit)
    return ws, ws.serve(host, port)
