"""VM-level RPC APIs: avax namespace, admin, health (roles of
/root/reference/plugin/evm/{service,admin,health}.go).

create_handlers() assembles the full RPC surface the reference exposes at
/ext/bc/C/{rpc,avax,admin} (vm.go:1138-1186 CreateHandlers).
"""

from __future__ import annotations

from typing import Optional

from ..eth.api import EthAPI, hb, hx, parse_bytes
from ..eth.backend import EthBackend
from ..eth.tracers import DebugAPI
from ..rpc.server import RPCError, RPCServer
from .atomic_tx import Tx, decode_tx
from .vm import ATOMIC_TX_INDEX_PREFIX


class AvaxAPI:
    """avax.* handlers (service.go:89-460): issueTx/getAtomicTx/getUTXOs."""

    def __init__(self, vm):
        self.vm = vm

    def issueTx(self, tx_bytes: str) -> dict:
        tx = decode_tx(parse_bytes(tx_bytes))
        self.vm.issue_atomic_tx(tx)
        return {"txID": hb(tx.id())}

    def getAtomicTxStatus(self, tx_id: str) -> dict:
        tid = parse_bytes(tx_id)
        if self.vm.mempool.has(tid):
            return {"status": "Processing"}
        blob = self.vm.blockchain.diskdb.get(ATOMIC_TX_INDEX_PREFIX + tid)
        if blob is not None:
            height = int.from_bytes(blob[:8], "big")
            return {"status": "Accepted", "blockHeight": hx(height)}
        return {"status": "Unknown"}

    def getAtomicTx(self, tx_id: str) -> dict:
        tid = parse_bytes(tx_id)
        tx = self.vm.mempool.get(tid)
        if tx is not None:
            return {"tx": hb(tx.encode()), "blockHeight": None}
        blob = self.vm.blockchain.diskdb.get(ATOMIC_TX_INDEX_PREFIX + tid)
        if blob is not None:
            return {
                "tx": hb(blob[8:]),
                "blockHeight": hx(int.from_bytes(blob[:8], "big")),
            }
        raise RPCError(-32000, "transaction not found")

    def getUTXOs(self, addresses, source_chain: str = "", limit: int = 100) -> dict:
        """UTXOs owned by [addresses] in this chain's inbound namespace."""
        if isinstance(addresses, str):
            addresses = [addresses]
        addrs = [parse_bytes(a) for a in addresses]
        source = parse_bytes(source_chain) if source_chain else self.vm.ctx.x_chain_id
        utxos, _, last = self.vm.shared_memory.indexed(
            source, addrs, limit=limit
        )
        return {
            "numFetched": hx(len(utxos)),
            "utxos": [hb(u) for u in utxos],
            "endIndex": hb(last) if last else None,
        }

    def version(self) -> dict:
        return {"version": "coreth-tpu/0.1.0"}


class AdminAPI:
    """coreth-admin (admin.go:29-62)."""

    def __init__(self, vm):
        self.vm = vm
        self.log_level = "info"

    def setLogLevel(self, level: str) -> bool:
        self.log_level = level
        return True

    def lockProfile(self) -> bool:
        return True  # profiling hooks are host-side no-ops here

    def memoryProfile(self) -> bool:
        return True

    def startCPUProfiler(self) -> bool:
        return True

    def stopCPUProfiler(self) -> bool:
        return True


class TxPoolAPI:
    """txpool namespace (internal/ethapi TxPoolAPI)."""

    def __init__(self, backend):
        self.b = backend

    def status(self) -> dict:
        pending, queued = self.b.txpool.stats()
        return {"pending": hx(pending), "queued": hx(queued)}

    def content(self) -> dict:
        out = {"pending": {}, "queued": {}}
        for addr, txs in self.b.txpool.pending_txs().items():
            out["pending"][hb(addr)] = {
                str(t.nonce): hb(t.hash()) for t in txs
            }
        return out


class NetAPI:
    def __init__(self, network_id: int):
        self._id = network_id

    def version(self) -> str:
        return str(self._id)

    def listening(self) -> bool:
        return True

    def peerCount(self) -> str:
        return hx(0)


class Web3API:
    def clientVersion(self) -> str:
        return "coreth-tpu/0.1.0"

    def sha3(self, data: str) -> str:
        from ..native import keccak256

        return hb(keccak256(parse_bytes(data)))


class FiltersAPI:
    """eth_newFilter family bridged onto the FilterSystem."""

    def __init__(self, backend):
        self.b = backend

    def newFilter(self, crit: dict) -> str:
        return self.b.filters.new_log_filter(crit)

    def newBlockFilter(self) -> str:
        return self.b.filters.new_block_filter()

    def newPendingTransactionFilter(self) -> str:
        return self.b.filters.new_pending_tx_filter()

    def uninstallFilter(self, fid: str) -> bool:
        return self.b.filters.uninstall(fid)

    def getFilterChanges(self, fid: str) -> list:
        items = self.b.filters.get_changes(fid)
        out = []
        api = EthAPI(self.b)
        for item in items:
            if isinstance(item, bytes):
                out.append(hb(item))
            else:
                out.append(api._marshal_log(item, 0))
        return out


def health_check(vm) -> dict:
    """health.go: the VM is healthy when the acceptor is alive."""
    healthy = vm.blockchain.acceptor_error is None
    return {
        "healthy": healthy,
        "lastAcceptedHeight": vm.blockchain.last_accepted.number,
        "error": vm.blockchain.acceptor_error,
    }


def create_handlers(vm, allow_unfinalized_queries: bool = False) -> RPCServer:
    """CreateHandlers (vm.go:1138): the full RPC surface on one server."""
    backend = EthBackend(vm.blockchain, vm.txpool, allow_unfinalized_queries)
    vm.eth_backend = backend
    server = RPCServer()
    eth = EthAPI(backend)
    server.register_api("eth", eth)
    filters_api = FiltersAPI(backend)
    server.register("eth", "newFilter", filters_api.newFilter)
    server.register("eth", "newBlockFilter", filters_api.newBlockFilter)
    server.register("eth", "newPendingTransactionFilter",
                    filters_api.newPendingTransactionFilter)
    server.register("eth", "uninstallFilter", filters_api.uninstallFilter)
    server.register("eth", "getFilterChanges", filters_api.getFilterChanges)
    server.register_api("debug", DebugAPI(backend))
    server.register_api("txpool", TxPoolAPI(backend))
    server.register_api("net", NetAPI(vm.network_id))
    server.register_api("web3", Web3API())
    server.register_api("avax", AvaxAPI(vm))
    server.register_api("admin", AdminAPI(vm))
    server.register("health", "check", lambda: health_check(vm))
    return server
