"""Atomic transactions: the UTXO ↔ EVM-account bridge (role of
/root/reference/plugin/evm/{tx,import_tx,export_tx,codec}.go).

ImportTx consumes UTXOs from a peer chain's shared memory and credits EVM
accounts; ExportTx debits EVM accounts (nonce-checked EVMInputs) and
produces UTXOs for the peer chain. Fees follow the reference's dynamic
model: gasUsed = bytes + per-signature cost (+10k fixed post-AP5), burned
AVAX (nAVAX, 9 decimals) must cover gasUsed*baseFee/1e9 (tx.go:150-259).

Serialization is a versioned RLP envelope (this framework's linear codec);
credentials are 65-byte recoverable secp256k1 signatures over the keccak
of the unsigned bytes, recovered to addresses like secp256k1fx.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .. import params, rlp
from ..crypto.secp256k1 import recover_address, sign
from ..native import keccak256
from .shared_memory import Element, Requests

CODEC_VERSION = 0
TX_BYTES_GAS = 1
# EVMOutput: 20B addr + 8B amount + 32B assetID; EVMInput adds 8B nonce + sig
EVM_OUTPUT_GAS = (20 + 8 + 32) * TX_BYTES_GAS
EVM_INPUT_GAS = (20 + 8 + 32 + 8) * TX_BYTES_GAS + 1000  # + per-sig cost
X2C_RATE = 10**9  # nAVAX (9 decimals) -> wei (18 decimals)

TYPE_IMPORT = 0
TYPE_EXPORT = 1


class AtomicTxError(Exception):
    pass


# params.AvalancheAtomicTxFee: the AP2 fixed atomic tx fee (1 milliAVAX)
AVALANCHE_ATOMIC_TX_FEE = 1_000_000  # nAVAX


def _flow_check(consumed: Dict[bytes, int], produced: Dict[bytes, int]) -> None:
    """avax.FlowChecker: every asset must consume >= produce (incl. fee)."""
    for asset, amount in produced.items():
        if consumed.get(asset, 0) < amount:
            raise AtomicTxError(
                f"flow check failed: asset {asset.hex()[:8]} consumes "
                f"{consumed.get(asset, 0)} < produces {amount}"
            )


def _required_fee(rules, tx: "Tx", base_fee: Optional[int]) -> int:
    """Per-fork atomic fee (import_tx.go:192-210): dynamic from AP3,
    fixed 1 mAVAX from AP2, free before."""
    if rules.is_apricot_phase3:
        if base_fee is None:
            raise AtomicTxError("base fee required post-AP3")
        return calculate_dynamic_fee(tx.gas_used(rules.is_apricot_phase5), base_fee)
    if rules.is_apricot_phase2:
        return AVALANCHE_ATOMIC_TX_FEE
    return 0


@dataclass
class UTXO:
    tx_id: bytes          # 32B source tx
    output_index: int
    asset_id: bytes       # 32B
    amount: int           # nAVAX
    address: bytes        # 20B owner (single-sig secp owner)
    locktime: int = 0
    threshold: int = 1

    def utxo_id(self) -> bytes:
        return keccak256(self.tx_id + self.output_index.to_bytes(4, "big"))

    def encode(self) -> bytes:
        return rlp.encode([
            self.tx_id, self.output_index, self.asset_id, self.amount,
            self.address, self.locktime, self.threshold,
        ])

    @classmethod
    def decode(cls, blob: bytes) -> "UTXO":
        i = rlp.decode(blob)
        return cls(i[0], _u(i[1]), i[2], _u(i[3]), i[4], _u(i[5]), _u(i[6]))


def _u(b) -> int:
    return int.from_bytes(b, "big") if isinstance(b, bytes) else b


@dataclass
class EVMInput:
    """Debit from an EVM account (export source) — nonce-checked."""

    address: bytes
    amount: int          # nAVAX
    asset_id: bytes
    nonce: int

    def items(self):
        return [self.address, self.amount, self.asset_id, self.nonce]


@dataclass
class EVMOutput:
    """Credit to an EVM account (import destination)."""

    address: bytes
    amount: int          # nAVAX
    asset_id: bytes

    def items(self):
        return [self.address, self.amount, self.asset_id]


@dataclass
class ImportTx:
    network_id: int
    blockchain_id: bytes
    source_chain: bytes
    imported_inputs: List[UTXO] = field(default_factory=list)
    outs: List[EVMOutput] = field(default_factory=list)

    type_id = TYPE_IMPORT

    def unsigned_items(self):
        return [
            TYPE_IMPORT, self.network_id, self.blockchain_id, self.source_chain,
            [u.encode() for u in self.imported_inputs],
            [o.items() for o in self.outs],
        ]

    def input_utxos(self) -> List[bytes]:
        return [u.utxo_id() for u in self.imported_inputs]

    def burned(self, asset_id: bytes) -> int:
        consumed = sum(u.amount for u in self.imported_inputs if u.asset_id == asset_id)
        produced = sum(o.amount for o in self.outs if o.asset_id == asset_id)
        return consumed - produced

    def gas_used(self, fixed_fee: bool, byte_len: int, n_sigs: int) -> int:
        gas = byte_len * TX_BYTES_GAS + n_sigs * 1000
        if fixed_fee:
            gas += params.ATOMIC_TX_BASE_COST
        return gas

    # --- verify + state transfer (import_tx.go:181-460) -------------------

    def verify(self, vm) -> None:
        if self.network_id != vm.network_id:
            raise AtomicTxError(
                f"wrong network id {self.network_id} != {vm.network_id}"
            )
        if self.blockchain_id != vm.chain_id_bytes:
            raise AtomicTxError("wrong blockchain id")
        if self.source_chain == vm.chain_id_bytes:
            raise AtomicTxError("cannot import from self")
        if not self.imported_inputs:
            raise AtomicTxError("import has no inputs")
        if any(o.amount == 0 for o in self.outs):
            raise AtomicTxError("zero-value output")
        ids = [u.utxo_id() for u in self.imported_inputs]
        if len(set(ids)) != len(ids):
            raise AtomicTxError("duplicate UTXO consumed")

    def semantic_verify(self, vm, tx: "Tx", base_fee: Optional[int]) -> None:
        self.verify(vm)
        # flow check on every fork (import_tx.go:192-220): consumed must
        # cover produced + the per-fork fee — otherwise imports mint value
        rules = vm.current_rules()
        consumed: Dict[bytes, int] = {}
        produced: Dict[bytes, int] = {}
        for u in self.imported_inputs:
            consumed[u.asset_id] = consumed.get(u.asset_id, 0) + u.amount
        for o in self.outs:
            produced[o.asset_id] = produced.get(o.asset_id, 0) + o.amount
        produced[vm.avax_asset_id] = (
            produced.get(vm.avax_asset_id, 0) + _required_fee(rules, tx, base_fee)
        )
        _flow_check(consumed, produced)
        # UTXOs must exist in shared memory with matching owners + sigs
        utxo_bytes = vm.shared_memory.get(self.source_chain, self.input_utxos())
        for i, (u, stored) in enumerate(zip(self.imported_inputs, utxo_bytes)):
            stored_utxo = UTXO.decode(stored)
            if stored_utxo.amount != u.amount or stored_utxo.asset_id != u.asset_id:
                raise AtomicTxError("UTXO mismatch vs shared memory")
            signer = tx.credential_address(i)
            if signer != stored_utxo.address:
                raise AtomicTxError("invalid UTXO signature")

    def evm_state_transfer(self, vm, state) -> None:
        """Credit outputs (import_tx.go:434): AVAX in wei, others multicoin."""
        for out in self.outs:
            if out.asset_id == vm.avax_asset_id:
                state.add_balance(out.address, out.amount * X2C_RATE)
            else:
                state.add_balance_multicoin(out.address, out.asset_id, out.amount)

    def atomic_ops(self) -> Tuple[bytes, Requests]:
        """Consume the imported UTXOs from [source_chain]'s namespace."""
        return self.source_chain, Requests(remove_requests=self.input_utxos())


@dataclass
class ExportTx:
    network_id: int
    blockchain_id: bytes
    destination_chain: bytes
    ins: List[EVMInput] = field(default_factory=list)
    exported_outputs: List[UTXO] = field(default_factory=list)

    type_id = TYPE_EXPORT

    def unsigned_items(self):
        return [
            TYPE_EXPORT, self.network_id, self.blockchain_id, self.destination_chain,
            [i.items() for i in self.ins],
            [u.encode() for u in self.exported_outputs],
        ]

    def input_utxos(self) -> List[bytes]:
        return []

    def burned(self, asset_id: bytes) -> int:
        consumed = sum(i.amount for i in self.ins if i.asset_id == asset_id)
        produced = sum(
            u.amount for u in self.exported_outputs if u.asset_id == asset_id
        )
        return consumed - produced

    def gas_used(self, fixed_fee: bool, byte_len: int, n_sigs: int) -> int:
        gas = byte_len * TX_BYTES_GAS + n_sigs * 1000
        if fixed_fee:
            gas += params.ATOMIC_TX_BASE_COST
        return gas

    def verify(self, vm) -> None:
        if self.network_id != vm.network_id:
            raise AtomicTxError(
                f"wrong network id {self.network_id} != {vm.network_id}"
            )
        if self.blockchain_id != vm.chain_id_bytes:
            raise AtomicTxError("wrong blockchain id")
        if self.destination_chain == vm.chain_id_bytes:
            raise AtomicTxError("cannot export to self")
        if not self.ins:
            raise AtomicTxError("export has no inputs")
        if any(u.amount == 0 for u in self.exported_outputs):
            raise AtomicTxError("zero-value exported output")

    def semantic_verify(self, vm, tx: "Tx", base_fee: Optional[int]) -> None:
        self.verify(vm)
        # flow check on every fork (export_tx.go SemanticVerify)
        rules = vm.current_rules()
        consumed: Dict[bytes, int] = {}
        produced: Dict[bytes, int] = {}
        for i in self.ins:
            consumed[i.asset_id] = consumed.get(i.asset_id, 0) + i.amount
        for u in self.exported_outputs:
            produced[u.asset_id] = produced.get(u.asset_id, 0) + u.amount
        produced[vm.avax_asset_id] = (
            produced.get(vm.avax_asset_id, 0) + _required_fee(rules, tx, base_fee)
        )
        _flow_check(consumed, produced)
        # each input must be signed by its account holder
        for i, inp in enumerate(self.ins):
            if tx.credential_address(i) != inp.address:
                raise AtomicTxError("export input signature mismatch")

    def evm_state_transfer(self, vm, state) -> None:
        """Debit inputs with nonce check (export_tx.go:372-401). Multiple
        inputs from one address carry the SAME nonce (e.g. asset + AVAX
        fee); the nonce bumps once per address after all checks, exactly
        as the reference's addrs map does (export_tx.go:393-400)."""
        addr_nonce: Dict[bytes, int] = {}
        for inp in self.ins:
            if inp.asset_id == vm.avax_asset_id:
                amount_wei = inp.amount * X2C_RATE
                if state.get_balance(inp.address) < amount_wei:
                    raise AtomicTxError("insufficient balance for export")
                state.sub_balance(inp.address, amount_wei)
            else:
                if state.get_balance_multicoin(inp.address, inp.asset_id) < inp.amount:
                    raise AtomicTxError("insufficient multicoin balance for export")
                state.sub_balance_multicoin(inp.address, inp.asset_id, inp.amount)
            if state.get_nonce(inp.address) != inp.nonce:
                raise AtomicTxError(
                    f"invalid export nonce: state {state.get_nonce(inp.address)} != tx {inp.nonce}"
                )
            addr_nonce[inp.address] = inp.nonce
        for addr, nonce in addr_nonce.items():
            state.set_nonce(addr, nonce + 1)

    def atomic_ops(self) -> Tuple[bytes, Requests]:
        """Produce UTXOs into [destination_chain]'s namespace."""
        puts = [
            Element(
                key=u.utxo_id(),
                value=u.encode(),
                traits=[u.address],
            )
            for u in self.exported_outputs
        ]
        return self.destination_chain, Requests(put_requests=puts)


class Tx:
    """Signed atomic tx envelope (tx.go Tx: UnsignedAtomicTx + Creds)."""

    def __init__(self, unsigned, creds: Optional[List[bytes]] = None):
        self.unsigned = unsigned
        self.creds: List[bytes] = creds or []  # 65-byte r||s||v signatures
        self._unsigned_bytes: Optional[bytes] = None
        self._signed_bytes: Optional[bytes] = None

    def unsigned_bytes(self) -> bytes:
        if self._unsigned_bytes is None:
            self._unsigned_bytes = rlp.encode(
                [CODEC_VERSION] + self.unsigned.unsigned_items()
            )
        return self._unsigned_bytes

    def encode(self) -> bytes:
        if self._signed_bytes is None:
            self._signed_bytes = rlp.encode(
                [CODEC_VERSION] + self.unsigned.unsigned_items() + [list(self.creds)]
            )
        return self._signed_bytes

    def id(self) -> bytes:
        return keccak256(self.encode())

    def sign(self, keys: List[bytes]) -> None:
        """One recoverable signature per input, over keccak(unsigned)."""
        h = keccak256(self.unsigned_bytes())
        self.creds = []
        for key in keys:
            v, r, s = sign(h, key)
            self.creds.append(r.to_bytes(32, "big") + s.to_bytes(32, "big") + bytes([v]))
        self._signed_bytes = None

    def credential_address(self, i: int) -> Optional[bytes]:
        if i >= len(self.creds):
            raise AtomicTxError("missing credential")
        sig = self.creds[i]
        h = keccak256(self.unsigned_bytes())
        return recover_address(
            h, sig[64], int.from_bytes(sig[:32], "big"), int.from_bytes(sig[32:64], "big")
        )

    def gas_used(self, fixed_fee: bool) -> int:
        return self.unsigned.gas_used(
            fixed_fee, len(self.encode()), len(self.creds)
        )

    def burned(self, asset_id: bytes) -> int:
        return self.unsigned.burned(asset_id)

    def block_fee_contribution(self, fixed_fee: bool, avax_asset_id: bytes,
                               base_fee: int) -> Tuple[int, int]:
        """(contribution in wei, gasUsed) — tx.go:185-215."""
        if base_fee is None or base_fee <= 0:
            raise AtomicTxError(f"invalid base fee {base_fee}")
        gas = self.gas_used(fixed_fee)
        fee = calculate_dynamic_fee(gas, base_fee)
        burned = self.burned(avax_asset_id)
        if fee > burned:
            raise AtomicTxError(f"insufficient AVAX burned ({burned}) to cover fee ({fee})")
        return (burned - fee) * X2C_RATE, gas

    def semantic_verify(self, vm, base_fee) -> None:
        self.unsigned.semantic_verify(vm, self, base_fee)

    def evm_state_transfer(self, vm, state) -> None:
        self.unsigned.evm_state_transfer(vm, state)

    def atomic_ops(self) -> Tuple[bytes, Requests]:
        return self.unsigned.atomic_ops()

    def input_utxos(self) -> List[bytes]:
        return self.unsigned.input_utxos()


def calculate_dynamic_fee(gas: int, base_fee: int) -> int:
    """CalculateDynamicFee (tx.go:243-257): wei fee → nAVAX, rounded up."""
    return (gas * base_fee + X2C_RATE - 1) // X2C_RATE


# --- codec ----------------------------------------------------------------


def decode_tx(blob: bytes) -> Tx:
    items = rlp.decode(blob)
    version = _u(items[0])
    if version != CODEC_VERSION:
        raise AtomicTxError(f"unknown codec version {version}")
    type_id = _u(items[1])
    if type_id == TYPE_IMPORT:
        unsigned = ImportTx(
            network_id=_u(items[2]),
            blockchain_id=items[3],
            source_chain=items[4],
            imported_inputs=[UTXO.decode(u) for u in items[5]],
            outs=[EVMOutput(o[0], _u(o[1]), o[2]) for o in items[6]],
        )
    elif type_id == TYPE_EXPORT:
        unsigned = ExportTx(
            network_id=_u(items[2]),
            blockchain_id=items[3],
            destination_chain=items[4],
            ins=[EVMInput(i[0], _u(i[1]), i[2], _u(i[3])) for i in items[5]],
            exported_outputs=[UTXO.decode(u) for u in items[6]],
        )
    else:
        raise AtomicTxError(f"unknown atomic tx type {type_id}")
    creds = [bytes(c) for c in items[7]] if len(items) > 7 else []
    return Tx(unsigned, creds)


def extract_atomic_txs(ext_data: bytes, batch: bool, codec=None) -> List[Tx]:
    """ExtractAtomicTxs (plugin/evm/tx.go): pre-AP5 blocks carry ONE atomic
    tx in ExtData; AP5+ carries an RLP list of them."""
    if not ext_data:
        return []
    if batch:
        return [decode_tx(rlp.encode(i) if isinstance(i, list) else i)
                for i in rlp.decode(ext_data)]
    return [decode_tx(ext_data)]


def encode_atomic_txs(txs: List[Tx], batch: bool) -> bytes:
    if not txs:
        return b""
    if batch:
        return rlp.encode([t.encode() for t in txs])
    assert len(txs) == 1
    return txs[0].encode()
