"""The snowman ChainVM (role of /root/reference/plugin/evm/vm.go).

Initialize wires config → databases → genesis/fork config → chain backend
→ mempools → atomic state (vm.go:315-549); buildBlock assembles through
the miner + atomic mempool (:991-1032); parseBlock/getBlock/SetPreference
serve the consensus engine (:1034-1096). Atomic txs flow through the
ConsensusCallbacks into block bodies (vm.go:696-851).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .. import params, rlp
from ..consensus.dummy import ConsensusCallbacks, DummyEngine
from ..core.blockchain import BlockChain, CacheConfig
from ..core.genesis import Genesis
from ..core.txpool import TxPool, TxPoolConfig
from ..core.types import Block as EthBlock
from ..miner.worker import Worker
from ..state.database import Database
from ..trie.triedb import TrieDatabase
from .atomic_tx import (
    Tx,
    calculate_dynamic_fee,
    decode_tx,
    encode_atomic_txs,
    extract_atomic_txs,
)
from .block import BlockStatus, VMBlock
from .mempool import Mempool
from .shared_memory import Requests

AVAX_ASSET_ID = b"\x41" * 32  # test default; ctx overrides

# accepted-atomic-tx index (atomic_tx_repository.go role). "Atx" cannot
# collide with snapshot (b"a"/b"o"), header/body (b"h"/b"b"), code (b"c"),
# or 32-byte trie-node keys.
ATOMIC_TX_INDEX_PREFIX = b"Atx"


@dataclass
class VMConfig:
    """plugin/evm/config.go subset — the knobs the runtime honors now."""

    pruning: bool = True
    commit_interval: int = 4096
    mempool_size: int = 4096
    clock: Optional[object] = None
    # flat snapshot tree (config.go snapshot-cache; 0 disables). The VM
    # serves sync leaves from it when enabled (leafs_request fast path).
    snapshot_limit: int = 256
    # "auto"/"batched": drain large dirty sets to the device keccak from
    # Trie.hash (trie/trie.go:618-619 parallel-threshold analog); "off": CPU
    device_hasher: str = "auto"
    # device-resident account trie (CacheConfig.resident_account_trie):
    # per-block account hashing as one resident commit on the mirror.
    # "auto": ON when a TPU backend resolves (production default)
    resident_account_trie: "bool | str" = "auto"
    # watchdog (s) per resident device commit; expiry -> host takeover
    resident_commit_timeout: "float | None" = 180.0
    # resident mirror host preference ("auto": host commits whenever no
    # TPU backend resolves; True/False force)
    resident_prefer_host: "bool | str" = "auto"
    # native CPU hasher worker threads; 0 = auto
    cpu_threads: int = 0


@dataclass
class SnowContext:
    """snow.Context subset the VM needs (ids + shared memory)."""

    network_id: int = 1337
    chain_id: bytes = b"\x02" * 32          # this blockchain's avalanche ID
    x_chain_id: bytes = b"\x58" * 32
    avax_asset_id: bytes = AVAX_ASSET_ID
    shared_memory: object = None


class VMError(Exception):
    pass


class VM:
    def __init__(self):
        self.initialized = False

    # --- snowman ChainVM: Initialize (vm.go:315-549) ----------------------

    def initialize(
        self,
        ctx: SnowContext,
        diskdb,
        genesis: Genesis,
        config: VMConfig = None,
        to_engine=None,
        config_bytes: bytes = b"",
    ) -> None:
        self.ctx = ctx
        if config is None and config_bytes:
            # JSON blob from the node (vm.go:326-334) → runtime knobs
            from .config import parse_config

            full = parse_config(config_bytes)
            self.full_config = full
            config = VMConfig(
                pruning=full.pruning_enabled,
                commit_interval=full.commit_interval,
                mempool_size=full.tx_pool_global_slots,
                device_hasher=full.device_hasher,
                resident_account_trie=full.resident_account_trie,
                # pass 0 through untouched: the mirror reads it as
                # "explicitly disabled" — collapsing it to None would
                # re-open the env-var override the operator turned off
                resident_commit_timeout=full.resident_commit_timeout,
                resident_prefer_host=full.resident_prefer_host,
                cpu_threads=full.cpu_threads,
            )
        else:
            from .config import Config as FullConfig

            self.full_config = FullConfig()
        self.config = config or VMConfig()
        self.chain_config = genesis.config
        self.network_id = ctx.network_id
        self.chain_id_bytes = ctx.chain_id
        self.avax_asset_id = ctx.avax_asset_id
        self.shared_memory = (
            ctx.shared_memory.new_shared_memory(ctx.chain_id)
            if hasattr(ctx.shared_memory, "new_shared_memory")
            else ctx.shared_memory
        )
        self.atomic_codec = None
        self.to_engine = to_engine  # callable: notify engine txs are ready

        # honor global observability knobs (vm.go:344-353 log config;
        # metrics.EnabledExpensive gate) — ONLY when the blob set them:
        # these are process-global, and a second VM in the same process
        # must not silently reset the first one's diagnostics
        explicit = getattr(self.full_config, "explicit_keys", set())
        if "log_level" in explicit:
            from .. import log as _log

            _log.set_level(self.full_config.log_level)
        if "metrics_expensive_enabled" in explicit:
            from .. import metrics as _metrics

            _metrics.enabled_expensive = (
                self.full_config.metrics_expensive_enabled)
        if "evm_fastloop" in explicit:
            from ..evm import interpreter as _interp

            _interp.FASTLOOP_DEFAULT = bool(self.full_config.evm_fastloop)
        if "spans_enabled" in explicit:
            from ..metrics import spans as _spans

            _spans.set_enabled(self.full_config.spans_enabled)
        if "span_ring_size" in explicit:
            from ..metrics import spans as _spans

            _spans.tracer.set_capacity(self.full_config.span_ring_size)
        if "tracing_enabled" in explicit:
            from ..metrics import tracectx as _tracectx

            _tracectx.set_enabled(self.full_config.tracing_enabled)
        if "trace_ring_size" in explicit:
            from ..metrics import tracectx as _tracectx

            _tracectx.ring.set_capacity(self.full_config.trace_ring_size)
        if "lock_slow_hold_budget" in explicit:
            from ..utils import racecheck as _racecheck

            _racecheck.set_slow_hold_budget(
                self.full_config.lock_slow_hold_budget)
        if "shard_telemetry_enabled" in explicit:
            from ..core import exec_shards as _exec_shards

            _exec_shards.set_telemetry_enabled(
                self.full_config.shard_telemetry_enabled)

        # node keystore (node/ keystore dir role; backs avax.importKey/
        # exportKey/import/export and the eth/personal signing RPC)
        ks_dir = getattr(self.full_config, "keystore_directory", "")
        if ks_dir:
            from ..accounts.keystore import KeyStore

            self.keystore = KeyStore(ks_dir)
        else:
            self.keystore = None
        # external (clef-style) signer daemon (accounts/external/
        # backend.go role): its accounts merge into eth_accounts, and
        # eth_signTransaction/sendTransaction for them route over IPC
        self.external_signer = None
        ext_path = getattr(self.full_config, "keystore_external_signer", "")
        if ext_path:
            from ..accounts.external import ExternalSigner

            self.external_signer = ExternalSigner(ext_path)

        clock = self.config.clock or (lambda: self._now())

        cb = ConsensusCallbacks(
            on_finalize_and_assemble=self._on_finalize_and_assemble,
            on_extra_state_change=self._on_extra_state_change,
        )
        self.engine = DummyEngine(cb)

        from ..ops.device import get_batch_keccak

        self.state_database = Database(TrieDatabase(
            diskdb, batch_keccak=get_batch_keccak(self.config.device_hasher)
        ))
        full = self.full_config
        self.blockchain = BlockChain(
            diskdb,
            CacheConfig(
                pruning=self.config.pruning,
                commit_interval=self.config.commit_interval,
                device_hasher=self.config.device_hasher,
                resident_account_trie=self.config.resident_account_trie,
                resident_commit_timeout=self.config.resident_commit_timeout,
                resident_prefer_host=self.config.resident_prefer_host,
                cpu_threads=self.config.cpu_threads,
                snapshot_limit=self.config.snapshot_limit,
                trie_dirty_limit=full.trie_dirty_cache * 1024 * 1024,
                accepted_cache_size=full.accepted_cache_size,
                flight_recorder_size=full.flight_recorder_size,
                device_call_timeout=full.device_call_timeout,
                device_max_retries=full.device_max_retries,
                device_probe_interval=full.device_probe_interval,
                device_promote_after=full.device_promote_after,
                resident_spot_check_interval=(
                    full.resident_spot_check_interval),
                resident_pipeline_depth=full.resident_pipeline_depth,
                insert_pipeline_depth=full.insert_pipeline_depth,
                resident_template_residency=(
                    full.resident_template_residency),
                resident_mesh_devices=full.resident_mesh_devices,
                tail_join_timeout=full.tail_join_timeout,
                db_verify_on_read=full.db_verify_on_read,
                db_retry_budget=full.db_retry_budget,
                state_backend=full.state_backend,
                shadow_check_interval=full.shadow_check_interval,
                evm_parallel_workers=full.evm_parallel_workers,
                evm_exec_shards=full.evm_exec_shards,
                insert_slo_budget=full.chain_insert_slo_budget,
            ),
            self.chain_config,
            genesis,
            self.engine,
            state_database=self.state_database,
        )
        self.txpool = TxPool(
            TxPoolConfig(
                price_limit=full.tx_pool_price_limit,
                price_bump=full.tx_pool_price_bump,
                account_slots=full.tx_pool_account_slots,
                global_slots=full.tx_pool_global_slots,
                account_queue=full.tx_pool_account_queue,
                global_queue=full.tx_pool_global_queue,
            ),
            self.chain_config, self.blockchain,
        )
        self.miner = Worker(
            self.chain_config, self.engine, self.blockchain,
            tx_pool=self.txpool, clock=clock,
        )

        # fork-scheduled gas-price floors (vm.go handleGasPriceUpdates).
        # Wall clock on purpose: fork timestamps are wall times and the
        # reference schedules with time.Until — the VM's block-timestamp
        # clock override must not skew the schedule.
        from .plumbing import GasPriceUpdater

        self.gas_price_updater = GasPriceUpdater(
            self.txpool, self.chain_config)
        self.gas_price_updater.start()

        def price(tx: Tx) -> int:
            gas = max(tx.gas_used(self.current_rules().is_apricot_phase5), 1)
            return tx.burned(self.avax_asset_id) // gas

        def fits_atomic_gas(tx: Tx) -> bool:
            rules = self.current_rules()
            if not rules.is_apricot_phase5:
                return True
            return tx.gas_used(True) <= params.ATOMIC_GAS_LIMIT

        self.mempool = Mempool(
            self.config.mempool_size, fee_fn=price, max_tx_gas=fits_atomic_gas
        )

        # atomic ops index with interval commits (atomic_trie.go)
        from .atomic_trie import AtomicTrie

        self.atomic_trie = AtomicTrie(
            diskdb, self.config.commit_interval,
            batch_keccak=get_batch_keccak(self.config.device_hasher),
        )

        self._verified_blocks: Dict[bytes, VMBlock] = {}
        self._accepted_atomic_ops: List = []

        # per-verified-block pending atomic state + tx repository
        # (atomic_backend.go / atomic_tx_repository.go)
        from .atomic_backend import AtomicBackend

        self.atomic_backend = AtomicBackend(self)
        genesis_vmb = VMBlock(self, self.blockchain.genesis_block)
        genesis_vmb.status = BlockStatus.ACCEPTED
        self.last_accepted_vm_block = genesis_vmb
        self.preferred_block: VMBlock = genesis_vmb
        self._building_txs: List[Tx] = []
        self.lock = threading.RLock()
        self.initialized = True

        # notify the engine when txs arrive (block_builder.go signal)
        # build throttling (block_builder.go:55-129): one PendingTxs
        # notification per outstanding build, retry-timer recovery
        from .block_builder import BlockBuilder

        self.block_builder = BlockBuilder(self)
        self.txpool.subscribe_new_txs(lambda txs: self._signal_txs_ready())

        # archival trie-gap healing behind the config knob (vm.go startup
        # order; core/blockchain.go:1899 populateMissingTries)
        if self.full_config.populate_missing_tries is not None:
            self.blockchain.populate_missing_tries(
                self.full_config.populate_missing_tries,
                self.full_config.populate_missing_tries_parallelism,
            )

        # inbound sync server (vm.go:547 initializeStateSyncServer): leaf/
        # block/code requests served off this chain, snapshot fast path
        # engaged automatically when the chain runs one
        from ..sync.handlers import SyncHandler

        self.sync_handler = SyncHandler(
            self.blockchain, self.state_database.triedb, diskdb
        )

        # continuous profiler (vm.go:1642, config.go:89-91)
        self.continuous_profiler = None
        if self.full_config.continuous_profiler_dir:
            from .api import ContinuousProfiler

            self.continuous_profiler = ContinuousProfiler(
                self.full_config.continuous_profiler_dir,
                freq=self.full_config.continuous_profiler_frequency,
                max_files=self.full_config.continuous_profiler_max_files,
            ).start()

        # in-process sampling profiler (metrics/profiler.py): daemon
        # thread, refcounted process-global singleton — a second VM (or
        # the chaos conductor) takes a reference on the same sampler and
        # our shutdown only drops ours
        self.sampling_profiler = None
        if self.full_config.profiler_hz > 0:
            from ..metrics import profiler as _profiler

            self.sampling_profiler = _profiler.start_profiler(
                self.full_config.profiler_hz,
                ring_size=self.full_config.profiler_ring_size)

        # stdlib /metrics + /healthz endpoint (metrics/http.py), reusing
        # the health_check verdict the RPC health namespace serves
        self.metrics_http = None
        if self.full_config.metrics_http_enabled:
            from ..metrics.http import MetricsHTTPServer
            from .api import health_check

            self.metrics_http = MetricsHTTPServer(
                health_fn=lambda: health_check(self))
            self.metrics_http.start(
                host=self.full_config.metrics_http_host,
                port=self.full_config.metrics_http_port,
            )

    @staticmethod
    def _now() -> int:
        import time

        return int(time.time())

    def current_rules(self):
        head = self.blockchain.current_block
        return self.chain_config.rules(head.number + 1, head.time)

    def _signal_txs_ready(self) -> None:
        self.block_builder.signal_txs_ready()

    # --- consensus callbacks (vm.go:696-851) ------------------------------

    def _on_finalize_and_assemble(self, header, state, txs):  # guarded-by: lock
        """Pull atomic txs from the mempool into the block being built."""
        rules = self.chain_config.rules(header.number, header.time)
        batch = rules.is_apricot_phase5
        picked: List[Tx] = []
        contribution = 0
        ext_gas_used = 0
        while True:
            tx = self.mempool.next_tx()
            if tx is None:
                break
            inner_snap = state.snapshot()
            try:
                tx.semantic_verify(self, header.base_fee)
                tx.evm_state_transfer(self, state)
            except Exception:
                from ..metrics import count_drop

                count_drop("vm/build/atomic_tx_invalid")
                state.revert_to_snapshot(inner_snap)
                self.mempool.remove_tx(tx)
                continue
            if rules.is_apricot_phase4:
                try:
                    contrib, gas = tx.block_fee_contribution(
                        rules.is_apricot_phase5, self.avax_asset_id, header.base_fee
                    )
                    contribution += contrib
                    ext_gas_used += gas
                except Exception:
                    from ..metrics import count_drop

                    count_drop("vm/build/atomic_tx_fee_error")
                    state.revert_to_snapshot(inner_snap)
                    self.mempool.remove_tx(tx)
                    continue
            if batch and ext_gas_used > params.ATOMIC_GAS_LIMIT:
                # this tx overflows the AP5 atomic gas budget: undo its
                # state changes, requeue it, and build with what we have
                state.revert_to_snapshot(inner_snap)
                if rules.is_apricot_phase4:
                    # undo the contribution accounting added above
                    contrib, gas = tx.block_fee_contribution(
                        rules.is_apricot_phase5, self.avax_asset_id, header.base_fee
                    )
                    contribution -= contrib
                    ext_gas_used -= gas
                self.mempool.cancel_current_tx(tx.id())
                break
            picked.append(tx)
            if not batch:
                break
        self._building_txs = picked
        ext_data = encode_atomic_txs(picked, batch)
        return ext_data, contribution, ext_gas_used

    def _on_extra_state_change(self, block, state):
        """Verify-side: apply the block's atomic txs to the state."""
        rules = self.chain_config.rules(block.number, block.time)
        txs = extract_atomic_txs(
            block.ext_data, rules.is_apricot_phase5, self.atomic_codec
        )
        contribution = 0
        ext_gas_used = 0
        for tx in txs:
            tx.evm_state_transfer(self, state)
            if rules.is_apricot_phase4:
                contrib, gas = tx.block_fee_contribution(
                    rules.is_apricot_phase5, self.avax_asset_id, block.base_fee
                )
                contribution += contrib
                ext_gas_used += gas
        return contribution, ext_gas_used

    # --- snowman interface -------------------------------------------------

    def build_block(self) -> VMBlock:
        """buildBlock (vm.go:991-1032)."""
        try:
            from ..metrics.spans import span

            with span("vm/buildBlock"):
                return self._build_block_inner()
        finally:
            # the engine consumed the PendingTxs notification by calling
            # us — success or not, reopen the gate + arm the retry timer
            # (block_builder.go handleGenerateBlock)
            self.block_builder.handle_generate_block()

    def _build_block_inner(self) -> VMBlock:
        with self.lock:
            self._building_txs = []
            try:
                eth_block = self.miner.commit_new_work()
                if not eth_block.transactions and not self._building_txs:
                    raise VMError("block contains no transactions")
                vmb = VMBlock(self, eth_block)
                # verify without writes: re-executes like a peer would
                vmb.syntactic_verify()
                self.blockchain.insert_block_manual(eth_block, writes=False)
            except Exception:
                # requeue any atomic txs popped into 'issued' during the
                # failed build (vm.go buildBlock error path CancelCurrentTxs)
                for tx in list(self.mempool.issued.values()):
                    self.mempool.cancel_current_tx(tx.id())
                raise
            self.mempool.issue_current_txs()
            return vmb

    def parse_block(self, blob: bytes) -> VMBlock:
        eth_block = EthBlock.decode(blob)
        existing = self._verified_blocks.get(eth_block.hash())
        if existing is not None:
            return existing
        return VMBlock(self, eth_block)

    def get_block(self, block_id: bytes) -> Optional[VMBlock]:
        vmb = self._verified_blocks.get(block_id)
        if vmb is not None:
            return vmb
        eth_block = self.blockchain.get_block(block_id)
        if eth_block is None:
            return None
        vmb = VMBlock(self, eth_block)
        if self.blockchain.get_canonical_hash(eth_block.number) == block_id and (
            eth_block.number <= self.last_accepted_vm_block.height()
        ):
            vmb.status = BlockStatus.ACCEPTED
        return vmb

    def set_preference(self, block_id: bytes) -> None:
        """SetPreference (vm.go:1076)."""
        vmb = self.get_block(block_id)
        if vmb is None:
            raise VMError("cannot set preference to unknown block")
        self.preferred_block = vmb
        self.blockchain.set_preference(vmb.eth_block)

    def last_accepted(self) -> VMBlock:
        return self.last_accepted_vm_block

    def shutdown(self) -> None:
        if self.initialized:
            self.block_builder.shutdown()
            self.gas_price_updater.stop()
            if self.continuous_profiler is not None:
                self.continuous_profiler.stop()
            if self.sampling_profiler is not None:
                from ..metrics import profiler as _profiler

                # drops only THIS VM's reference — other holders of the
                # process sampler keep sampling
                _profiler.stop_profiler()
                self.sampling_profiler = None
            if self.metrics_http is not None:
                self.metrics_http.stop()
            # graceful RPC drain first: in-flight reads finish (bounded
            # by rpc-drain-timeout) before the chain under them stops
            rpc_server = getattr(self, "rpc_server", None)
            if rpc_server is not None:
                rpc_server.stop()
                self.rpc_server = None
            self.blockchain.stop()

    # --- VMBlock support ---------------------------------------------------

    def add_verified_block(self, vmb: VMBlock) -> None:
        self._verified_blocks[vmb.id()] = vmb

    def forget_verified_block(self, block_id: bytes) -> None:
        self._verified_blocks.pop(block_id, None)

    def set_last_accepted(self, vmb: VMBlock) -> None:
        self.last_accepted_vm_block = vmb

    def atomic_backend_apply(self, vmb: VMBlock, tx: Tx) -> None:
        """Back-compat single-tx apply; the accept path now drains whole
        blocks through AtomicBackend.accept (atomic_backend.py)."""
        chain, requests = tx.atomic_ops()
        batch = self.blockchain.diskdb.new_batch()
        batch.put(
            ATOMIC_TX_INDEX_PREFIX + tx.id(),
            vmb.height().to_bytes(8, "big") + tx.encode(),
        )
        self.shared_memory.apply({chain: requests}, batch=batch)
        self.mempool.remove_tx(tx)
        self.atomic_trie.index(vmb.height(), {chain: requests})

    # --- atomic tx issuance (vm.go:1297-1417) -----------------------------

    # --- cross-chain eth_call capability (peer/network.go:199-301 +
    # message/eth_call_request.go): another chain's VM evaluates a
    # read-only call against OUR latest accepted state ------------------

    def handle_cross_chain_request(self, blob: bytes) -> bytes:
        """Typed cross-chain dispatcher: register with
        Network.register_cross_chain_handler(vm.chain_id_bytes, ...)."""
        import json as _json

        from ..sync.messages import (EthCallRequest, EthCallResponse,
                                     decode_message)

        msg = decode_message(blob)
        if not isinstance(msg, EthCallRequest):
            raise VMError(f"unsupported cross-chain request {type(msg)}")
        backend = getattr(self, "eth_backend", None)
        if backend is None:
            from ..eth.backend import EthBackend

            backend = EthBackend(self.blockchain, self.txpool)
            self.eth_backend = backend
        try:
            call_obj = _json.loads(msg.request_args.decode())
            result, _, _ = backend.do_call(call_obj, "latest")
        except Exception as e:  # noqa: BLE001 — errors travel in-band
            return EthCallResponse(result=b"", error=str(e).encode()).encode()
        if result.err is not None:
            return EthCallResponse(result=result.return_data,
                                   error=str(result.err).encode()).encode()
        return EthCallResponse(result=result.return_data).encode()

    def cross_chain_eth_call(self, network, chain_id: bytes,
                             call_obj: dict, deadline: float = 10.0):
        """Client side: eth_call on [chain_id]'s VM over the cross-chain
        transport. Returns the raw return data; raises VMError with the
        remote error string on failure."""
        import json as _json

        from ..sync.messages import EthCallRequest, decode_message

        req = EthCallRequest(
            request_args=_json.dumps(call_obj).encode()).encode()
        resp = decode_message(
            network.send_cross_chain_request(chain_id, req, deadline))
        if resp.error:
            raise VMError(
                f"cross-chain eth_call failed: {resp.error.decode()}")
        return resp.result

    def issue_atomic_tx(self, tx: Tx) -> None:
        tx.semantic_verify(self, self._next_base_fee())
        self.mempool.add(tx)
        self._signal_txs_ready()

    def _next_base_fee(self) -> Optional[int]:
        head = self.blockchain.current_block.header
        if not self.chain_config.is_apricot_phase3(head.time):
            return None
        from ..consensus.dummy import estimate_next_base_fee

        _, fee = estimate_next_base_fee(self.chain_config, head, head.time)
        return fee

    def issue_tx(self, tx) -> None:
        """eth tx entry (API/gossip)."""
        self.txpool.add_local(tx)
