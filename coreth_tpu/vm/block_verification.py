"""Syntactic block verification per fork ruleset (role of
/root/reference/plugin/evm/block_verification.go).

These are the Avalanche-specific shape checks that run before the chain's
own header verification: ExtDataHash binding, version, uncle emptiness,
atomic gas limits. Gas/fee field checks live in consensus.dummy.
"""

from __future__ import annotations

from .. import params
from ..native import keccak256

ZERO_HASH = b"\x00" * 32
EMPTY_UNCLE_HASH = bytes.fromhex(
    "1dcc4de8dec75d7aab85b567b6ccd41ad312451b948a7413f0a142fd40d49347"
)


class BlockVerificationError(Exception):
    pass


def syntactic_verify(vm, vmblock) -> None:
    b = vmblock.eth_block
    header = b.header
    config = vm.chain_config
    timestamp = b.time
    rules = config.rules(b.number, timestamp)

    # ExtDataHash must bind the ext data (block_verification.go:61-70)
    if not b.ext_data:
        if header.ext_data_hash != ZERO_HASH:
            raise BlockVerificationError(
                "extra data hash set with empty extra data"
            )
    else:
        if header.ext_data_hash != keccak256(b.ext_data):
            raise BlockVerificationError("extra data hash mismatch")

    if header.uncle_hash != EMPTY_UNCLE_HASH or b.uncles:
        raise BlockVerificationError("uncles not allowed")

    # version is always 0 (block_verification.go versions check)
    if b.version != 0:
        raise BlockVerificationError(f"invalid version {b.version}")

    if header.nonce != b"\x00" * 8 or header.mix_digest != ZERO_HASH:
        raise BlockVerificationError("nonce/mixDigest must be zero")

    if rules.is_apricot_phase1 and b.ext_data and len(b.ext_data) > 64 * 1024:
        raise BlockVerificationError("extra data too large")

    # atomic gas limit (AP5): sum of atomic tx gas bounded
    if rules.is_apricot_phase5:
        total = sum(t.gas_used(True) for t in vmblock.atomic_txs)
        if total > params.ATOMIC_GAS_LIMIT:
            raise BlockVerificationError(
                f"atomic gas used {total} exceeds limit {params.ATOMIC_GAS_LIMIT}"
            )
    elif len(vmblock.atomic_txs) > 1:
        raise BlockVerificationError("only one atomic tx allowed pre-AP5")
