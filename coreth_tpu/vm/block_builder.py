"""Block-build throttling (role of /root/reference/plugin/evm/
block_builder.go:40-155).

The engine must be notified exactly once per outstanding build: after a
PendingTxs notification goes out, further tx arrivals stay silent until
the engine actually calls BuildBlock (`build_sent` gate). After a build,
a retry timer re-notifies once the minimum delay passes IF the
pools still hold work — so an engine that drops a notification, or a
mempool that refills immediately, never wedges and never spins."""

from __future__ import annotations

import threading
from typing import Callable, Optional

# minBlockBuildingRetryDelay (block_builder.go): floor between notifying
# the engine twice over the same mempool contents
MIN_BLOCK_BUILDING_RETRY_DELAY = 0.5


class BlockBuilder:
    def __init__(self, vm,
                 retry_delay: float = MIN_BLOCK_BUILDING_RETRY_DELAY):
        self.vm = vm
        self.retry_delay = retry_delay
        self.lock = threading.Lock()
        self.build_sent = False
        self._timer: Optional[threading.Timer] = None
        self._shutdown = False
        # observability for tests/metrics
        self.notifications_sent = 0

    # --- inputs -----------------------------------------------------------

    def signal_txs_ready(self) -> None:
        """New work arrived (tx pool feed / gossip / atomic mempool)."""
        with self.lock:
            self._mark_building()

    def handle_generate_block(self) -> None:
        """Called by the VM right after BuildBlock (block_builder.go:90):
        reopen the gate and arm the retry timer."""
        with self.lock:
            self.build_sent = False
            self._set_timer()

    def shutdown(self) -> None:
        with self.lock:
            self._shutdown = True
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None

    # --- internals --------------------------------------------------------

    def need_to_build(self) -> bool:
        """Outstanding work in either pool (block_builder.go:104-108)."""
        vm = self.vm
        pending = 0
        if getattr(vm, "txpool", None) is not None:
            pending = vm.txpool.stats()[0]
        mempool = len(vm.mempool) if getattr(vm, "mempool", None) is not None else 0
        return pending > 0 or mempool > 0

    def _mark_building(self) -> None:  # guarded-by: lock
        if self.build_sent or self._shutdown:
            return  # engine already has an un-consumed notification
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        notify = getattr(self.vm, "to_engine", None)  # live lookup: tests
        # and the node may swap the engine channel after initialize
        if notify is not None:
            try:
                notify()
            except Exception:
                # engine channel full: the retry timer recovers, and the
                # backpressure is countable
                from ..metrics import count_drop

                count_drop("vm/builder/engine_notify_error")
                return
        self.build_sent = True
        self.notifications_sent += 1
        from ..metrics import default_registry

        default_registry.counter("vm/builder/notifications").inc()

    def _set_timer(self) -> None:  # guarded-by: lock
        if self._timer is not None:
            self._timer.cancel()
        if self._shutdown:
            return

        def fire():
            with self.lock:
                self._timer = None
                if self.need_to_build():
                    self._mark_building()

        self._timer = threading.Timer(self.retry_delay, fire)
        self._timer.daemon = True
        self._timer.start()
