"""Transaction gossip (role of /root/reference/plugin/evm/gossiper.go).

Gossips new eth/atomic txs to peers and handles inbound gossip into the
pools; regossip loops re-broadcast the highest-value pending txs on a
ticker (gossiper.go:223-241,423-523).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from .. import rlp
from ..core.types import Transaction
from .atomic_tx import decode_tx

GOSSIP_ETH_TXS = 0
GOSSIP_ATOMIC_TX = 1

REGOSSIP_INTERVAL = 60.0     # gossiper.go regossipFrequency
MAX_TXS_PER_GOSSIP = 16


def encode_tx_gossip(txs: List[Transaction]) -> bytes:
    return bytes([GOSSIP_ETH_TXS]) + rlp.encode([t.encode() for t in txs])


def encode_atomic_gossip(tx) -> bytes:
    return bytes([GOSSIP_ATOMIC_TX]) + tx.encode()


class Gossiper:
    def __init__(self, vm, network):
        self.vm = vm
        self.network = network
        # regossip knobs from the node config (config.go regossip-*)
        full = getattr(vm, "full_config", None)
        self.regossip_interval = getattr(
            full, "regossip_frequency", REGOSSIP_INTERVAL)
        self.regossip_max_txs = getattr(
            full, "regossip_max_txs", MAX_TXS_PER_GOSSIP)
        self._recently_gossiped: set = set()
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._regossip_thread: Optional[threading.Thread] = None

        network.subscribe_gossip(self.handle_gossip)
        vm.txpool.subscribe_new_txs(self.gossip_new_txs)

    # --- outbound ---------------------------------------------------------

    def gossip_new_txs(self, txs: List[Transaction]) -> None:
        """GossipEthTxs (gossiper.go:479): fan out fresh pool entries."""
        fresh = []
        with self._lock:
            for t in txs:
                h = t.hash()
                if h not in self._recently_gossiped:
                    self._recently_gossiped.add(h)
                    fresh.append(t)
            if len(self._recently_gossiped) > 4096:
                self._recently_gossiped = set(list(self._recently_gossiped)[-2048:])
        for i in range(0, len(fresh), MAX_TXS_PER_GOSSIP):
            self.network.gossip(encode_tx_gossip(fresh[i:i + MAX_TXS_PER_GOSSIP]))

    def gossip_atomic_tx(self, tx) -> None:
        self.network.gossip(encode_atomic_gossip(tx))

    def start_regossip(self) -> None:
        """Regossip ticker (gossiper.go:223-241)."""

        def loop():
            while not self._stop.wait(self.regossip_interval):
                self.regossip()

        self._regossip_thread = threading.Thread(target=loop, daemon=True)
        self._regossip_thread.start()

    def regossip(self) -> None:
        pending = self.vm.txpool.pending_txs()
        best: List[Transaction] = []
        for txs in pending.values():
            if txs:
                best.append(txs[0])  # lowest-nonce executable per account
        best.sort(key=lambda t: -t.gas_tip_cap)
        if best:
            self.network.gossip(
                encode_tx_gossip(best[:self.regossip_max_txs]))

    def stop(self) -> None:
        self._stop.set()

    # --- inbound ----------------------------------------------------------

    def handle_gossip(self, sender: bytes, payload: bytes) -> None:
        """GossipHandler.HandleEthTxs/HandleAtomicTx (gossiper.go:423-479).

        Drops are never fatal but always COUNTED (the reference keeps
        gossip stats; VERDICT r4 #9): gossip/drops/<reason> meters make
        a peer spraying malformed or unacceptable txs visible."""
        from ..metrics import count_drop

        def drop(reason: str):
            count_drop(f"gossip/drops/{reason}")

        if not payload:
            drop("empty")
            return
        kind, body = payload[0], payload[1:]
        try:
            if kind == GOSSIP_ETH_TXS:
                for blob in rlp.decode(body):
                    tx = Transaction.decode(bytes(blob) if not isinstance(blob, list)
                                            else rlp.encode(blob))
                    try:
                        self.vm.txpool.add_remote(tx)
                    except Exception:
                        drop("eth_tx_rejected")
            elif kind == GOSSIP_ATOMIC_TX:
                tx = decode_tx(body)
                try:
                    tx.semantic_verify(self.vm, self.vm._next_base_fee())
                    self.vm.mempool.add(tx)
                except Exception:
                    drop("atomic_tx_rejected")
            else:
                drop("unknown_kind")
        except Exception:
            drop("malformed")
