"""Per-verified-block pending atomic state + the atomic tx repository
(roles of /root/reference/plugin/evm/atomic_backend.go,
atomic_state.go, atomic_tx_repository.go).

At VERIFY time every block gets an `AtomicState` capturing its atomic
txs' shared-memory requests and the UTXO ids they consume; insertion
checks the block's consumed set against every PENDING (verified, not yet
accepted) ancestor so one unaccepted chain can never double-spend a
UTXO internally — the check the reference performs in
atomic_backend.InsertTxs. Accept applies the precomputed requests to
shared memory atomically with the repository index batch and drops the
pending state; Reject just drops it.

The repository indexes accepted atomic txs BOTH by tx id and by height
(atomic_tx_repository.go), and ships the bonus-block repair: mainnet
"bonus blocks" were accepted twice at different heights, leaving their
txs double-indexed; `repair_bonus_blocks` drops the bonus-height index
rows whose txs are already indexed at their canonical (lowest) height.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set, Tuple

TX_INDEX_PREFIX = b"Atx"      # Atx + tx_id -> height(8) + tx bytes
HEIGHT_INDEX_PREFIX = b"Ath"  # Ath + height(8) -> concat of 32-byte tx ids


class AtomicBackendError(Exception):
    pass


class AtomicState:
    """Pending atomic effects of ONE verified block (atomic_state.go)."""

    def __init__(self, block_hash: bytes, parent_hash: bytes, height: int,
                 txs: List, ops: Dict[bytes, object], consumed: Set[bytes]):
        self.block_hash = block_hash
        self.parent_hash = parent_hash
        self.height = height
        self.txs = txs
        self.ops = ops              # chain_id -> Requests
        self.consumed = consumed    # UTXO ids spent by this block


class AtomicTxRepository:
    """Height + id indexes over accepted atomic txs
    (atomic_tx_repository.go)."""

    def __init__(self, diskdb):
        self.diskdb = diskdb

    def write(self, batch, height: int, txs: List) -> None:
        ids = b""
        for tx in txs:
            batch.put(TX_INDEX_PREFIX + tx.id(),
                      height.to_bytes(8, "big") + tx.encode())
            ids += tx.id()
        if ids:
            batch.put(HEIGHT_INDEX_PREFIX + height.to_bytes(8, "big"), ids)

    def get_by_id(self, tx_id: bytes) -> Optional[Tuple[int, bytes]]:
        blob = self.diskdb.get(TX_INDEX_PREFIX + tx_id)
        if blob is None:
            return None
        return int.from_bytes(blob[:8], "big"), blob[8:]

    def tx_ids_at_height(self, height: int) -> List[bytes]:
        blob = self.diskdb.get(
            HEIGHT_INDEX_PREFIX + height.to_bytes(8, "big"))
        if not blob:
            return []
        return [blob[i:i + 32] for i in range(0, len(blob), 32)]

    def iterate_heights(self):
        for k, blob in self.diskdb.iterate(prefix=HEIGHT_INDEX_PREFIX):
            height = int.from_bytes(k[len(HEIGHT_INDEX_PREFIX):], "big")
            yield height, [blob[i:i + 32] for i in range(0, len(blob), 32)]

    def repair_bonus_blocks(self, bonus_heights: Set[int]) -> int:
        """Drop height-index rows for bonus blocks whose txs are already
        canonically indexed at a LOWER height; re-point the tx index at
        the canonical height. Returns rows repaired. Idempotent."""
        repaired = 0
        batch = self.diskdb.new_batch()
        for height in sorted(bonus_heights):
            ids = self.tx_ids_at_height(height)
            if not ids:
                continue
            all_dupe = True
            for tx_id in ids:
                entry = self.get_by_id(tx_id)
                if entry is None:
                    all_dupe = False
                    continue
                canonical = self._lowest_height_of(tx_id, height)
                if canonical is None or canonical >= height:
                    all_dupe = False
                    continue
                # keep the tx body; re-point its height at the canonical one
                _, tx_bytes = entry
                batch.put(TX_INDEX_PREFIX + tx_id,
                          canonical.to_bytes(8, "big") + tx_bytes)
            if all_dupe:
                batch.delete(HEIGHT_INDEX_PREFIX + height.to_bytes(8, "big"))
                repaired += 1
        batch.write()
        return repaired

    def _lowest_height_of(self, tx_id: bytes, below: int) -> Optional[int]:
        best = None
        for height, ids in self.iterate_heights():
            if height >= below:
                break
            if tx_id in ids:
                best = height if best is None else min(best, height)
        return best


class AtomicBackend:
    """Pending-state manager keyed by block hash (atomic_backend.go)."""

    def __init__(self, vm):
        self.vm = vm
        self.repo = AtomicTxRepository(vm.blockchain.diskdb)
        self._pending: Dict[bytes, AtomicState] = {}
        self._lock = threading.Lock()

    # --- verify -----------------------------------------------------------

    def insert_block(self, vmb) -> AtomicState:
        """Build the block's pending atomic state; reject UTXO
        double-spends against pending ancestors."""
        ops: Dict[bytes, object] = {}
        consumed: Set[bytes] = set()
        for tx in vmb.atomic_txs:
            chain, requests = tx.atomic_ops()
            if chain in ops:
                ops[chain].remove_requests.extend(requests.remove_requests)
                ops[chain].put_requests.extend(requests.put_requests)
            else:
                from .shared_memory import Requests

                ops[chain] = Requests(list(requests.remove_requests),
                                      list(requests.put_requests))
            for uid in getattr(tx.unsigned, "input_utxos", lambda: [])():
                consumed.add(uid)

        parent = vmb.eth_block.parent_hash
        with self._lock:
            anc = self._pending.get(parent)
            while anc is not None:
                overlap = consumed & anc.consumed
                if overlap:
                    raise AtomicBackendError(
                        "conflicting atomic inputs with pending ancestor "
                        f"{anc.block_hash.hex()[:12]}"
                    )
                anc = self._pending.get(anc.parent_hash)
            st = AtomicState(vmb.id(), parent, vmb.height(), list(vmb.atomic_txs),
                             ops, consumed)
            self._pending[vmb.id()] = st
        return st

    # --- accept / reject ---------------------------------------------------

    def accept(self, vmb) -> None:
        """Apply the precomputed requests + repository rows in ONE batch
        with the shared-memory commit (block.go:164-168 versiondb shape)."""
        with self._lock:
            st = self._pending.pop(vmb.id(), None)
        if st is None:
            # re-derive for blocks verified before this backend existed
            st = self.insert_block(vmb)
            with self._lock:
                self._pending.pop(vmb.id(), None)
        batch = self.vm.blockchain.diskdb.new_batch()
        self.repo.write(batch, st.height, st.txs)
        if st.ops:
            self.vm.shared_memory.apply(st.ops, batch=batch)
        else:
            batch.write()
        for tx in st.txs:
            self.vm.mempool.remove_tx(tx)
        if st.ops:
            self.vm.atomic_trie.index(st.height, st.ops)

    def reject(self, vmb) -> None:
        with self._lock:
            self._pending.pop(vmb.id(), None)

    def pending_for(self, block_hash: bytes) -> Optional[AtomicState]:
        with self._lock:
            return self._pending.get(block_hash)
