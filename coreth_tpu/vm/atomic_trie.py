"""Atomic operations trie (role of /root/reference/plugin/evm/
{atomic_trie,atomic_trie_iterator,atomic_syncer}.go).

Indexes every accepted block's atomic shared-memory requests in its own
merkle trie keyed (height, peer chain id), committing a root every
COMMIT_INTERVAL heights (atomic_trie.go:333). The committed roots anchor
state-sync summaries; the atomic syncer replays synced leaves into shared
memory. Uses the same TPU-batched TrieDatabase as the state trie.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

from .. import rlp
from ..trie.node import EMPTY_ROOT
from ..trie.triedb import TrieDatabase
from .shared_memory import Element, Requests

ATOMIC_TRIE_COMMIT_INTERVAL = 4096

# db keys (atomic_trie.go appliedSharedMemoryCursorKey etc.)
LAST_COMMITTED_KEY = b"atomicTrieLastCommitted"


def _height_key(height: int, chain_id: bytes) -> bytes:
    """Keys sort by height so iteration replays in order (atomic_trie.go)."""
    return height.to_bytes(8, "big") + chain_id


def _encode_requests(req: Requests) -> bytes:
    return rlp.encode([
        list(req.remove_requests),
        [[e.key, e.value, list(e.traits)] for e in req.put_requests],
    ])


def _decode_requests(blob: bytes) -> Requests:
    items = rlp.decode(blob)
    return Requests(
        remove_requests=[bytes(k) for k in items[0]],
        put_requests=[
            Element(bytes(e[0]), bytes(e[1]), [bytes(t) for t in e[2]])
            for e in items[1]
        ],
    )


class AtomicTrie:
    def __init__(self, diskdb, commit_interval: int = ATOMIC_TRIE_COMMIT_INTERVAL,
                 batch_keccak=None):
        self.diskdb = diskdb
        self.triedb = TrieDatabase(diskdb, batch_keccak=batch_keccak)
        self.commit_interval = commit_interval

        stored = diskdb.get(LAST_COMMITTED_KEY)
        if stored is not None:
            self.last_committed_root = stored[:32]
            self.last_committed_height = int.from_bytes(stored[32:40], "big")
        else:
            self.last_committed_root = EMPTY_ROOT
            self.last_committed_height = 0
        self._open_trie = self.triedb.open_trie(self.last_committed_root)

    # --- indexing ---------------------------------------------------------

    def update_trie(self, height: int, requests: Dict[bytes, Requests]) -> None:
        """Index one accepted block's atomic ops (atomic_trie.go Index)."""
        for chain_id, req in requests.items():
            self._open_trie.update(_height_key(height, chain_id), _encode_requests(req))

    def index(self, height: int, requests: Dict[bytes, Requests]) -> Optional[bytes]:
        """Index + commit at interval boundaries; returns the committed root
        when a commit happened."""
        self.update_trie(height, requests)
        if height % self.commit_interval == 0:
            return self.commit(height)
        return None

    def commit(self, height: int) -> bytes:
        root, nodes = self._open_trie.commit(collect_leaf=False)
        if nodes is not None:
            from ..trie.trienode import MergedNodeSet

            merged = MergedNodeSet()
            merged.merge(nodes)
            self.triedb.update(root, self.last_committed_root, merged)
        self.triedb.commit(root)
        self.diskdb.put(
            LAST_COMMITTED_KEY, root + height.to_bytes(8, "big")
        )
        self.last_committed_root = root
        self.last_committed_height = height
        self._open_trie = self.triedb.open_trie(root)
        return root

    # --- queries ----------------------------------------------------------

    def root_at(self) -> Tuple[bytes, int]:
        return self.last_committed_root, self.last_committed_height

    def iterate(self, root: Optional[bytes] = None) -> Iterator[Tuple[int, bytes, Requests]]:
        """Yield (height, chain_id, requests) in height order
        (atomic_trie_iterator.go)."""
        from ..trie.iterator import iterate_leaves

        trie = self.triedb.open_trie(root if root is not None else self.last_committed_root)
        for key, value in iterate_leaves(trie):
            height = int.from_bytes(key[:8], "big")
            chain_id = key[8:]
            yield height, chain_id, _decode_requests(value)

    def apply_to_shared_memory(self, shared_memory, last_height: int,
                               from_height: int = 0) -> int:
        """Replay indexed ops into shared memory (state-sync finish path,
        atomic_backend.go ApplyToSharedMemory). Returns ops applied."""
        applied = 0
        for height, chain_id, req in self.iterate():
            if height <= from_height or height > last_height:
                continue
            try:
                shared_memory.apply({chain_id: req})
                applied += 1
            except KeyError:
                # already-consumed UTXOs on replay are fine (idempotent)
                pass
        return applied


class AtomicSyncer:
    """atomic_syncer.go: fetch the atomic trie's leaves via the sync client,
    rebuilding it locally with interval commits."""

    def __init__(self, client, diskdb, target_root: bytes, target_height: int,
                 commit_interval: int = ATOMIC_TRIE_COMMIT_INTERVAL):
        self.client = client
        self.trie = AtomicTrie(diskdb, commit_interval)
        self.target_root = target_root
        self.target_height = target_height

    def sync(self) -> None:
        if self.target_root == EMPTY_ROOT:
            return
        from ..trie.stacktrie import StackTrie

        batch = self.trie.diskdb.new_batch()

        def write_node(path: bytes, node_hash: bytes, blob: bytes) -> None:
            batch.put(node_hash, blob)

        st = StackTrie(write_fn=write_node)
        start = b""
        while True:
            resp = self.client.get_leafs(self.target_root, start=start)
            for k, v in zip(resp.keys, resp.vals):
                st.update(k, v)
            if not resp.more or not resp.keys:
                break
            from ..sync.statesync import _next_key

            start = _next_key(resp.keys[-1])
        got = st.hash()
        if got != self.target_root:
            raise RuntimeError(
                f"atomic trie root mismatch: want {self.target_root.hex()[:12]} "
                f"got {got.hex()[:12]}"
            )
        batch.write()
        self.trie.diskdb.put(
            LAST_COMMITTED_KEY,
            self.target_root + self.target_height.to_bytes(8, "big"),
        )
        self.trie.last_committed_root = self.target_root
        self.trie.last_committed_height = self.target_height
        self.trie._open_trie = self.trie.triedb.open_trie(self.target_root)
