"""Snowman consensus Block wrapper (role of /root/reference/plugin/evm/
block.go).

Wraps a chain Block with the consensus lifecycle: Verify inserts into the
BlockChain without marking canonical-final (block.go:229-253), Accept
finalizes through the acceptor queue + atomic shared-memory commit
(:136-169), Reject drops trie refs and re-queues atomic txs (:173-191).
"""

from __future__ import annotations

from enum import Enum
from typing import Optional


class BlockStatus(Enum):
    PROCESSING = 0
    ACCEPTED = 1
    REJECTED = 2


class VMBlock:
    def __init__(self, vm, eth_block):
        self.vm = vm
        self.eth_block = eth_block
        self.status = BlockStatus.PROCESSING
        self.atomic_txs = []
        if eth_block.ext_data:
            from .atomic_tx import extract_atomic_txs

            self.atomic_txs = extract_atomic_txs(
                eth_block.ext_data,
                batch=vm.chain_config.is_apricot_phase5(eth_block.time),
                codec=vm.atomic_codec,
            )

    # --- identity ---------------------------------------------------------

    def id(self) -> bytes:
        return self.eth_block.hash()

    def parent_id(self) -> bytes:
        return self.eth_block.parent_hash

    def height(self) -> int:
        return self.eth_block.number

    def timestamp(self) -> int:
        return self.eth_block.time

    def bytes(self) -> bytes:
        return self.eth_block.encode()

    # --- lifecycle --------------------------------------------------------

    def verify(self, writes: bool = True) -> None:
        """Verify (block.go:229-253): syntactic checks + InsertBlockManual
        + pinning the block's pending atomic state (atomic_backend.go)."""
        self.syntactic_verify()
        for atx in self.atomic_txs:
            atx.semantic_verify(self.vm, self.eth_block.base_fee)
        if writes:
            # conflict-check against pending ancestors BEFORE the chain
            # insert so a double-spending fork never lands in the chain
            self.vm.atomic_backend.insert_block(self)
        try:
            self.vm.blockchain.insert_block_manual(self.eth_block, writes)
        except Exception:
            if writes:
                self.vm.atomic_backend.reject(self)
            raise
        if writes:
            self.vm.add_verified_block(self)

    def syntactic_verify(self) -> None:
        from .block_verification import syntactic_verify

        syntactic_verify(self.vm, self)

    def accept(self) -> None:
        """Accept (block.go:136-169): chain accept + the block's pending
        atomic state applied in one repository/shared-memory batch."""
        vm = self.vm
        vm.blockchain.accept(self.eth_block)
        self.status = BlockStatus.ACCEPTED
        vm.set_last_accepted(self)
        vm.atomic_backend.accept(self)
        vm.forget_verified_block(self.id())

    def reject(self) -> None:
        """Reject (block.go:173-191): losing fork; re-issue atomic txs."""
        vm = self.vm
        vm.atomic_backend.reject(self)
        for atx in self.atomic_txs:
            try:
                vm.mempool.add(atx, force=True)
            except Exception:
                # re-issue is best-effort (block.go Reject logs and moves
                # on); the chain-level reject must still run
                from ..metrics import count_drop

                count_drop("vm/block/reject_reissue_error")
        vm.blockchain.reject(self.eth_block)
        self.status = BlockStatus.REJECTED
        vm.forget_verified_block(self.id())
