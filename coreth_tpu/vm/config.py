"""VM configuration (role of /root/reference/plugin/evm/config.go).

The node hands the VM a JSON blob at Initialize (vm.go:327); it decodes
into Config with SetDefaults/Validate. The knob set mirrors config.go
:80-193 — caches, pruning, tx pool, gossip, state sync, profiling, API
gating.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field, fields
from typing import List, Optional

DEFAULT_ETH_APIS = [
    "eth", "eth-filter", "net", "web3", "internal-eth", "internal-blockchain",
    "internal-transaction",
]


@dataclass
class Config:
    # --- API gating (config.go eth-apis) ---------------------------------
    eth_apis: List[str] = field(default_factory=lambda: list(DEFAULT_ETH_APIS))
    admin_api_enabled: bool = False
    health_api_enabled: bool = True
    coreth_admin_api_enabled: bool = False
    ws_cpu_refill_rate: int = 0
    ws_cpu_max_stored: int = 0
    api_max_duration: float = 0.0
    api_max_blocks_per_request: int = 0
    allow_unfinalized_queries: bool = False
    allow_unprotected_txs: bool = False

    # --- RPC overload protection (ROBUSTNESS.md: serving under overload) --
    # cheap-lane worker threads; 0 disables pooling entirely (inline
    # dispatch on the transport thread — the seed behavior)
    rpc_max_workers: int = 8
    # cheap-lane admission queue depth; a full queue sheds -32005/429
    rpc_queue_size: int = 64
    # expensive-lane (eth_call/eth_getLogs/debug_trace*) workers + queue:
    # a tracing storm saturates this lane and never touches cheap reads
    rpc_expensive_workers: int = 4
    rpc_expensive_queue_size: int = 16
    # expensive-method deadline budget (s); 0 falls back to
    # api-max-duration (which covers cheap methods). 0/0 = no deadlines
    rpc_expensive_duration: float = 0.0
    # batch + body caps (proper error object instead of an OOM)
    rpc_batch_limit: int = 100
    rpc_body_limit: int = 5 * 1024 * 1024
    # expensive-method circuit breaker: threshold consecutive timeouts
    # open it; while open every probe-every-th arrival probes; close-after
    # consecutive probe passes re-close it. threshold 0 disables
    rpc_breaker_threshold: int = 5
    rpc_breaker_probe_every: int = 8
    rpc_breaker_close_after: int = 3
    # stop() drains in-flight dispatch up to this many seconds before
    # abandoning (reported in the drain result)
    rpc_drain_timeout: float = 5.0
    # concurrent HTTP connection cap (excess answered 429 inline); 0 off
    rpc_max_connections: int = 128
    # per-websocket-client bounded notification queue; overflow
    # disconnects the slow client. 0 = legacy unbuffered direct writes
    ws_notify_queue_size: int = 256
    # successful requests slower than this (seconds) are auto-captured
    # into the trace ring (debug_traceRequest); 0 disables auto-capture
    rpc_slo_budget: float = 1.0

    # --- caches ----------------------------------------------------------
    trie_clean_cache: int = 512        # MB
    trie_dirty_cache: int = 256        # MB
    trie_dirty_commit_target: int = 20  # MB
    snapshot_cache: int = 256          # MB
    accepted_cache_size: int = 32
    # read-tier result caches (eth/cache.py): gasprice oracle tips keyed
    # by accepted-head hash, and eth_getLogs bloom-index candidate
    # offsets keyed by (section, criteria). 0 disables a cache
    gasprice_cache_size: int = 8
    logs_cache_size: int = 64

    # --- eth settings -----------------------------------------------------
    preimages_enabled: bool = False
    snapshot_async: bool = True
    snapshot_verification_enabled: bool = False
    # fast EVM dispatch loop (pre-parsed instruction streams); false
    # reverts to the legacy dict-dispatch loop. The CORETH_TPU_EVM_FASTLOOP
    # env var overrides either way.
    evm_fastloop: bool = True
    # Block-STM optimistic parallel execution workers (core/parallel_exec):
    # transactions execute concurrently against versioned reads and fold
    # deterministically in tx-index order. 0 (default) keeps the serial
    # loop; the CORETH_TPU_EVM_PARALLEL env var overrides either way.
    evm_parallel_workers: int = 0
    # GIL-free process-level execution shards (core/exec_shards): forked
    # worker processes run speculative tx execution and ship write-sets
    # back for the deterministic fold/validate gate. 0 (default) keeps
    # the in-process paths; checked before evm-parallel-workers; the
    # CORETH_TPU_EVM_EXEC_SHARDS env var overrides either way.
    evm_exec_shards: int = 0

    # --- pruning ----------------------------------------------------------
    pruning_enabled: bool = True
    commit_interval: int = 4096
    accepted_queue_limit: int = 64
    allow_missing_tries: bool = False
    populate_missing_tries: Optional[int] = None
    populate_missing_tries_parallelism: int = 1024
    offline_pruning_enabled: bool = False
    offline_pruning_bloom_filter_size: int = 512   # MB
    offline_pruning_data_directory: str = ""

    # --- device hashing ---------------------------------------------------
    # "auto": large dirty sets drain to the device keccak; "off": CPU only
    device_hasher: str = "auto"
    # device-resident account trie: block commits run as resident device
    # commits on the account-trie mirror (trie/resident_mirror.py);
    # requires the native incremental planner (silent fallback otherwise).
    # "auto" (default): ON exactly when a TPU backend resolves — the
    # TPU-native path is the production default on TPU hardware, with a
    # host takeover if the device later fails (resident-commit-timeout)
    resident_account_trie: "bool | str" = "auto"
    # watchdog budget (s) per resident device commit; on expiry the
    # mirror takes over on the host and the chain continues (0 disables)
    resident_commit_timeout: float = 180.0
    # resident mirror host preference: "auto" (default) runs commits on
    # the threaded native CPU hasher whenever no TPU backend resolves
    # (the XLA-CPU "device" keccak is ~150x slower than native); true
    # forces host commits, false pins the device path even on CPU
    resident_prefer_host: "bool | str" = "auto"
    # cross-commit device pipelining depth (0-4; 1-2 recommended): up to
    # this many resident commits stay in flight on the device, their
    # roots optimistically recorded as the header roots and compared at
    # the next drain point (accept/reject/reorg/spot-check/export) —
    # host planning of block k+1 overlaps device execution of block k.
    # 0 = every commit synchronizes before verify returns
    resident_pipeline_depth: int = 0
    # staged insert pipeline depth (0-3): up to this many successor
    # blocks run sender recovery + speculative execution (against the
    # predecessor's speculated post-state) while the predecessor holds
    # chainmu for commit/device-hash/write. 0 = serial insert loop
    insert_pipeline_depth: int = 0
    # template residency: per-commit device->host digest absorb keeps
    # the host cache warm (root/export always valid, instant takeover)
    # while the device keeps row arenas + digest store resident, so
    # uploads carry only fresh leaf content. Excludes pipelining
    resident_template_residency: bool = False
    # mesh-sharded resident commits: shard the digest store + row arenas
    # P('batch', None) over this many devices (the promoted MULTICHIP
    # dryrun path). 0 = unsharded single-device executor (default);
    # widths must divide the 16-lane planner bucket, so 1/2/4/8. A wedge
    # demotes mesh -> single-device resident -> host, each rung
    # bit-exact vs the host oracle
    resident_mesh_devices: int = 0
    # native CPU hasher worker threads (plan execute + batch keccak);
    # 0 = auto (env CORETH_TPU_CPU_THREADS, else min(16, cores))
    cpu_threads: int = 0

    # --- robustness (ROBUSTNESS.md: device degradation ladder + tail) ----
    # per-call watchdog deadline (s) for laddered device dispatches
    # (planned commit, batched keccak); 0 disables the watchdog
    device_call_timeout: float = 0.0
    # transient-error retries (capped backoff) before a dispatch demotes
    # the device to the bit-exact host path
    device_max_retries: int = 1
    # seconds between background health probes while demoted; <= 0 means
    # demotion is permanent for the process
    device_probe_interval: float = 5.0
    # consecutive healthy probes required before re-promotion
    device_promote_after: int = 3
    # resident-mirror spot check (device root vs host keccak oracle)
    # every K committed inserts; divergence quarantines the mirror. 0 off
    resident_spot_check_interval: int = 0
    # deadline (s) for insert-tail / acceptor-queue joins; on expiry the
    # join raises a diagnosable TailStalled instead of hanging. 0 off
    tail_join_timeout: float = 0.0
    # re-hash hash-addressed payloads (headers/code, body/receipt
    # content) as they leave disk: a mismatch raises typed
    # CorruptDataError + counts db/verify_failures instead of feeding
    # bad bytes into consensus ("db-verify-on-read")
    db_verify_on_read: bool = False
    # transient storage-error retries (fault.Backoff-paced) for insert
    # tail writes before the chain demotes itself to the degraded
    # read-only rung; 0 = first failure degrades ("db-retry-budget")
    db_retry_budget: int = 2
    # commitment backend (COMMITMENT.md): "mpt" (consensus default) or
    # "bintrie-shadow" (mount the experimental binary-Merkle backend
    # beside the MPT; divergences quarantine, consensus is unaffected)
    state_backend: str = "mpt"
    # shadow canonical-rebuild spot check every K commits; 0 disables
    shadow_check_interval: int = 16

    # --- tx pool ----------------------------------------------------------
    local_txs_enabled: bool = False
    tx_pool_price_limit: int = 1
    tx_pool_price_bump: int = 10
    tx_pool_account_slots: int = 16
    tx_pool_global_slots: int = 4096
    tx_pool_account_queue: int = 64
    tx_pool_global_queue: int = 1024

    # --- gossip -----------------------------------------------------------
    remote_gossip_only_enabled: bool = False
    regossip_frequency: float = 60.0
    regossip_max_txs: int = 16
    regossip_tx_queue_size: int = 64

    # --- logging / profiling ---------------------------------------------
    log_level: str = "info"
    log_json_format: bool = False
    continuous_profiler_dir: str = ""
    continuous_profiler_frequency: float = 900.0
    continuous_profiler_max_files: int = 5

    # --- metrics / observability -----------------------------------------
    metrics_expensive_enabled: bool = False
    # block-pipeline span tracing (metrics/spans.py): process-global, so
    # like log-level it only applies when set explicitly. The
    # CORETH_TPU_SPANS env var seeds the default.
    spans_enabled: bool = False
    # finished-span ring capacity (debug_spanDump window)
    span_ring_size: int = 4096
    # per-chain flight recorder depth (debug_blockFlightRecord window)
    flight_recorder_size: int = 64
    # stdlib /metrics + /healthz endpoint (metrics/http.py); binds
    # loopback unless metrics-http-host says otherwise, port 0 = ephemeral
    metrics_http_enabled: bool = False
    metrics_http_host: str = "127.0.0.1"
    metrics_http_port: int = 0
    # request/insert trace-id propagation (metrics/tracectx.py):
    # process-global like spans-enabled, so it only applies when set
    # explicitly; the CORETH_TPU_TRACING env var seeds the default (on)
    tracing_enabled: bool = True
    # captured-trace ring capacity (debug_traceRequest window)
    trace_ring_size: int = 256
    # block-insert SLO budget (seconds): inserts slower than this are
    # auto-captured into the trace ring; 0 disables auto-capture
    chain_insert_slo_budget: float = 0.0
    # in-process sampling profiler (metrics/profiler.py): samples per
    # second the daemon thread walks sys._current_frames(); 0 = off.
    # Process-global like spans — debug_profileDump serves the table.
    profiler_hz: float = 0.0
    # max distinct (role, collapsed-stack) rows before new stacks fold
    # into a per-role overflow bucket
    profiler_ring_size: int = 2048
    # seconds a single canonical-lock hold may last before racecheck
    # captures traceback + trace id into the flight recorder; 0 = off
    lock_slow_hold_budget: float = 0.0
    # gates the parent-side registry merge of shard-worker ShardStats
    # deltas (the per-worker flight-record stamp stays on regardless)
    shard_telemetry_enabled: bool = True

    # --- keystore ---------------------------------------------------------
    keystore_directory: str = ""
    keystore_external_signer: str = ""
    keystore_insecure_unlock_allowed: bool = False

    # --- state sync -------------------------------------------------------
    state_sync_enabled: bool = False
    state_sync_skip_resume: bool = False
    state_sync_server_trie_cache: int = 64  # MB
    state_sync_ids: str = ""
    state_sync_commit_interval: int = 16384
    state_sync_min_blocks: int = 300_000

    # --- sync robustness (ROBUSTNESS.md: bootstrap under Byzantine peers) -
    # peer rotation attempts per logical request
    sync_max_attempts: int = 32
    # capped-exponential backoff between attempts (seconds)
    sync_backoff_base: float = 0.02
    sync_backoff_cap: float = 1.0
    # per-request-class deadlines (seconds); each is additionally capped
    # by any ambient utils/deadline budget on the calling thread
    sync_leafs_deadline: float = 10.0
    sync_blocks_deadline: float = 10.0
    sync_code_deadline: float = 10.0
    # hedged duplicate leafs requests: after hedge-delay seconds without
    # an answer, the next-best peer races the primary (tail latency)
    sync_hedge_requests: bool = False
    sync_hedge_delay: float = 0.25
    # distinct don't-have peers before a root is presumed stale and the
    # sync pivots (clamped down to the connected-peer count)
    sync_stale_root_votes: int = 3
    # peer ladder: cumulative failure score that turns a peer suspect /
    # quarantined, the base quarantine window (doubles per strike), and
    # consecutive probe passes that re-admit a quarantined peer
    sync_suspect_score: float = 4.0
    sync_quarantine_score: float = 8.0
    sync_quarantine_seconds: float = 30.0
    sync_readmit_probes: int = 2

    # --- misc -------------------------------------------------------------
    max_outbound_active_requests: int = 16
    max_outbound_active_cross_chain_requests: int = 64

    # which field names the Initialize JSON blob set explicitly (filled by
    # parse_config) — process-global settings (log level, expensive
    # metrics) are only applied when the operator actually asked
    explicit_keys: set = field(default_factory=set)

    def validate(self) -> None:
        """config.go Validate."""
        if self.populate_missing_tries is not None and (
            self.offline_pruning_enabled or self.pruning_enabled
        ):
            raise ValueError(
                "cannot enable populate-missing-tries while pruning (must be archival)"
            )
        if self.offline_pruning_enabled and not self.pruning_enabled:
            raise ValueError("cannot run offline pruning while pruning is disabled")
        if self.commit_interval == 0 and self.pruning_enabled:
            raise ValueError("commit interval must be non-zero in pruning mode")
        if self.state_sync_commit_interval % self.commit_interval != 0:
            raise ValueError(
                f"state sync commit interval ({self.state_sync_commit_interval}) "
                f"must be a multiple of commit interval ({self.commit_interval})"
            )
        if self.device_hasher not in ("auto", "planned", "batched", "fused", "off"):
            raise ValueError(f"unknown device-hasher mode {self.device_hasher!r}")
        if self.resident_account_trie not in (True, False, "auto"):
            raise ValueError(
                f"resident-account-trie must be true, false, or \"auto\" "
                f"(got {self.resident_account_trie!r})")
        if self.resident_prefer_host not in (True, False, "auto"):
            raise ValueError(
                f"resident-prefer-host must be true, false, or \"auto\" "
                f"(got {self.resident_prefer_host!r})")
        if self.cpu_threads < 0:
            raise ValueError(
                f"cpu-threads must be >= 0 (got {self.cpu_threads})")
        if not (0 <= self.resident_pipeline_depth <= 4):
            raise ValueError(
                f"resident-pipeline-depth must be in [0, 4] "
                f"(got {self.resident_pipeline_depth})")
        if not (0 <= self.insert_pipeline_depth <= 3):
            raise ValueError(
                f"insert-pipeline-depth must be in [0, 3] "
                f"(got {self.insert_pipeline_depth})")
        if self.resident_template_residency not in (True, False):
            raise ValueError(
                f"resident-template-residency must be a boolean "
                f"(got {self.resident_template_residency!r})")
        if self.resident_mesh_devices not in (0, 1, 2, 4, 8):
            raise ValueError(
                f"resident-mesh-devices must be one of 0, 1, 2, 4, 8 "
                f"(widths must divide the 16-lane planner bucket; got "
                f"{self.resident_mesh_devices})")
        if not (0 <= self.evm_parallel_workers <= 64):
            raise ValueError(
                f"evm-parallel-workers must be in [0, 64] "
                f"(got {self.evm_parallel_workers})")
        if not (0 <= self.evm_exec_shards <= 16):
            raise ValueError(
                f"evm-exec-shards must be in [0, 16] "
                f"(got {self.evm_exec_shards})")
        if self.device_call_timeout < 0:
            raise ValueError(
                f"device-call-timeout must be >= 0 "
                f"(got {self.device_call_timeout})")
        if self.device_max_retries < 0:
            raise ValueError(
                f"device-max-retries must be >= 0 "
                f"(got {self.device_max_retries})")
        if self.device_promote_after <= 0:
            raise ValueError(
                f"device-promote-after must be > 0 "
                f"(got {self.device_promote_after})")
        if self.resident_spot_check_interval < 0:
            raise ValueError(
                f"resident-spot-check-interval must be >= 0 "
                f"(got {self.resident_spot_check_interval})")
        if self.tail_join_timeout < 0:
            raise ValueError(
                f"tail-join-timeout must be >= 0 "
                f"(got {self.tail_join_timeout})")
        if self.db_retry_budget < 0:
            raise ValueError(
                f"db-retry-budget must be >= 0 "
                f"(got {self.db_retry_budget})")
        if self.state_backend not in ("mpt", "bintrie-shadow"):
            raise ValueError(
                f"state-backend must be 'mpt' or 'bintrie-shadow' "
                f"(got {self.state_backend!r})")
        if self.shadow_check_interval < 0:
            raise ValueError(
                f"shadow-check-interval must be >= 0 "
                f"(got {self.shadow_check_interval})")
        if self.span_ring_size <= 0:
            raise ValueError(
                f"span-ring-size must be > 0 (got {self.span_ring_size})")
        if self.trace_ring_size <= 0:
            raise ValueError(
                f"trace-ring-size must be > 0 (got {self.trace_ring_size})")
        if self.rpc_slo_budget < 0:
            raise ValueError(
                f"rpc-slo-budget must be >= 0 (got {self.rpc_slo_budget})")
        if self.chain_insert_slo_budget < 0:
            raise ValueError(
                f"chain-insert-slo-budget must be >= 0 "
                f"(got {self.chain_insert_slo_budget})")
        if self.profiler_hz < 0 or self.profiler_hz > 1000:
            raise ValueError(
                f"profiler-hz must be in [0, 1000] (got {self.profiler_hz})")
        if self.profiler_ring_size <= 0:
            raise ValueError(
                f"profiler-ring-size must be > 0 "
                f"(got {self.profiler_ring_size})")
        if self.lock_slow_hold_budget < 0:
            raise ValueError(
                f"lock-slow-hold-budget must be >= 0 "
                f"(got {self.lock_slow_hold_budget})")
        if self.flight_recorder_size <= 0:
            raise ValueError(
                f"flight-recorder-size must be > 0 "
                f"(got {self.flight_recorder_size})")
        if not (0 <= self.metrics_http_port <= 65535):
            raise ValueError(
                f"metrics-http-port must be in [0, 65535] "
                f"(got {self.metrics_http_port})")
        if self.api_max_duration < 0:
            raise ValueError(
                f"api-max-duration must be >= 0 (got {self.api_max_duration})")
        if self.api_max_blocks_per_request < 0:
            raise ValueError(
                f"api-max-blocks-per-request must be >= 0 "
                f"(got {self.api_max_blocks_per_request})")
        if self.gasprice_cache_size < 0:
            raise ValueError(
                f"gasprice-cache-size must be >= 0 "
                f"(got {self.gasprice_cache_size})")
        if self.logs_cache_size < 0:
            raise ValueError(
                f"logs-cache-size must be >= 0 "
                f"(got {self.logs_cache_size})")
        for knob in ("rpc_max_workers", "rpc_expensive_duration",
                     "rpc_batch_limit", "rpc_body_limit",
                     "rpc_breaker_threshold", "rpc_drain_timeout",
                     "rpc_max_connections", "ws_notify_queue_size"):
            if getattr(self, knob) < 0:
                raise ValueError(
                    f"{knob.replace('_', '-')} must be >= 0 "
                    f"(got {getattr(self, knob)})")
        if self.rpc_max_workers > 0:
            for knob in ("rpc_queue_size", "rpc_expensive_workers",
                         "rpc_expensive_queue_size"):
                if getattr(self, knob) < 1:
                    raise ValueError(
                        f"{knob.replace('_', '-')} must be >= 1 when "
                        f"rpc-max-workers > 0 (got {getattr(self, knob)})")
        for knob in ("rpc_breaker_probe_every", "rpc_breaker_close_after"):
            if getattr(self, knob) < 1:
                raise ValueError(
                    f"{knob.replace('_', '-')} must be >= 1 "
                    f"(got {getattr(self, knob)})")
        for knob in ("sync_backoff_base", "sync_backoff_cap",
                     "sync_leafs_deadline", "sync_blocks_deadline",
                     "sync_code_deadline", "sync_hedge_delay",
                     "sync_quarantine_seconds"):
            if getattr(self, knob) < 0:
                raise ValueError(
                    f"{knob.replace('_', '-')} must be >= 0 "
                    f"(got {getattr(self, knob)})")
        for knob in ("sync_max_attempts", "sync_stale_root_votes",
                     "sync_readmit_probes"):
            if getattr(self, knob) < 1:
                raise ValueError(
                    f"{knob.replace('_', '-')} must be >= 1 "
                    f"(got {getattr(self, knob)})")
        if self.sync_backoff_cap < self.sync_backoff_base:
            raise ValueError(
                f"sync-backoff-cap ({self.sync_backoff_cap}) must be >= "
                f"sync-backoff-base ({self.sync_backoff_base})")
        if not (0 < self.sync_suspect_score <= self.sync_quarantine_score):
            raise ValueError(
                f"need 0 < sync-suspect-score <= sync-quarantine-score "
                f"(got {self.sync_suspect_score} / "
                f"{self.sync_quarantine_score})")
        if self.resident_account_trie is True and not self.pruning_enabled:
            raise ValueError(
                "resident-account-trie requires pruning: interval "
                "persistence cannot honor the archival every-block-on-disk "
                "guarantee"
            )


def parse_config(config_bytes: bytes) -> Config:
    """Decode the Initialize JSON blob, applying defaults for absent keys
    (vm.go:326-334). JSON keys are the reference's kebab-case names."""
    cfg = Config()
    cfg.explicit_keys = set()
    if not config_bytes:
        return cfg
    raw = json.loads(config_bytes)
    key_map = {f.name.replace("_", "-"): f.name for f in fields(Config)}
    for k, v in raw.items():
        attr = key_map.get(k)
        if attr is None:
            continue  # unknown keys are ignored like the reference
        setattr(cfg, attr, v)
        cfg.explicit_keys.add(attr)
    cfg.validate()
    return cfg
