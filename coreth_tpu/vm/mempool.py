"""Atomic-tx mempool (role of /root/reference/plugin/evm/mempool.go +
tx_heap.go): price heap by gas price, UTXO-conflict tracking, discarded
LRU, pending signal."""

from __future__ import annotations

import heapq
import threading
from collections import OrderedDict
from typing import Dict, List, Optional

from .atomic_tx import Tx

DISCARDED_CACHE_SIZE = 50


class MempoolError(Exception):
    pass


ErrTooManyAtomicTx = "too many pending atomic txs"
ErrConflictingAtomicTx = "conflicting atomic tx present"
ErrAlreadyKnown = "already known"


class Mempool:
    def __init__(self, max_size: int = 4096, fee_fn=None, max_tx_gas=None):
        self.mu = threading.RLock()
        self.max_size = max_size
        self.fee_fn = fee_fn  # tx -> gas price (nAVAX/gas); default burned/gas
        # per-tx gas cap (AP5 atomic gas limit): a tx that can never fit in
        # a block must be rejected at admission or it starves the heap
        self.max_tx_gas = max_tx_gas  # callable: tx -> bool (fits)

        self.tx_heap: list = []  # (-price, seq, tx_id)
        self._seq = 0
        self.txs: Dict[bytes, Tx] = {}
        self.prices: Dict[bytes, int] = {}
        self.issued: Dict[bytes, Tx] = {}     # currently in a building block
        self.utxo_spenders: Dict[bytes, bytes] = {}  # utxo_id -> tx_id
        self.discarded: "OrderedDict[bytes, Tx]" = OrderedDict()
        self.pending_signal = threading.Event()

    def _price(self, tx: Tx) -> int:
        if self.fee_fn is not None:
            return self.fee_fn(tx)
        gas = max(tx.gas_used(True), 1)
        burned = max(tx.burned(b"\x00" * 32), 0)
        # default ordering: burned-per-gas; VM injects the real asset id
        return burned // gas

    def add(self, tx: Tx, force: bool = False) -> None:
        with self.mu:
            tx_id = tx.id()
            if tx_id in self.txs or tx_id in self.issued:
                raise MempoolError(ErrAlreadyKnown)
            if tx_id in self.discarded and not force:
                raise MempoolError(ErrAlreadyKnown)
            if len(self.txs) >= self.max_size:
                raise MempoolError(ErrTooManyAtomicTx)
            if self.max_tx_gas is not None and not self.max_tx_gas(tx):
                raise MempoolError("atomic tx exceeds atomic gas limit")
            price = self._price(tx)
            # conflict: collect ALL conflicting spenders first, compare
            # against the highest-priced one, only then evict (mempool.go —
            # a rejected add must not mutate the pool)
            conflicts = {
                self.utxo_spenders[u]
                for u in tx.input_utxos()
                if u in self.utxo_spenders
            }
            if conflicts:
                max_price = max(self.prices.get(c, 0) for c in conflicts)
                if max_price >= price and not force:
                    raise MempoolError(ErrConflictingAtomicTx)
                for other in conflicts:
                    self._remove(other)
            self.txs[tx_id] = tx
            self.prices[tx_id] = price
            self.discarded.pop(tx_id, None)
            for utxo in tx.input_utxos():
                self.utxo_spenders[utxo] = tx_id
            heapq.heappush(self.tx_heap, (-price, self._seq, tx_id))
            self._seq += 1
            self.pending_signal.set()

    def _remove(self, tx_id: bytes) -> None:  # guarded-by: mu
        tx = self.txs.pop(tx_id, None)
        self.prices.pop(tx_id, None)
        if tx is not None:
            for utxo in tx.input_utxos():
                if self.utxo_spenders.get(utxo) == tx_id:
                    del self.utxo_spenders[utxo]

    def next_tx(self) -> Optional[Tx]:
        """Pop the best-priced pending tx, marking it issued."""
        with self.mu:
            while self.tx_heap:
                _, _, tx_id = heapq.heappop(self.tx_heap)
                tx = self.txs.get(tx_id)
                if tx is None:
                    continue
                self._remove(tx_id)
                self.issued[tx_id] = tx
                return tx
            self.pending_signal.clear()
            return None

    def cancel_current_tx(self, tx_id: bytes) -> None:
        """Issued tx didn't make it into a block: requeue."""
        with self.mu:
            tx = self.issued.pop(tx_id, None)
            if tx is not None:
                try:
                    self.add(tx, force=True)
                except MempoolError:
                    pass

    def issue_current_txs(self) -> None:
        """Issued txs made it into the preferred block."""
        with self.mu:
            self.issued.clear()

    def remove_tx(self, tx: Tx) -> None:
        """Tx was accepted in a block: drop everywhere; discard conflicts."""
        with self.mu:
            tx_id = tx.id()
            self.issued.pop(tx_id, None)
            self._remove(tx_id)
            for utxo in tx.input_utxos():
                other = self.utxo_spenders.pop(utxo, None)
                if other is not None and other != tx_id:
                    conflicting = self.txs.get(other)
                    self._remove(other)  # clears ALL of its utxo entries
                    if conflicting is not None:
                        self._discard(other, conflicting)

    def _discard(self, tx_id: bytes, tx: Tx) -> None:  # guarded-by: mu
        self.discarded[tx_id] = tx
        while len(self.discarded) > DISCARDED_CACHE_SIZE:
            self.discarded.popitem(last=False)

    def get(self, tx_id: bytes) -> Optional[Tx]:
        with self.mu:
            return self.txs.get(tx_id) or self.issued.get(tx_id) or self.discarded.get(tx_id)

    def has(self, tx_id: bytes) -> bool:
        return self.get(tx_id) is not None

    def __len__(self) -> int:
        with self.mu:
            return len(self.txs)
