"""Wallet-side atomic tx construction: UTXO selection + fee-aware
building of import/export txs (roles of newImportTx/newExportTx and the
spendable-funds selectors, /root/reference/plugin/evm/vm.go:1419-1626).

The fee depends on the signed tx's byte length, which depends on how many
inputs the fee forces in — the reference resolves this by building once
with every available UTXO (imports consume everything addressed to the
keys) and iterating the fee for exports. Here both builders converge the
fee by fixed-point iteration on the fully signed size (2-3 rounds: size
is monotone in the fee only through int division, so it settles fast).
"""

from __future__ import annotations

from typing import List, Optional, Tuple

from ..crypto.secp256k1 import priv_to_address
from .atomic_tx import (
    UTXO,
    AtomicTxError,
    EVMInput,
    EVMOutput,
    ExportTx,
    ImportTx,
    Tx,
    calculate_dynamic_fee,
)


def spendable_utxos(vm, source_chain: bytes,
                    addresses: List[bytes]) -> List[UTXO]:
    """All shared-memory UTXOs addressed to [addresses] from
    [source_chain], paged until exhaustion (GetAtomicUTXOs,
    vm.go:1419-1455)."""
    blobs: List[bytes] = []
    start_key = b""
    while True:
        page, _, last_key = vm.shared_memory.indexed(
            source_chain, addresses, start_key=start_key, limit=1024)
        blobs.extend(page)
        if len(page) < 1024 or not last_key or last_key == start_key:
            break
        start_key = last_key
    utxos = [UTXO.decode(b) for b in blobs]
    # skip locked outputs and foreign thresholds (secp fx single-sig only)
    now = vm.blockchain.current_block.header.time
    return [u for u in utxos
            if u.locktime <= now and u.threshold == 1
            and u.address in addresses]


def _fee_fixed_point(build_and_sign, base_fee: int, fixed_fee: bool,
                     max_iters: int = 4) -> Tx:
    """Iterate fee -> size -> fee until stable; returns the signed tx."""
    fee = 0
    tx = None
    for _ in range(max_iters):
        tx = build_and_sign(fee)
        new_fee = calculate_dynamic_fee(tx.gas_used(fixed_fee), base_fee)
        if new_fee <= fee:
            return tx
        fee = new_fee
    return build_and_sign(fee)


def new_import_tx(vm, to_address: bytes, source_chain: bytes,
                  keys: List[bytes],
                  base_fee: Optional[int] = None) -> Tx:
    """Consume every spendable UTXO owned by [keys] on [source_chain] and
    credit the balances (minus the AVAX fee) to [to_address]
    (newImportTx, vm.go:1419-1517)."""
    if source_chain == vm.chain_id_bytes:
        raise AtomicTxError("cannot import from self")
    addr_key = {priv_to_address(k): k for k in keys}
    utxos = spendable_utxos(vm, source_chain, list(addr_key))
    if not utxos:
        raise AtomicTxError("no spendable UTXOs for the provided keys")
    if base_fee is None:
        base_fee = vm._next_base_fee() or 1
    rules = vm.current_rules()
    fixed_fee = rules.is_apricot_phase5

    totals = {}
    for u in utxos:
        totals[u.asset_id] = totals.get(u.asset_id, 0) + u.amount
    sign_keys = [addr_key[u.address] for u in utxos]

    def build_and_sign(fee: int) -> Tx:
        outs = []
        avax_total = totals.get(vm.avax_asset_id, 0)
        if avax_total > fee:
            outs.append(EVMOutput(address=to_address,
                                  amount=avax_total - fee,
                                  asset_id=vm.avax_asset_id))
        for asset, amount in totals.items():
            if asset != vm.avax_asset_id:
                outs.append(EVMOutput(address=to_address, amount=amount,
                                      asset_id=asset))
        if not outs:
            raise AtomicTxError(
                f"imported AVAX ({avax_total}) does not cover the fee "
                f"({fee})")
        tx = Tx(ImportTx(
            network_id=vm.network_id,
            blockchain_id=vm.chain_id_bytes,
            source_chain=source_chain,
            imported_inputs=utxos,
            outs=outs,
        ))
        tx.sign(sign_keys)
        return tx

    if not rules.is_apricot_phase3:
        # fixed (AP2) or zero fee: a single build at the flat fee suffices
        from .atomic_tx import AVALANCHE_ATOMIC_TX_FEE

        flat = AVALANCHE_ATOMIC_TX_FEE if rules.is_apricot_phase2 else 0
        return build_and_sign(flat)
    return _fee_fixed_point(build_and_sign, base_fee, fixed_fee)


def new_export_tx(vm, amount: int, asset_id: bytes,
                  destination_chain: bytes, to_address: bytes,
                  keys: List[bytes],
                  base_fee: Optional[int] = None) -> Tx:
    """Debit [amount] of [asset_id] (plus the AVAX fee) from the first
    key's EVM account and export a UTXO owned by [to_address] to
    [destination_chain] (newExportTx, vm.go:1519-1626)."""
    if destination_chain == vm.chain_id_bytes:
        raise AtomicTxError("cannot export to self")
    if amount == 0:
        raise AtomicTxError("export amount must be positive")
    if not keys:
        raise AtomicTxError("no keys to sign the export")
    if base_fee is None:
        base_fee = vm._next_base_fee() or 1
    rules = vm.current_rules()
    fixed_fee = rules.is_apricot_phase5
    from_key = keys[0]
    from_addr = priv_to_address(from_key)
    state = vm.blockchain.state()
    nonce = state.get_nonce(from_addr)
    avax = vm.avax_asset_id

    def build_and_sign(fee: int) -> Tx:
        if asset_id == avax:
            ins = [EVMInput(address=from_addr, amount=amount + fee,
                            asset_id=avax, nonce=nonce)]
        else:
            ins = [EVMInput(address=from_addr, amount=amount,
                            asset_id=asset_id, nonce=nonce)]
            if fee:
                # AVAX fee rides a second input against the same nonce
                # (the reference spends fee and asset from one account
                # state transition)
                ins.append(EVMInput(address=from_addr, amount=fee,
                                    asset_id=avax, nonce=nonce))
        tx = Tx(ExportTx(
            network_id=vm.network_id,
            blockchain_id=vm.chain_id_bytes,
            destination_chain=destination_chain,
            ins=ins,
            exported_outputs=[UTXO(
                tx_id=b"\x00" * 32, output_index=0, asset_id=asset_id,
                amount=amount, address=to_address,
            )],
        ))
        tx.sign([from_key] * len(ins))
        return tx

    if not rules.is_apricot_phase3:
        from .atomic_tx import AVALANCHE_ATOMIC_TX_FEE

        flat = AVALANCHE_ATOMIC_TX_FEE if rules.is_apricot_phase2 else 0
        tx = build_and_sign(flat)
    else:
        tx = _fee_fixed_point(build_and_sign, base_fee, fixed_fee)
    # pre-flight balance check: semantic verify would reject later anyway,
    # but the builder should fail with a clear error (vm.go:1560-1580)
    need_avax = sum(i.amount for i in tx.unsigned.ins if i.asset_id == avax)
    from .atomic_tx import X2C_RATE

    if state.get_balance(from_addr) < need_avax * X2C_RATE:
        raise AtomicTxError(
            f"insufficient AVAX balance: need {need_avax} nAVAX")
    if asset_id != avax:
        have = state.get_balance_multicoin(from_addr, asset_id)
        if have < amount:
            raise AtomicTxError(
                f"insufficient multicoin balance: need {amount}, have {have}")
    return tx
