"""In-process shared memory between chains (role of avalanchego's
atomic.Memory as used by /root/reference/plugin/evm — the X/P↔C UTXO
bridge).

Each (requesting chain, peer chain) pair shares one namespace of
key→value elements with traits (indexes). Apply() commits a batch of
puts/removes atomically together with the VM's own database batch, the
same contract as avalanchego's SharedMemory.Apply (plugin/evm/block.go:
164-168 commit batch pattern).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple


@dataclass
class Element:
    key: bytes
    value: bytes
    traits: List[bytes] = field(default_factory=list)


@dataclass
class Requests:
    remove_requests: List[bytes] = field(default_factory=list)
    put_requests: List[Element] = field(default_factory=list)


class SharedMemory:
    """One chain's view onto the shared atomic memory."""

    def __init__(self, memory: "Memory", chain_id: bytes):
        self._memory = memory
        self._chain_id = chain_id

    def get(self, peer_chain_id: bytes, keys: List[bytes]) -> List[bytes]:
        ns = self._memory._namespace(self._chain_id, peer_chain_id)
        out = []
        for k in keys:
            v = ns.get(k)
            if v is None:
                raise KeyError(f"key {k.hex()} not found in shared memory")
            out.append(v.value)
        return out

    def indexed(self, peer_chain_id: bytes, traits: List[bytes],
                start_trait: bytes = b"", start_key: bytes = b"",
                limit: int = 100) -> Tuple[List[bytes], bytes, bytes]:
        """Fetch values whose traits intersect [traits] (UTXO lookup)."""
        ns = self._memory._namespace(self._chain_id, peer_chain_id)
        hits = []
        for el in ns.values():
            if any(t in el.traits for t in traits):
                hits.append(el)
        hits.sort(key=lambda e: e.key)
        if start_key:
            hits = [e for e in hits if e.key > start_key]
        vals = [e.value for e in hits[:limit]]
        last_key = hits[min(limit, len(hits)) - 1].key if hits else b""
        return vals, b"", last_key

    def apply(self, requests: Dict[bytes, Requests], batch=None) -> None:
        """Atomically apply removes/puts across peer chains, then write the
        caller's db batch — all under one lock."""
        with self._memory._lock:
            # validate first: removes must exist
            for peer, req in requests.items():
                ns = self._memory._namespace(peer, self._chain_id)
                my_ns = self._memory._namespace(self._chain_id, peer)
                for k in req.remove_requests:
                    if k not in my_ns:
                        raise KeyError(f"cannot remove missing key {k.hex()}")
            for peer, req in requests.items():
                # removes target OUR inbound namespace (consuming imports);
                # puts go to the PEER's inbound namespace (exports to them)
                my_ns = self._memory._namespace(self._chain_id, peer)
                peer_ns = self._memory._namespace(peer, self._chain_id)
                for k in req.remove_requests:
                    del my_ns[k]
                for el in req.put_requests:
                    peer_ns[el.key] = el
            if batch is not None:
                batch.write()


class Memory:
    """The hub shared by all chains in one process (test fixture +
    production single-process topology)."""

    def __init__(self):
        self._lock = threading.RLock()
        # (owner chain, peer chain) -> {key: Element}: elements readable by
        # [owner] that were produced by [peer]
        self._spaces: Dict[Tuple[bytes, bytes], Dict[bytes, Element]] = {}

    def new_shared_memory(self, chain_id: bytes) -> SharedMemory:
        return SharedMemory(self, chain_id)

    def _namespace(self, owner: bytes, peer: bytes) -> Dict[bytes, Element]:
        return self._spaces.setdefault((owner, peer), {})
