"""WebSocket JSON-RPC transport with push subscriptions (role of
/root/reference/rpc/websocket.go + subscription.go).

RFC 6455 over stdlib sockets — handshake (Sec-WebSocket-Accept), frame
codec (client->server masked, server->client unmasked), ping/pong/close.
Each text frame is a JSON-RPC request; `eth_subscribe`/`eth_unsubscribe`
are connection-scoped: notifications push as

    {"jsonrpc":"2.0","method":"eth_subscription",
     "params":{"subscription": id, "result": ...}}

and every subscription a connection holds is torn down when it closes
(websocket.go connection lifetime semantics). A per-connection token
bucket throttles message processing — the reference's WS CPU limiter
(plugin/evm/vm.go:1178-1186, ws-cpu-refill-rate / ws-cpu-max-stored).

Backpressure (ROBUSTNESS.md "Serving under overload"): when
`notify_queue_size` > 0, notifications go through a bounded per-client
queue drained by a dedicated writer thread; a client that stops reading
fills its queue and is *disconnected deterministically*
(`rpc/ws/slow_disconnects`) instead of blocking the producer — one
stalled subscriber can never wedge block acceptance. `max_payload`
bounds inbound frames (the websocket half of the rpc-body-limit cap).

`WSClient` is the in-repo test/tooling client (role of the reference's
rpc.DialWebsocket for its own tests).
"""

from __future__ import annotations

import base64
import hashlib
import json
import os
import queue
import socket
import struct
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..fault import failpoint, register
from ..metrics import count_drop, default_registry
from ..metrics import tracectx

_GUID = "258EAFA5-E914-47DA-95CA-C5AB0DC85B11"

# `hang` here parks the notification writer — a deterministic stand-in
# for a client that stopped reading (no TCP buffer games needed).
register("ws/before_notify",
         "in the per-connection writer thread, before each subscription "
         "notification frame is written")

OP_TEXT = 0x1
OP_CLOSE = 0x8
OP_PING = 0x9
OP_PONG = 0xA


def _accept_key(key: str) -> str:
    return base64.b64encode(
        hashlib.sha1((key + _GUID).encode()).digest()
    ).decode()


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("socket closed")
        buf += chunk
    return buf


class FrameTooLarge(ConnectionError):
    """An inbound frame exceeded [max_payload] — raised *before* the
    oversized payload is buffered."""

    def __init__(self, size: int, limit: int):
        super().__init__(f"frame too large ({size} > {limit} bytes)")


def read_frame(sock: socket.socket,
               max_payload: int = 0) -> Tuple[int, bytes]:
    """-> (opcode, payload); handles fragmentation by concatenation.
    [max_payload] > 0 rejects oversized frames from the declared length
    (never buffering them) with FrameTooLarge."""
    payload = b""
    opcode = None
    while True:
        h = _recv_exact(sock, 2)
        fin = h[0] & 0x80
        op = h[0] & 0x0F
        masked = h[1] & 0x80
        ln = h[1] & 0x7F
        if ln == 126:
            ln = struct.unpack(">H", _recv_exact(sock, 2))[0]
        elif ln == 127:
            ln = struct.unpack(">Q", _recv_exact(sock, 8))[0]
        if max_payload and len(payload) + ln > max_payload:
            raise FrameTooLarge(len(payload) + ln, max_payload)
        mask = _recv_exact(sock, 4) if masked else None
        data = _recv_exact(sock, ln) if ln else b""
        if mask:
            data = bytes(b ^ mask[i % 4] for i, b in enumerate(data))
        if opcode is None:
            opcode = op
        payload += data
        if fin:
            return opcode, payload


def write_frame(sock: socket.socket, opcode: int, payload: bytes,
                mask: bool = False) -> None:
    b0 = 0x80 | opcode
    header = bytes([b0])
    ln = len(payload)
    mask_bit = 0x80 if mask else 0
    if ln < 126:
        header += bytes([mask_bit | ln])
    elif ln < (1 << 16):
        header += bytes([mask_bit | 126]) + struct.pack(">H", ln)
    else:
        header += bytes([mask_bit | 127]) + struct.pack(">Q", ln)
    if mask:
        mk = os.urandom(4)
        payload = bytes(b ^ mk[i % 4] for i, b in enumerate(payload))
        header += mk
    sock.sendall(header + payload)


class _TokenBucket:
    """ws-cpu-refill-rate / ws-cpu-max-stored: each message costs one
    token; an empty bucket sleeps the connection until refill. 0 rates
    disable throttling (config.go default)."""

    def __init__(self, refill_per_s: float, max_stored: float):
        self.rate = refill_per_s
        # a rate with cap<1 could never accumulate a whole token and
        # take() would hang forever; clamp so throttling stays sane
        self.cap = max(max_stored, 1.0) if refill_per_s > 0 else max_stored
        self.tokens = self.cap
        self.t = time.monotonic()

    def take(self) -> None:
        if self.rate <= 0:
            return
        while True:
            now = time.monotonic()
            self.tokens = min(self.cap, self.tokens + (now - self.t) * self.rate)
            self.t = now
            if self.tokens >= 1:
                self.tokens -= 1
                return
            time.sleep((1 - self.tokens) / self.rate)


class WSServer:
    """WebSocket front-end over an RPCServer's method registry."""

    def __init__(self, rpc_server, refill_rate: float = 0,
                 max_stored: float = 0, notify_queue_size: int = 0,
                 max_payload: int = 0):
        self.rpc = rpc_server
        self.refill_rate = refill_rate
        self.max_stored = max_stored
        # 0 = legacy unbuffered notification writes (no backpressure)
        self.notify_queue_size = notify_queue_size
        self.max_payload = max_payload
        self._sock: Optional[socket.socket] = None
        self._stop = threading.Event()
        self._conns_lock = threading.Lock()
        self._conns: set = set()  # guarded-by: _conns_lock

    def serve(self, host: str = "127.0.0.1", port: int = 0) -> int:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self._sock.listen(16)
        threading.Thread(target=self._accept_loop, daemon=True).start()
        return self._sock.getsockname()[1]

    def stop(self) -> None:
        self._stop.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        with self._conns_lock:
            live = list(self._conns)
        for conn in live:  # unblock readers parked in read_frame
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            with self._conns_lock:
                self._conns.add(conn)
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _handshake(self, conn: socket.socket) -> bool:
        data = b""
        while b"\r\n\r\n" not in data:
            chunk = conn.recv(4096)
            if not chunk:
                return False
            data += chunk
        headers = {}
        for line in data.split(b"\r\n")[1:]:
            if b":" in line:
                k, v = line.split(b":", 1)
                headers[k.strip().lower()] = v.strip()
        key = headers.get(b"sec-websocket-key")
        if key is None:
            return False
        resp = (
            "HTTP/1.1 101 Switching Protocols\r\n"
            "Upgrade: websocket\r\n"
            "Connection: Upgrade\r\n"
            f"Sec-WebSocket-Accept: {_accept_key(key.decode())}\r\n\r\n"
        )
        conn.sendall(resp.encode())
        return True

    def _serve_conn(self, conn: socket.socket) -> None:
        subs: List[str] = []
        wlock = threading.Lock()
        bucket = _TokenBucket(self.refill_rate, self.max_stored)
        closed = threading.Event()
        notify_q: "Optional[queue.Queue]" = (
            queue.Queue(maxsize=self.notify_queue_size)
            if self.notify_queue_size > 0 else None)

        def send_json(obj) -> None:
            data = json.dumps(obj).encode()
            with wlock:
                write_frame(conn, OP_TEXT, data)

        def drop_conn() -> None:
            # deterministic disconnect: close the socket so the reader
            # unwinds and tears every subscription down
            closed.set()
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass

        def notify_writer() -> None:
            while True:
                item = notify_q.get()
                if item is None:
                    return
                ctx, obj = item
                try:
                    # the producer's trace context (captured at enqueue)
                    # rides across the writer-thread boundary, so a
                    # notify failure attributes back to the block insert
                    # or request that produced the event
                    with tracectx.scope(ctx):
                        failpoint("ws/before_notify")
                        send_json(obj)
                except Exception:
                    # a dead or erroring client ends *its* delivery only
                    count_drop("rpc/ws/notify_errors")
                    tracectx.capture(ctx, "ws_notify_error")
                    drop_conn()
                    return

        def send_notification(obj) -> None:
            """Producer-side entry (runs on block-acceptance threads):
            never blocks — a full queue means the client is too slow."""
            if notify_q is None:
                failpoint("ws/before_notify")
                send_json(obj)
                return
            if closed.is_set():
                default_registry.counter("rpc/ws/notify_drops").inc()
                return
            ctx = tracectx.current()
            try:
                notify_q.put_nowait((ctx, obj))
            except queue.Full:
                default_registry.counter("rpc/ws/notify_drops").inc()
                default_registry.counter("rpc/ws/slow_disconnects").inc()
                tracectx.capture(ctx, "ws_notify_dropped")
                drop_conn()

        if notify_q is not None:
            threading.Thread(target=notify_writer, daemon=True,
                             name="ws-notify").start()
        try:
            if not self._handshake(conn):
                return
            while not self._stop.is_set():
                op, payload = read_frame(conn, self.max_payload)
                if op == OP_CLOSE:
                    with wlock:
                        write_frame(conn, OP_CLOSE, b"")
                    return
                if op == OP_PING:
                    with wlock:
                        write_frame(conn, OP_PONG, payload)
                    continue
                if op != OP_TEXT:
                    continue
                bucket.take()
                self._handle_message(payload, send_json, send_notification,
                                     subs)
        except FrameTooLarge as e:
            default_registry.counter("rpc/body_oversize").inc()
            try:
                send_json({"jsonrpc": "2.0", "id": None,
                           "error": {"code": -32600, "message": str(e)}})
            except OSError:
                pass  # too-slow-to-even-read clients skip the courtesy
        except (ConnectionError, OSError):
            pass
        finally:
            for sid in subs:
                self.rpc.unsubscribe(sid)
            if notify_q is not None:
                try:
                    notify_q.put_nowait(None)  # release the writer
                except queue.Full:
                    pass  # writer is wedged; drop_conn unwedges its write
            drop_conn()
            with self._conns_lock:
                self._conns.discard(conn)

    def _handle_message(self, payload: bytes, send_json, send_notification,
                        subs: List[str]):
        try:
            req = json.loads(payload)
        except Exception:
            send_json({"jsonrpc": "2.0", "id": None,
                       "error": {"code": -32700, "message": "parse error"}})
            return
        if isinstance(req, dict) and req.get("method") == "eth_subscribe":
            self._do_subscribe(req, send_json, send_notification, subs)
            return
        if isinstance(req, dict) and req.get("method") == "eth_unsubscribe":
            params = req.get("params") or []
            ok = bool(params) and self.rpc.unsubscribe(params[0])
            if ok and params[0] in subs:
                subs.remove(params[0])
            send_json({"jsonrpc": "2.0", "id": req.get("id"), "result": ok})
            return
        resp = self.rpc.handle_raw(payload)
        send_json(json.loads(resp))

    def _do_subscribe(self, req: dict, send_json, send_notification,
                      subs: List[str]) -> None:
        params = req.get("params") or []
        if not params:
            send_json({"jsonrpc": "2.0", "id": req.get("id"),
                       "error": {"code": -32602,
                                 "message": "subscription kind required"}})
            return
        kind = params[0]
        holder = [None]  # filled once the server assigns the id; events
        # that race registration are dropped (no id to address them to)

        def notify(item):
            if holder[0] is None:
                return
            send_notification({
                "jsonrpc": "2.0",
                "method": "eth_subscription",
                "params": {"subscription": holder[0], "result": item},
            })

        try:
            sub_id = self.rpc.subscribe(f"eth_{kind}", notify, *params[1:])
            holder[0] = sub_id
        except Exception as e:
            send_json({"jsonrpc": "2.0", "id": req.get("id"),
                       "error": {"code": -32602, "message": str(e)}})
            return
        subs.append(sub_id)
        send_json({"jsonrpc": "2.0", "id": req.get("id"), "result": sub_id})


class WSClient:
    """Blocking test/tooling client: request() correlates by id;
    notifications queue for next_notification()."""

    def __init__(self, host: str, port: int, timeout: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        key = base64.b64encode(os.urandom(16)).decode()
        req = (
            f"GET / HTTP/1.1\r\nHost: {host}:{port}\r\n"
            "Upgrade: websocket\r\nConnection: Upgrade\r\n"
            f"Sec-WebSocket-Key: {key}\r\nSec-WebSocket-Version: 13\r\n\r\n"
        )
        self.sock.sendall(req.encode())
        data = b""
        while b"\r\n\r\n" not in data:
            chunk = self.sock.recv(4096)
            if not chunk:
                raise ConnectionError("handshake failed")
            data += chunk
        if b"101" not in data.split(b"\r\n", 1)[0]:
            raise ConnectionError(f"handshake rejected: {data[:120]!r}")
        want = _accept_key(key).encode()
        if want not in data:
            raise ConnectionError("bad Sec-WebSocket-Accept")
        self._id = 0
        self._notifications: List[dict] = []
        self._lock = threading.Lock()

    def _recv_json(self) -> dict:
        while True:
            op, payload = read_frame(self.sock)
            if op == OP_CLOSE:
                raise ConnectionError("server closed")
            if op == OP_PING:
                write_frame(self.sock, OP_PONG, payload, mask=True)
                continue
            if op == OP_TEXT:
                return json.loads(payload)

    def request(self, method: str, params: Optional[list] = None) -> Any:
        with self._lock:
            self._id += 1
            rid = self._id
        msg = {"jsonrpc": "2.0", "id": rid, "method": method,
               "params": params or []}
        write_frame(self.sock, OP_TEXT, json.dumps(msg).encode(), mask=True)
        while True:
            obj = self._recv_json()
            if obj.get("method") == "eth_subscription":
                self._notifications.append(obj)
                continue
            if obj.get("id") == rid:
                if "error" in obj:
                    raise RuntimeError(obj["error"])
                return obj["result"]
            # stale response (shouldn't happen on a serial client): drop

    def next_notification(self, timeout: float = 10.0) -> dict:
        if self._notifications:
            return self._notifications.pop(0)
        old = self.sock.gettimeout()
        self.sock.settimeout(timeout)
        try:
            while True:
                obj = self._recv_json()
                if obj.get("method") == "eth_subscription":
                    return obj
        finally:
            self.sock.settimeout(old)

    def close(self) -> None:
        try:
            write_frame(self.sock, OP_CLOSE, b"", mask=True)
            self.sock.close()
        except OSError:
            pass
