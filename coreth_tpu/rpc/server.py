"""JSON-RPC 2.0 engine (role of /root/reference/rpc/{server,http,
websocket,subscription}.go).

Method registry keyed `namespace_method`, single + batch dispatch,
standard error codes, and pub/sub subscriptions. Serves over HTTP via the
stdlib ThreadingHTTPServer (handlers.go equivalents); tests can dispatch
in-process through `handle_raw`.
"""

from __future__ import annotations

import json
import threading
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

PARSE_ERROR = -32700
INVALID_REQUEST = -32600
METHOD_NOT_FOUND = -32601
INVALID_PARAMS = -32602
INTERNAL_ERROR = -32603


class RPCError(Exception):
    def __init__(self, code: int, message: str, data=None):
        super().__init__(message)
        self.code = code
        self.data = data


class Subscription:
    def __init__(self, sub_id: str, notify: Callable[[Any], None]):
        self.id = sub_id
        self.notify = notify
        self.active = True
        self.cleanup: Optional[Callable[[], None]] = None


class RPCServer:
    def __init__(self):
        self._methods: Dict[str, Callable] = {}
        self._subscriptions: Dict[str, Subscription] = {}
        self._sub_factories: Dict[str, Callable] = {}
        self.lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None

    # --- registration -----------------------------------------------------

    def register(self, namespace: str, name: str, fn: Callable) -> None:
        self._methods[f"{namespace}_{name}"] = fn

    def register_api(self, namespace: str, api: object) -> None:
        """Register every public method of [api] under [namespace]
        (rpc/service.go reflection registration)."""
        for attr in dir(api):
            if attr.startswith("_"):
                continue
            fn = getattr(api, attr)
            if callable(fn):
                self.register(namespace, attr, fn)

    def unregister(self, namespace: str, name: str) -> None:
        """Remove one method — API gating carve-outs (the reference's
        eth-apis list gates at sub-namespace granularity, vm.go:1140)."""
        self._methods.pop(f"{namespace}_{name}", None)

    def register_subscription(self, namespace: str, name: str,
                              factory: Callable) -> None:
        """factory(notify_fn, *params) -> cleanup_fn|None."""
        self._sub_factories[f"{namespace}_{name}"] = factory

    # --- dispatch ---------------------------------------------------------

    def handle_raw(self, raw: bytes) -> bytes:
        try:
            payload = json.loads(raw)
        except Exception:
            return self._encode_error(None, PARSE_ERROR, "parse error")
        if isinstance(payload, list):
            if not payload:
                return self._encode_error(None, INVALID_REQUEST, "empty batch")
            out = [self._handle_one(req) for req in payload]
            return json.dumps([json.loads(o) for o in out if o]).encode()
        return self._handle_one(payload)

    def _handle_one(self, req) -> bytes:
        if not isinstance(req, dict):
            return self._encode_error(None, INVALID_REQUEST, "invalid request")
        req_id = req.get("id")
        method = req.get("method")
        if not isinstance(method, str):
            return self._encode_error(req_id, INVALID_REQUEST, "missing method")
        params = req.get("params", [])
        fn = self._methods.get(method)
        if fn is None:
            return self._encode_error(
                req_id, METHOD_NOT_FOUND, f"the method {method} does not exist"
            )
        try:
            from ..metrics.spans import span

            with span("rpc/" + method):
                if isinstance(params, dict):
                    result = fn(**params)
                else:
                    result = fn(*params)
        except RPCError as e:
            return self._encode_error(req_id, e.code, str(e), e.data)
        except TypeError as e:
            return self._encode_error(req_id, INVALID_PARAMS, str(e))
        except Exception as e:
            return self._encode_error(req_id, INTERNAL_ERROR, str(e))
        return json.dumps(
            {"jsonrpc": "2.0", "id": req_id, "result": result}
        ).encode()

    @staticmethod
    def _encode_error(req_id, code: int, message: str, data=None) -> bytes:
        err = {"code": code, "message": message}
        if data is not None:
            err["data"] = data
        return json.dumps({"jsonrpc": "2.0", "id": req_id, "error": err}).encode()

    # --- subscriptions ----------------------------------------------------

    def subscribe(self, method: str, notify: Callable[[Any], None], *params) -> str:
        factory = self._sub_factories.get(method)
        if factory is None:
            raise RPCError(METHOD_NOT_FOUND, f"no subscription {method}")
        sub_id = "0x" + uuid.uuid4().hex
        sub = Subscription(sub_id, notify)
        with self.lock:
            self._subscriptions[sub_id] = sub
        try:
            cleanup = factory(lambda item: self._notify(sub_id, item), *params)
        except BaseException:
            with self.lock:
                self._subscriptions.pop(sub_id, None)
            raise
        with self.lock:
            if sub_id in self._subscriptions:
                sub.cleanup = cleanup
                return sub_id
        # unsubscribe raced registration: tear the feed down now
        if cleanup is not None:
            cleanup()
        return sub_id

    def _notify(self, sub_id: str, item) -> None:
        sub = self._subscriptions.get(sub_id)
        if sub is not None and sub.active:
            sub.notify(item)

    def unsubscribe(self, sub_id: str) -> bool:
        with self.lock:
            sub = self._subscriptions.pop(sub_id, None)
        if sub is not None:
            sub.active = False
            if sub.cleanup is not None:
                sub.cleanup()
            return True
        return False

    # --- HTTP transport ---------------------------------------------------

    def serve_http(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Start the HTTP listener; returns the bound port."""
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                resp = server.handle_raw(body)
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(resp)))
                self.end_headers()
                self.wfile.write(resp)

            def log_message(self, *args):
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        thread.start()
        return self._httpd.server_address[1]

    # --- IPC transport ----------------------------------------------------

    def serve_ipc(self, path: str):
        """Unix-domain-socket endpoint (rpc/ipc.go): newline-delimited
        JSON-RPC, one connection per client, served on daemon threads.
        Returns a stop() callable."""
        import os
        import socket
        import socketserver

        try:
            os.unlink(path)
        except OSError:
            pass
        server = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                for line in self.rfile:
                    line = line.strip()
                    if not line:
                        continue
                    resp = server.handle_raw(line)
                    self.wfile.write(resp + b"\n")
                    self.wfile.flush()

        class _Srv(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True

        srv = _Srv(path, Handler)
        thread = threading.Thread(target=srv.serve_forever, daemon=True)
        thread.start()

        def stop():
            srv.shutdown()
            srv.server_close()
            try:
                os.unlink(path)
            except OSError:
                pass

        return stop

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd = None
