"""JSON-RPC 2.0 engine (role of /root/reference/rpc/{server,http,
websocket,subscription}.go).

Method registry keyed `namespace_method`, single + batch dispatch,
standard error codes, and pub/sub subscriptions. Serves over HTTP via the
stdlib ThreadingHTTPServer (handlers.go equivalents); tests can dispatch
in-process through `handle_raw`.

Overload behavior (ROBUSTNESS.md "Serving under overload"): when built
with a `ServingPolicy` (vm/api.create_handlers wires one from config),
dispatch runs on bounded cheap/expensive worker lanes, sheds `-32005`
(HTTP 429 + Retry-After) when a lane saturates, enforces cooperative
per-request deadlines, routes expensive methods through a circuit
breaker, and `stop()` drains in-flight work up to `rpc-drain-timeout`
before reporting what it abandoned. A bare `RPCServer()` (no policy)
dispatches inline exactly as the seed did.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional

from ..fault import failpoint, register
from ..metrics import default_registry, observe_slo
from ..metrics import spans as _spans
from ..metrics import tracectx
from ..utils.deadline import Deadline, DeadlineExceeded
from ..utils.deadline import scope as _deadline_scope
from .admission import (ABANDONED, LIMIT_EXCEEDED, TIMEOUT_ERROR,
                        ServingPolicy, Shed, is_expensive)

PARSE_ERROR = -32700
INVALID_REQUEST = -32600
METHOD_NOT_FOUND = -32601
INVALID_PARAMS = -32602
INTERNAL_ERROR = -32603

# Overload/slow-handler drills (tests, CORETH_TPU_FAILPOINTS): `hang`
# here parks a worker exactly like a wedged handler would.
register("rpc/before_dispatch",
         "before every RPC handler invocation (on the serving worker)")
register("rpc/before_dispatch_expensive",
         "before expensive-lane handlers only (eth_call/eth_getLogs/"
         "debug_trace*), after the generic before_dispatch point")


class RPCError(Exception):
    def __init__(self, code: int, message: str, data=None):
        super().__init__(message)
        self.code = code
        self.data = data


class Subscription:
    def __init__(self, sub_id: str, notify: Callable[[Any], None]):
        self.id = sub_id
        self.notify = notify
        self.active = True
        self.cleanup: Optional[Callable[[], None]] = None


class RPCServer:
    def __init__(self, policy: Optional[ServingPolicy] = None):
        self._methods: Dict[str, Callable] = {}
        self._subscriptions: Dict[str, Subscription] = {}
        self._sub_factories: Dict[str, Callable] = {}
        self.lock = threading.Lock()
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._ipc_stops: List[Callable[[], None]] = []
        self.policy = policy
        self._draining = False

    @property
    def draining(self) -> bool:
        """True once stop() has begun: /healthz flips to 503 so load
        balancers route away while in-flight work drains."""
        return self._draining

    # --- registration -----------------------------------------------------

    def register(self, namespace: str, name: str, fn: Callable) -> None:
        self._methods[f"{namespace}_{name}"] = fn

    def register_api(self, namespace: str, api: object) -> None:
        """Register every public method of [api] under [namespace]
        (rpc/service.go reflection registration)."""
        for attr in dir(api):
            if attr.startswith("_"):
                continue
            fn = getattr(api, attr)
            if callable(fn):
                self.register(namespace, attr, fn)

    def unregister(self, namespace: str, name: str) -> None:
        """Remove one method — API gating carve-outs (the reference's
        eth-apis list gates at sub-namespace granularity, vm.go:1140)."""
        self._methods.pop(f"{namespace}_{name}", None)

    def register_subscription(self, namespace: str, name: str,
                              factory: Callable) -> None:
        """factory(notify_fn, *params) -> cleanup_fn|None."""
        self._sub_factories[f"{namespace}_{name}"] = factory

    # --- dispatch ---------------------------------------------------------

    def handle_raw(self, raw: bytes, meta: Optional[dict] = None) -> bytes:
        """Dispatch one wire payload. [meta], when given, receives
        transport hints: `status` (429/503/413) + `retry_after` when the
        whole payload was shed, so HTTP can answer with the right code
        while IPC/WS just relay the JSON error object."""
        policy = self.policy
        if (policy is not None and policy.body_limit
                and len(raw) > policy.body_limit):
            default_registry.counter("rpc/body_oversize").inc()
            if meta is not None:
                meta["status"] = 413
            return self._encode_error(
                None, INVALID_REQUEST,
                f"request body too large "
                f"({len(raw)} > {policy.body_limit} bytes)")
        try:
            payload = json.loads(raw)
        except Exception:
            return self._encode_error(None, PARSE_ERROR, "parse error")
        if isinstance(payload, list):
            if not payload:
                return self._encode_error(None, INVALID_REQUEST, "empty batch")
            if (policy is not None and policy.batch_limit
                    and len(payload) > policy.batch_limit):
                default_registry.counter("rpc/batch_oversize").inc()
                return self._encode_error(
                    None, INVALID_REQUEST,
                    f"batch too large "
                    f"({len(payload)} > {policy.batch_limit} requests)")
            out = [self._handle_one(req, meta) for req in payload]
            self._finish_meta(meta, len(payload))
            return json.dumps([json.loads(o) for o in out if o]).encode()
        resp = self._handle_one(payload, meta)
        self._finish_meta(meta, 1)
        return resp

    @staticmethod
    def _finish_meta(meta: Optional[dict], total: int) -> None:
        """Fully-shed payloads surface as HTTP 429 (503 while draining —
        set at shed time); partial batch sheds stay 200 with per-item
        error objects, standard JSON-RPC batch semantics."""
        if meta is not None and meta.get("sheds", 0) >= total:
            meta.setdefault("status", 429)
            meta.setdefault("retry_after", 1)

    def _handle_one(self, req, meta: Optional[dict] = None) -> bytes:
        if not isinstance(req, dict):
            return self._encode_error(None, INVALID_REQUEST, "invalid request")
        req_id = req.get("id")
        method = req.get("method")
        if not isinstance(method, str):
            return self._encode_error(req_id, INVALID_REQUEST, "missing method")
        params = req.get("params", [])
        fn = self._methods.get(method)
        if fn is None:
            return self._encode_error(
                req_id, METHOD_NOT_FOUND, f"the method {method} does not exist"
            )
        # mint the request's trace context at admission: it rides the
        # lane handoff (WorkerPool.submit captures it), parents worker-side
        # spans, and stamps every shed/expiry/abandonment answer
        ctx = None
        if tracectx.enabled:
            parent_span_id = None
            if _spans.enabled:
                cur = _spans.tracer.current()
                if cur is not None:
                    parent_span_id = cur.span_id
            ctx = tracectx.begin("rpc", parent_span_id)
            ctx.meta["method"] = method
        t0 = time.monotonic()
        with tracectx.scope(ctx):
            resp = self._dispatch_one(req_id, method, fn, params, meta)
            elapsed = time.monotonic() - t0
            observe_slo("slo/rpc/" + method, elapsed,
                        ctx.trace_id if ctx is not None else None)
            if ctx is not None and "outcome" not in ctx.meta:
                policy = self.policy
                slo = policy.slo_budget if policy is not None else 0.0
                if 0 < slo < elapsed:
                    ctx.meta["over_slo_budget_s"] = slo
                    tracectx.capture(ctx, "slow")
        return resp

    def _dispatch_one(self, req_id, method, fn, params,
                      meta: Optional[dict]) -> bytes:
        policy = self.policy
        if policy is None:
            return self._run_handler(req_id, method, fn, params, None)[0]
        lane = policy.lane(method)
        deadline = None
        budget = policy.budget_for(method)
        if budget > 0:
            # the budget covers queue wait + execution: bounded latency,
            # not just bounded run time
            deadline = Deadline(budget)
            ctx = tracectx.current()
            if ctx is not None:
                ctx.meta["budget_s"] = budget
        if lane is None:
            return self._run_handler(req_id, method, fn, params, deadline)[0]
        return self._dispatch_pooled(req_id, method, fn, params, lane,
                                     deadline, meta)

    def _dispatch_pooled(self, req_id, method, fn, params, lane, deadline,
                         meta: Optional[dict]) -> bytes:
        policy = self.policy
        expensive = lane is policy.expensive_pool
        probe = False
        if expensive:
            verdict = policy.breaker.admit()
            if verdict == "shed":
                self._count_shed(method, "breaker", meta)
                return self._encode_error(
                    req_id, LIMIT_EXCEEDED,
                    "circuit breaker open: expensive methods are "
                    "timing out; retry later",
                    self._trace_capture("shed", reason="breaker",
                                        code=LIMIT_EXCEEDED))
            probe = verdict == "probe"
        try:
            fut = lane.submit(
                method,
                lambda: self._run_handler(req_id, method, fn, params,
                                          deadline))
        except Shed as s:
            self._count_shed(method, s.reason, meta)
            code = TIMEOUT_ERROR if s.reason == "draining" else LIMIT_EXCEEDED
            return self._encode_error(
                req_id, code, str(s),
                self._trace_capture("shed", reason=s.reason, code=code))
        # Cooperative handlers answer by their deadline; the wait backstop
        # only catches a handler that never reaches a checkpoint (its
        # worker stays lost until it returns — threads cannot be killed).
        wait_timeout = None
        if deadline is not None:
            wait_timeout = (deadline.remaining()
                            + max(1.0, 2.0 * deadline.budget))
        done, value = fut.wait(wait_timeout)
        if not done:
            default_registry.counter("rpc/timeout").inc()
            default_registry.counter("rpc/stuck_workers").inc()
            if expensive:
                policy.breaker.record(True, probe)
            return self._encode_error(
                req_id, TIMEOUT_ERROR,
                f"request exceeded its {deadline.budget:g}s budget "
                f"(handler missed every deadline checkpoint)",
                self._trace_capture("stuck", code=TIMEOUT_ERROR))
        if value is ABANDONED:
            return self._encode_error(
                req_id, TIMEOUT_ERROR,
                "server shut down before the request was served",
                self._trace_capture("abandoned", code=TIMEOUT_ERROR))
        resp, timed_out = value
        if expensive:
            policy.breaker.record(timed_out, probe)
        return resp

    def _run_handler(self, req_id, method, fn, params, deadline):
        """Invoke one handler (inline or on a lane worker).
        -> (response bytes, timed_out)."""
        try:
            failpoint("rpc/before_dispatch")
            if is_expensive(method):
                failpoint("rpc/before_dispatch_expensive")
            with _spans.span("rpc/" + method):
                with _deadline_scope(deadline):
                    if deadline is not None:
                        deadline.check()  # shed queue-expired work unrun
                    if isinstance(params, dict):
                        result = fn(**params)
                    else:
                        result = fn(*params)
        except DeadlineExceeded as e:
            default_registry.counter("rpc/timeout").inc()
            return self._encode_error(
                req_id, TIMEOUT_ERROR, str(e),
                self._trace_capture("deadline_expired",
                                    code=TIMEOUT_ERROR)), True
        except RPCError as e:
            return self._encode_error(req_id, e.code, str(e), e.data), False
        except TypeError as e:
            return self._encode_error(req_id, INVALID_PARAMS, str(e)), False
        except Exception as e:
            return self._encode_error(req_id, INTERNAL_ERROR, str(e)), False
        return json.dumps(
            {"jsonrpc": "2.0", "id": req_id, "result": result}
        ).encode(), False

    @staticmethod
    def _trace_capture(outcome: str, reason: Optional[str] = None,
                       code: Optional[int] = None) -> Optional[dict]:
        """Capture the calling thread's trace (if any) into the ring with
        [outcome], and return the error `data` payload carrying its id —
        None when tracing is off, so `_encode_error` stays clean."""
        ctx = tracectx.current()
        if ctx is None:
            return None
        ctx.meta["outcome"] = outcome
        if reason is not None:
            ctx.meta["shed_reason"] = reason
        if code is not None:
            ctx.meta["error_code"] = code
        tracectx.capture(ctx, outcome)
        return {"traceId": ctx.trace_id}

    @staticmethod
    def _count_shed(method: str, reason: str, meta: Optional[dict]) -> None:
        default_registry.counter("rpc/shed").inc()
        default_registry.counter(f"rpc/shed/{reason}").inc()
        if meta is not None:
            meta["sheds"] = meta.get("sheds", 0) + 1
            if reason == "draining":
                meta["status"] = 503
                meta["retry_after"] = 1

    @staticmethod
    def _encode_error(req_id, code: int, message: str, data=None) -> bytes:
        err = {"code": code, "message": message}
        if data is not None:
            err["data"] = data
        return json.dumps({"jsonrpc": "2.0", "id": req_id, "error": err}).encode()

    # --- subscriptions ----------------------------------------------------

    def subscribe(self, method: str, notify: Callable[[Any], None], *params) -> str:
        factory = self._sub_factories.get(method)
        if factory is None:
            raise RPCError(METHOD_NOT_FOUND, f"no subscription {method}")
        sub_id = "0x" + uuid.uuid4().hex
        sub = Subscription(sub_id, notify)
        with self.lock:
            self._subscriptions[sub_id] = sub
        try:
            cleanup = factory(lambda item: self._notify(sub_id, item), *params)
        except BaseException:
            with self.lock:
                self._subscriptions.pop(sub_id, None)
            raise
        with self.lock:
            if sub_id in self._subscriptions:
                sub.cleanup = cleanup
                return sub_id
        # unsubscribe raced registration: tear the feed down now
        if cleanup is not None:
            cleanup()
        return sub_id

    def _notify(self, sub_id: str, item) -> None:
        sub = self._subscriptions.get(sub_id)
        if sub is not None and sub.active:
            sub.notify(item)

    def unsubscribe(self, sub_id: str) -> bool:
        with self.lock:
            sub = self._subscriptions.pop(sub_id, None)
        if sub is not None:
            sub.active = False
            if sub.cleanup is not None:
                sub.cleanup()
            return True
        return False

    # --- HTTP transport ---------------------------------------------------

    def serve_http(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Start the HTTP listener; returns the bound port."""
        server = self
        policy = self.policy
        conn_sem = (threading.BoundedSemaphore(policy.max_connections)
                    if policy is not None and policy.max_connections > 0
                    else None)

        class Handler(BaseHTTPRequestHandler):
            def _respond(self, status: int, resp: bytes,
                         retry_after=None, close=False):
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(resp)))
                if retry_after is not None:
                    self.send_header("Retry-After", str(retry_after))
                if close:
                    self.send_header("Connection", "close")
                self.end_headers()
                self.wfile.write(resp)

            def do_POST(self):
                length = int(self.headers.get("Content-Length", 0) or 0)
                if (policy is not None and policy.body_limit
                        and length > policy.body_limit):
                    # reject on the declared length: never buffer a body
                    # the policy already rules out
                    default_registry.counter("rpc/body_oversize").inc()
                    self._respond(
                        413,
                        server._encode_error(
                            None, INVALID_REQUEST,
                            f"request body too large "
                            f"({length} > {policy.body_limit} bytes)"),
                        close=True)
                    return
                body = self.rfile.read(length)
                meta: dict = {}
                resp = server.handle_raw(body, meta)
                self._respond(meta.get("status", 200), resp,
                              meta.get("retry_after"))

            def log_message(self, *args):
                pass

        class _Srv(ThreadingHTTPServer):
            # hard cap on concurrent connections: past it the socket is
            # answered 429 inline instead of spawning a thread
            def process_request(self, request, client_address):
                if conn_sem is not None and not conn_sem.acquire(
                        blocking=False):
                    default_registry.counter("rpc/shed").inc()
                    default_registry.counter("rpc/shed/connections").inc()
                    try:
                        request.sendall(
                            b"HTTP/1.1 429 Too Many Requests\r\n"
                            b"Retry-After: 1\r\nContent-Length: 0\r\n"
                            b"Connection: close\r\n\r\n")
                    except OSError:
                        pass  # client gone: the 429 had no audience
                    self.shutdown_request(request)
                    return
                try:
                    super().process_request(request, client_address)
                except BaseException:
                    if conn_sem is not None:
                        conn_sem.release()
                    raise

            def process_request_thread(self, request, client_address):
                try:
                    super().process_request_thread(request, client_address)
                finally:
                    if conn_sem is not None:
                        conn_sem.release()

        self._httpd = _Srv((host, port), Handler)
        thread = threading.Thread(target=self._httpd.serve_forever, daemon=True)
        thread.start()
        return self._httpd.server_address[1]

    # --- IPC transport ----------------------------------------------------

    def serve_ipc(self, path: str):
        """Unix-domain-socket endpoint (rpc/ipc.go): newline-delimited
        JSON-RPC, one connection per client, served on daemon threads.
        Returns a stop() callable (also invoked by RPCServer.stop())."""
        import os
        import socketserver

        try:
            os.unlink(path)
        except OSError:
            pass
        server = self
        policy = self.policy
        body_limit = policy.body_limit if policy is not None else 0

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    # bounded readline: an endless unterminated line must
                    # not buffer past the body cap
                    line = self.rfile.readline(
                        body_limit + 2 if body_limit else -1)
                    if not line:
                        return
                    payload = line.rstrip(b"\r\n")
                    if body_limit and len(payload) > body_limit:
                        default_registry.counter("rpc/body_oversize").inc()
                        self.wfile.write(server._encode_error(
                            None, INVALID_REQUEST,
                            f"request body too large "
                            f"(> {body_limit} bytes)") + b"\n")
                        self.wfile.flush()
                        return  # the stream is mid-line: resync is a new conn
                    if not payload:
                        continue
                    resp = server.handle_raw(payload)
                    self.wfile.write(resp + b"\n")
                    self.wfile.flush()

        class _Srv(socketserver.ThreadingUnixStreamServer):
            daemon_threads = True

        srv = _Srv(path, Handler)
        thread = threading.Thread(target=srv.serve_forever, daemon=True)
        thread.start()

        def stop():
            srv.shutdown()
            srv.server_close()
            try:
                os.unlink(path)
            except OSError:
                pass

        self._ipc_stops.append(stop)
        return stop

    # --- shutdown ---------------------------------------------------------

    def serving_status(self) -> dict:
        """Live admission/breaker/drain state (debug_rpcStatus)."""
        if self.policy is None:
            return {"pooled": False}
        return self.policy.status()

    def stop(self, drain_timeout: Optional[float] = None) -> dict:
        """Stop accepting (HTTP + every IPC endpoint), drain in-flight
        dispatches up to [drain_timeout] (default: the rpc-drain-timeout
        knob), then report what was abandoned:
        {"drained": bool, "abandoned": n, "abandoned_methods": [...]}."""
        self._draining = True
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        ipc_stops, self._ipc_stops = self._ipc_stops, []
        for stop_ipc in ipc_stops:
            stop_ipc()
        if self.policy is None:
            return {"drained": True, "abandoned": 0, "abandoned_methods": []}
        return self.policy.drain(drain_timeout)
