"""Admission control for the RPC tier (the overload half of ROADMAP
item 4; see ROBUSTNESS.md "Serving under overload").

The serving model is the standard load-shedding ladder:

    admission (bounded queue, shed -32005)
      -> deadline (cooperative Deadline token, timeout -32000)
        -> breaker (consecutive expensive timeouts open it; probes close)
          -> drain (stop() bounded by rpc-drain-timeout, abandons loudly)

Two independent lanes — *cheap* and *expensive* (`eth_call`,
`eth_getLogs`, `debug_trace*`, ...) — each a fixed worker pool fed by a
bounded queue, so a storm of tracing can never starve `eth_blockNumber`.
A full queue sheds immediately with JSON-RPC `-32005 limit exceeded`
(HTTP 429 + Retry-After at the transport): under saturation the server
answers fast with "no" instead of queuing unboundedly and answering
slowly with "maybe".

The circuit breaker is *count-based*, not clock-based, mirroring the
device ladder's demote/probe/re-promote shape (ops/device.py): K
consecutive expensive-lane timeouts open it; while open every Nth
arrival is admitted as a probe; M consecutive probe successes close it.
Arrival-clocked state means overload drills replay deterministically.

Everything lives behind a `ServingPolicy` built from vm/config knobs;
an `RPCServer` without a policy dispatches inline exactly as before.
"""

from __future__ import annotations

import queue
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..metrics import default_registry
from ..metrics import tracectx
from ..utils.deadline import Deadline, DeadlineExceeded, scope as deadline_scope

__all__ = [
    "ABANDONED", "LIMIT_EXCEEDED", "TIMEOUT_ERROR", "CircuitBreaker",
    "Shed", "ServingPolicy", "WorkerPool", "is_expensive",
    "Deadline", "DeadlineExceeded", "deadline_scope",
]

# Sentinel future value for requests a drain gave up on: waiters check
# identity and answer their client with a shutdown error. First-set-wins
# futures make this safe even if the worker finishes later.
ABANDONED = object()

# JSON-RPC error codes the overload ladder speaks: -32005 is the
# conventional "limit exceeded" code (infura/geth rate-cap replies);
# -32000 is the generic server-error band used for deadline expiry and
# draining rejections.
LIMIT_EXCEEDED = -32005
TIMEOUT_ERROR = -32000

# Methods whose cost is unbounded in the request (state re-execution,
# multi-block scans). They dispatch on the expensive lane so their
# concurrency budget is independent of the cheap read path.
EXPENSIVE_METHODS = frozenset({
    "eth_call", "eth_callDetailed", "eth_estimateGas",
    "eth_createAccessList", "eth_getLogs", "eth_getProof",
    "eth_feeHistory",
})
EXPENSIVE_PREFIXES = (
    "debug_trace", "debug_dump", "debug_accountRange",
    "debug_storageRangeAt", "debug_getModifiedAccounts",
)

# Namespaces whose handlers honor deadline checkpoints. Operator/consensus
# surfaces (admin_importChain inserts blocks; avax_issueTx crosses into
# shared memory) must never be aborted mid-mutation by a read budget.
DEADLINE_NAMESPACES = ("eth_", "debug_", "personal_", "txpool_", "web3_",
                       "net_", "health_")


def is_expensive(method: str) -> bool:
    return (method in EXPENSIVE_METHODS
            or method.startswith(EXPENSIVE_PREFIXES))


class Shed(Exception):
    """Raised at submit time when a request cannot be admitted.
    `reason` is the metrics suffix: queue_full | breaker | draining."""

    def __init__(self, reason: str, message: str):
        super().__init__(message)
        self.reason = reason


class _Future:
    """Single-slot result holder; first set wins (a drain can answer a
    future whose worker later completes — the late result is dropped)."""

    __slots__ = ("_ev", "_value", "_lock")

    def __init__(self):
        self._ev = threading.Event()
        self._value = None
        self._lock = threading.Lock()

    def set(self, value) -> bool:
        with self._lock:
            if self._ev.is_set():
                return False
            self._value = value
            self._ev.set()
            return True

    def wait(self, timeout: Optional[float]):
        """-> (completed, value)."""
        if self._ev.wait(timeout):
            return True, self._value
        return False, None


class WorkerPool:
    """Fixed worker threads fed by a bounded admission queue.

    Workers spawn lazily on first submit (a server that only ever
    dispatches inline never owns threads). `drain()` stops admission,
    waits for in-flight + queued work on a Condition (no sleep-polling —
    SA006), then answers whatever is left with an error and reports it.
    """

    def __init__(self, name: str, workers: int, queue_size: int):
        self.name = name
        self.workers = workers
        # maxsize=0 means *unbounded* for queue.Queue — never allow it
        # here; the bounded queue IS the admission control (SA007).
        self._q: "queue.Queue[Optional[tuple]]" = queue.Queue(
            maxsize=max(1, queue_size))
        self._lock = threading.Lock()
        self._idle = threading.Condition(self._lock)
        self._spawned = 0
        self._inflight = 0
        # worker thread id -> (method, future): drain answers these
        self._active: Dict[int, Tuple[str, _Future]] = {}
        self._draining = False
        self._g_queue = default_registry.gauge(f"rpc/queue/{name}")
        self._g_inflight = default_registry.gauge(f"rpc/inflight/{name}")

    def submit(self, method: str, fn: Callable[[], object]) -> _Future:
        fut = _Future()
        # capture the admitting thread's trace context so the worker
        # thread that eventually runs fn inherits it (lane handoff)
        ctx = tracectx.current()
        with self._lock:
            if self._draining:
                raise Shed("draining", "server is draining")
            if self._spawned < self.workers:
                self._spawned += 1
                threading.Thread(target=self._loop, daemon=True,
                                 name=f"rpc-{self.name}-{self._spawned}").start()
        try:
            self._q.put_nowait((method, fn, fut, ctx))
        except queue.Full:
            raise Shed(
                "queue_full",
                f"{self.name} lane at capacity "
                f"({self.workers} workers, {self._q.maxsize} queued)")
        if ctx is not None:
            ctx.meta["lane"] = self.name
            ctx.meta["queued_behind"] = self._q.qsize() - 1
        self._g_queue.update(self._q.qsize())
        return fut

    def _loop(self) -> None:
        while True:
            item = self._q.get()
            if item is None:
                return
            method, fn, fut, ctx = item
            tid = threading.get_ident()
            with self._lock:
                self._inflight += 1
                self._active[tid] = (method, fut)
            self._g_queue.update(self._q.qsize())
            self._g_inflight.update(self._inflight)
            try:
                with tracectx.scope(ctx):
                    fut.set(fn())
            finally:
                with self._lock:
                    self._inflight -= 1
                    self._active.pop(tid, None)
                    if self._inflight == 0 and self._q.empty():
                        self._idle.notify_all()
                self._g_inflight.update(self._inflight)

    def busy(self) -> int:
        with self._lock:
            return self._inflight + self._q.qsize()

    def status(self) -> dict:
        with self._lock:
            return {
                "workers": self.workers,
                "queue_depth": self._q.qsize(),
                "queue_capacity": self._q.maxsize,
                "inflight": self._inflight,
                "active": sorted(m for m, _f in self._active.values()),
                "draining": self._draining,
            }

    def drain(self, timeout: float) -> Tuple[bool, List[str]]:
        """Stop admission, wait up to [timeout]s for quiescence, then
        answer leftovers with the ABANDONED sentinel so their waiters
        unblock deterministically. -> (clean, abandoned method names:
        still-running workers + never-started queue items)."""
        with self._lock:
            self._draining = True
            self._idle.wait_for(
                lambda: self._inflight == 0 and self._q.empty(),
                timeout=timeout)
            stuck = sorted(self._active.values(), key=lambda mf: mf[0])
        abandoned = []
        for method, fut in stuck:  # unblock waiters on wedged workers
            abandoned.append(method)
            fut.set(ABANDONED)
        while True:  # answer queued-but-never-started requests
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                break
            if item is None:
                continue
            method, _fn, fut, _ctx = item
            abandoned.append(method)
            fut.set(ABANDONED)
        for _ in range(self._spawned):  # release parked workers
            try:
                self._q.put_nowait(None)
            except queue.Full:
                break
        self._g_queue.update(0)
        return (not abandoned), abandoned


class CircuitBreaker:
    """Expensive-lane breaker, arrival-clocked for determinism.

    CLOSED --threshold consecutive timeouts--> OPEN
    OPEN: sheds; every probe_every-th arrival admitted as a probe
    OPEN --close_after consecutive probe passes--> CLOSED
    (a probe timeout resets the pass streak: stays OPEN)
    """

    def __init__(self, threshold: int, probe_every: int, close_after: int):
        self.threshold = threshold
        self.probe_every = max(1, probe_every)
        self.close_after = max(1, close_after)
        self._lock = threading.Lock()
        self._open = False
        self._consecutive_timeouts = 0
        self._probe_passes = 0
        self._arrivals_while_open = 0
        self._g_state = default_registry.gauge("rpc/breaker/state")
        self._c_opens = default_registry.counter("rpc/breaker/opens")
        self._c_closes = default_registry.counter("rpc/breaker/closes")
        self._g_state.update(0)

    def admit(self) -> str:
        """-> 'admit' | 'probe' | 'shed' for one expensive arrival."""
        if self.threshold <= 0:
            return "admit"
        with self._lock:
            if not self._open:
                return "admit"
            self._arrivals_while_open += 1
            if self._arrivals_while_open % self.probe_every == 0:
                return "probe"
            return "shed"

    def record(self, timed_out: bool, probe: bool) -> None:
        """Outcome of an admitted (or probed) expensive request."""
        if self.threshold <= 0:
            return
        with self._lock:
            if timed_out:
                self._probe_passes = 0
                if not self._open:
                    self._consecutive_timeouts += 1
                    if self._consecutive_timeouts >= self.threshold:
                        self._open = True
                        self._arrivals_while_open = 0
                        self._c_opens.inc()
                        self._g_state.update(1)
                return
            if self._open:
                if probe:
                    self._probe_passes += 1
                    if self._probe_passes >= self.close_after:
                        self._open = False
                        self._consecutive_timeouts = 0
                        self._probe_passes = 0
                        self._c_closes.inc()
                        self._g_state.update(0)
            else:
                self._consecutive_timeouts = 0

    def is_open(self) -> bool:
        with self._lock:
            return self._open

    def status(self) -> dict:
        with self._lock:
            return {
                "state": "open" if self._open else "closed",
                "threshold": self.threshold,
                "consecutive_timeouts": self._consecutive_timeouts,
                "probe_passes": self._probe_passes,
                "probe_every": self.probe_every,
                "close_after": self.close_after,
            }


class ServingPolicy:
    """All overload knobs in one object, owned by an RPCServer.

    rpc-max-workers = 0 disables pooling entirely (inline dispatch, no
    shedding, no breaker) — the bare-RPCServer/unit-test shape. Deadline
    budgets and batch/body caps still apply when set.
    """

    def __init__(self, *,
                 max_workers: int = 8,
                 queue_size: int = 64,
                 expensive_workers: int = 4,
                 expensive_queue_size: int = 16,
                 cheap_budget: float = 0.0,
                 expensive_budget: float = 0.0,
                 batch_limit: int = 100,
                 body_limit: int = 5 * 1024 * 1024,
                 breaker_threshold: int = 5,
                 breaker_probe_every: int = 8,
                 breaker_close_after: int = 3,
                 drain_timeout: float = 5.0,
                 max_connections: int = 128,
                 ws_notify_queue_size: int = 256,
                 slo_budget: float = 1.0):
        self.max_workers = max_workers
        self.cheap_budget = cheap_budget
        self.expensive_budget = expensive_budget or cheap_budget
        # completions slower than this (seconds) are auto-captured into
        # the trace ring even though they succeeded; 0 disables
        self.slo_budget = slo_budget
        self.batch_limit = batch_limit
        self.body_limit = body_limit
        self.drain_timeout = drain_timeout
        self.max_connections = max_connections
        self.ws_notify_queue_size = ws_notify_queue_size
        self.breaker = CircuitBreaker(
            breaker_threshold, breaker_probe_every, breaker_close_after)
        if max_workers > 0:
            self.cheap_pool: Optional[WorkerPool] = WorkerPool(
                "cheap", max_workers, queue_size)
            self.expensive_pool: Optional[WorkerPool] = WorkerPool(
                "expensive", expensive_workers, expensive_queue_size)
        else:
            self.cheap_pool = None
            self.expensive_pool = None
        self._drained = False

    @classmethod
    def from_config(cls, cfg) -> "ServingPolicy":
        """Build from a vm.config.Config (kebab-case knobs, defaults in
        the dataclass)."""
        return cls(
            max_workers=cfg.rpc_max_workers,
            queue_size=cfg.rpc_queue_size,
            expensive_workers=cfg.rpc_expensive_workers,
            expensive_queue_size=cfg.rpc_expensive_queue_size,
            cheap_budget=cfg.api_max_duration,
            expensive_budget=cfg.rpc_expensive_duration,
            batch_limit=cfg.rpc_batch_limit,
            body_limit=cfg.rpc_body_limit,
            breaker_threshold=cfg.rpc_breaker_threshold,
            breaker_probe_every=cfg.rpc_breaker_probe_every,
            breaker_close_after=cfg.rpc_breaker_close_after,
            drain_timeout=cfg.rpc_drain_timeout,
            max_connections=cfg.rpc_max_connections,
            ws_notify_queue_size=cfg.ws_notify_queue_size,
            slo_budget=cfg.rpc_slo_budget,
        )

    # --- dispatch helpers -------------------------------------------------

    def lane(self, method: str) -> Optional[WorkerPool]:
        if self.cheap_pool is None:
            return None
        return (self.expensive_pool if is_expensive(method)
                else self.cheap_pool)

    def budget_for(self, method: str) -> float:
        """Per-method deadline budget in seconds; 0 = no deadline."""
        if not method.startswith(DEADLINE_NAMESPACES):
            return 0.0
        return (self.expensive_budget if is_expensive(method)
                else self.cheap_budget)

    def status(self) -> dict:
        out = {
            "pooled": self.cheap_pool is not None,
            "breaker": self.breaker.status(),
            "batch_limit": self.batch_limit,
            "body_limit": self.body_limit,
            "cheap_budget": self.cheap_budget,
            "expensive_budget": self.expensive_budget,
            "slo_budget": self.slo_budget,
            "drain_timeout": self.drain_timeout,
            "drained": self._drained,
        }
        if self.cheap_pool is not None:
            out["cheap"] = self.cheap_pool.status()
            out["expensive"] = self.expensive_pool.status()
        return out

    def drain(self, timeout: Optional[float] = None) -> dict:
        """Drain both lanes; idempotent. -> report dict."""
        if self._drained:
            return {"drained": True, "abandoned": 0, "abandoned_methods": []}
        self._drained = True
        budget = self.drain_timeout if timeout is None else timeout
        deadline = Deadline(budget)  # ONE budget across both lanes
        abandoned: List[str] = []
        clean = True
        for pool in (self.cheap_pool, self.expensive_pool):
            if pool is None:
                continue
            ok, left = pool.drain(max(0.0, deadline.remaining()))
            clean = clean and ok
            abandoned.extend(left)
        if abandoned:
            default_registry.counter("rpc/abandoned").inc(len(abandoned))
        return {"drained": clean, "abandoned": len(abandoned),
                "abandoned_methods": abandoned}
