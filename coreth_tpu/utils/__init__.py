"""Shared helpers (role of /root/reference/utils/)."""

from __future__ import annotations

import os

_cache_enabled = False


def enable_compilation_cache(path: str | None = None) -> None:
    """Persist XLA compilations across processes.

    The keccak kernel compiles one program per (batch-bucket, block-bucket)
    shape; with the disk cache a fresh process (bench run, node restart)
    reuses them instead of paying the multi-second compile per shape again.
    """
    global _cache_enabled
    if _cache_enabled:
        return
    import jax

    cache_dir = path or os.environ.get(
        "CORETH_TPU_JAX_CACHE", os.path.expanduser("~/.cache/coreth_tpu_xla")
    )
    os.makedirs(cache_dir, exist_ok=True)
    try:
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass  # older jax without the knobs: cache is an optimization only
    _cache_enabled = True
