"""Race-detection harness — the test-time analog of `go test -race`
(SURVEY §5 concurrency discipline).

Go's race detector instruments memory accesses; Python's GIL hides most
word-level races but NOT compound-operation races (check-then-act,
iterate-while-mutate) — exactly the class the chain's locking discipline
must prevent. `RaceDetector.guard(obj, methods)` wraps methods so that
any wall-clock OVERLAP of two guarded calls from different threads is
recorded as a violation: if the owner's locks are correct, guarded
mutators can never overlap no matter how hard tests hammer the object.

Usage (tests/test_race_discipline.py):

    det = RaceDetector()
    det.guard(triedb, ["update", "commit", "dereference", "cap"])
    ... run concurrent chain load ...
    assert det.violations == []
"""

from __future__ import annotations

import functools
import threading
from typing import List


class RaceDetector:
    def __init__(self):
        self.violations: List[str] = []
        self._meta = threading.Lock()
        # (group, thread id) -> nesting depth; "any OTHER thread with
        # depth > 0 in my group" IS the overlap condition — one source of
        # truth, so a violation can never be masked by which thread
        # happened to enter first
        self._depth: dict = {}

    def guard(self, obj, methods) -> None:
        """Wrap [methods] of [obj]; overlapping entry from two threads into
        ANY pair of them is a violation (they form one exclusion group)."""
        group = id(obj)
        for name in methods:
            orig = getattr(obj, name)
            setattr(obj, name, self._wrap(group, name, orig))

    def _wrap(self, group, name, fn):
        @functools.wraps(fn)
        def wrapped(*a, **kw):
            me = threading.get_ident()
            with self._meta:
                others = [
                    t for (g, t), d in self._depth.items()
                    if g == group and t != me and d > 0
                ]
                if others:
                    self.violations.append(
                        f"{name} entered by thread {me} while threads "
                        f"{others} hold guarded methods"
                    )
                key = (group, me)
                self._depth[key] = self._depth.get(key, 0) + 1
            try:
                return fn(*a, **kw)
            finally:
                with self._meta:
                    key = (group, me)
                    self._depth[key] -= 1
                    if self._depth[key] == 0:
                        del self._depth[key]

        return wrapped
