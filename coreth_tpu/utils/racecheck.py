"""Race-detection harness — the test-time analog of `go test -race`
(SURVEY §5 concurrency discipline).

Go's race detector instruments memory accesses; Python's GIL hides most
word-level races but NOT compound-operation races (check-then-act,
iterate-while-mutate) — exactly the class the chain's locking discipline
must prevent. `RaceDetector.guard(obj, methods)` wraps methods so that
any wall-clock OVERLAP of two guarded calls from different threads is
recorded as a violation: if the owner's locks are correct, guarded
mutators can never overlap no matter how hard tests hammer the object.

Three modes:

* `guard(obj, methods)` — overlap detection: any wall-clock overlap of
  two guarded calls from different threads is a violation.
* `require_lock(obj, methods, lock_attr)` — lock-ownership detection:
  the named lock attribute is replaced with an owner-tracking proxy and
  every guarded method asserts on entry that the CURRENT thread holds
  that lock.  This is strictly stronger than overlap detection (it
  catches a caller that never takes the lock even when no other thread
  happens to be inside) and is the runtime twin of the static SA002
  `# guarded-by:` annotations.
* `LockOrderWitness` — acquisition-order detection: named locks are
  swapped for proxies that maintain a per-thread held stack; acquiring
  a lock ranked EARLIER in `CANONICAL_LOCK_ORDER` than one already held
  is a violation.  This is the runtime twin of the static SA013
  lock-order lint: SA013 proves the may-acquire graph is acyclic under
  its naming/resolution model, the witness checks that real executions
  match the canonical linearisation of that graph.

Usage (tests/test_race_discipline.py):

    det = RaceDetector()
    det.guard(triedb, ["update", "commit", "dereference", "cap"])
    det.require_lock(chain, ["_write_block"], "chainmu")
    ... run concurrent chain load ...
    assert det.violations == []
"""

from __future__ import annotations

import functools
import threading
import time
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from ..metrics import (
    DEFAULT_SLO_BUCKETS,
    count_drop,
    default_registry,
    sanitize_metric_name,
)

# The canonical single-process lock order, outermost first.  This is the
# checked-in linearisation of the may-acquire graph the static analyzer
# derives (SA013; `python -m coreth_tpu.analysis --graph locks` prints
# the live graph) restricted to the locks the chain's write/serve paths
# actually nest.  tests/test_static_analysis.py asserts every statically
# observed edge between members agrees with this tuple, so a refactor
# that inverts a nesting fails the lint before the witness ever runs.
#
# Notes on placement:
#  * BlockChain._degraded_mu has no static edge ordering it against
#    chainmu (the tail worker takes it bare); it sits after the chainmu
#    cluster because VM._build_block_inner's closure may take it while
#    VM.lock is held.
#  * InsertPipeline._mu never nests with chainmu by design (the commit
#    worker drains its queue BEFORE entering chainmu); listing both
#    still lets the witness catch a regression that nests them the
#    wrong way around.
CANONICAL_LOCK_ORDER: Tuple[str, ...] = (
    "VMServer._lock",
    "BlockBuilder.lock",
    "VM.lock",
    "BlockChain.chainmu",
    "BlockChain._acceptor_tip_lock",
    "BlockChain._insert_recs_mu",
    "BlockChain._view_mu",
    "BlockChain._degraded_mu",
    "InsertPipeline._mu",
    "TxPool.mu",
    "Registry._lock",
    "Tree.lock",
)


# --------------------------------------------------------------------------
# lock-contention telemetry (PR 20): every wrapped canonical lock records
# acquire-wait and hold time into lock/<name>/{wait,hold}_seconds SLO
# histograms, and holds longer than the slow-hold budget capture a
# traceback + trace id into the configured sink (the chain's flight
# recorder, wired by vm.py / the chaos conductor).  Instruments are
# pre-bound per canonical name at wrap time — never constructed on the
# acquire path (SA003's hot-path purity contract).

# seconds a single hold may last before it is captured; 0 disables
_slow_hold_budget: float = 0.0
# callable(dict) fed one event per budget breach (flight.note_event shape)
_slow_hold_sink = None
# bounded ring of recent breaches for debug_lockStatus (sink-less runs)
_recent_slow_holds: deque = deque(maxlen=32)

# pre-bound at import, like the per-lock histograms: the breach path must
# never construct instruments — default_registry.counter() acquires
# Registry._lock, which the chaos conductor witness-wraps, so a lazy bind
# during a slow hold OF Registry._lock would re-acquire the still-held
# non-reentrant inner lock on the same thread and deadlock
_c_slow_holds = default_registry.counter("lock/slow_holds")


def set_slow_hold_budget(seconds: float) -> None:
    global _slow_hold_budget
    _slow_hold_budget = max(0.0, float(seconds))


def slow_hold_budget() -> float:
    return _slow_hold_budget


def set_slow_hold_sink(sink) -> None:
    """Install the slow-hold event consumer (None disconnects). The sink
    must be cheap and non-raising; a raising sink only counts a drop."""
    global _slow_hold_sink
    _slow_hold_sink = sink


class _LockTelemetry:
    """Per-canonical-lock wait/hold histograms, created once per name."""

    __slots__ = ("name", "wait", "hold")

    def __init__(self, name: str):
        self.name = name
        self.wait = default_registry.histogram(
            f"lock/{name}/wait_seconds", buckets=DEFAULT_SLO_BUCKETS)
        self.hold = default_registry.histogram(
            f"lock/{name}/hold_seconds", buckets=DEFAULT_SLO_BUCKETS)


_telemetry_mu = threading.Lock()
_telemetry: Dict[str, _LockTelemetry] = {}
# sanitized exposition family -> canonical lock name: the exposition
# flattens `/`, `.` and `:` to `_`, and this mapping is what keeps the
# flattening invertible (the round-trip test asserts injectivity over
# CANONICAL_LOCK_ORDER plus the module-lock `module:NAME` form)
_family_to_canonical: Dict[str, str] = {}


def lock_telemetry(name: str) -> _LockTelemetry:
    with _telemetry_mu:
        tele = _telemetry.get(name)
        if tele is None:
            tele = _LockTelemetry(name)
            _telemetry[name] = tele
            for kind in ("wait", "hold"):
                fam = sanitize_metric_name(f"lock/{name}/{kind}_seconds")
                _family_to_canonical[fam] = name
        return tele


def canonical_for_family(family: str) -> Optional[str]:
    """Invert the exposition flattening: sanitized `lock_*_{wait,hold}_
    seconds` family name -> canonical lock name."""
    with _telemetry_mu:
        return _family_to_canonical.get(family)


def contention_table() -> List[Dict[str, object]]:
    """The debug_lockStatus payload: one row per instrumented lock,
    ranked by total measured acquire-wait (descending)."""
    with _telemetry_mu:
        items = list(_telemetry.items())
    rows = []
    for name, tele in items:
        rows.append({
            "lock": name,
            "wait_count": tele.wait.count(),
            "wait_total_seconds": tele.wait.sum(),
            "wait_p99_seconds": tele.wait.percentile(0.99),
            "hold_count": tele.hold.count(),
            "hold_total_seconds": tele.hold.sum(),
            "hold_p99_seconds": tele.hold.percentile(0.99),
        })
    rows.sort(key=lambda r: r["wait_total_seconds"], reverse=True)
    return rows


def recent_slow_holds() -> List[Dict[str, object]]:
    return list(_recent_slow_holds)


def _note_slow_hold(name: str, held_s: float) -> None:
    """Record one budget breach.  Callers MUST invoke this only AFTER the
    slow lock has been released: the sink may take arbitrary locks (the
    flight recorder does), and running it while the slow lock is still
    held would at best record spurious lock-order edges in the witness
    and at worst deadlock (a slow hold of Registry._lock meeting any
    registry access here)."""
    import traceback

    from ..metrics import tracectx

    _c_slow_holds.inc()
    ev = {
        "lock": name,
        "held_seconds": held_s,
        "budget_seconds": _slow_hold_budget,
        "thread": threading.current_thread().name,
        "trace_id": tracectx.current_id(),
        "stack": "".join(traceback.format_stack(limit=12)),
    }
    _recent_slow_holds.append(ev)
    sink = _slow_hold_sink
    if sink is not None:
        try:
            sink(ev)
        except Exception:  # noqa: BLE001 - telemetry must not raise into holders
            count_drop("drop/lock/slow_hold_sink")


class _OwnedLock:
    """Proxy around a Lock/RLock that records which thread holds it.

    Only the acquire/release surface is intercepted; everything else
    delegates to the wrapped lock, so Conditions built on it and direct
    `acquire(timeout=...)` callers keep working.  Reentrant acquisition
    is counted so RLock owners stay owners until the outermost release.
    """

    def __init__(self, inner, name: Optional[str] = None):
        self._inner = inner
        self._owner: int | None = None
        self._count = 0
        self._tele = lock_telemetry(name) if name else None
        self._hold_t0 = 0.0

    def acquire(self, *a, **kw):
        if self._tele is None:
            got = self._inner.acquire(*a, **kw)
        else:
            t0 = time.monotonic()
            got = self._inner.acquire(*a, **kw)
            if got:
                self._tele.wait.update(time.monotonic() - t0)
        if got:
            self._owner = threading.get_ident()
            self._count += 1
            if self._count == 1:
                self._hold_t0 = time.monotonic()
        return got

    def release(self):
        slow = 0.0
        if self._count > 0:
            self._count -= 1
            if self._count == 0:
                self._owner = None
                if self._tele is not None:
                    held = time.monotonic() - self._hold_t0
                    self._tele.hold.update(held)
                    if 0.0 < _slow_hold_budget <= held:
                        slow = held
        self._inner.release()
        if slow > 0.0:  # deferred past release — see _note_slow_hold
            _note_slow_hold(self._tele.name, slow)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def held_by_me(self) -> bool:
        return self._owner == threading.get_ident()

    def locked(self):
        return self._inner.locked()

    def __getattr__(self, name):
        return getattr(self._inner, name)


class _WitnessLock:
    """Proxy that reports acquire/release to a LockOrderWitness.

    Same delegation contract as `_OwnedLock`: only acquire/release (and
    the context-manager surface) are intercepted; `locked()`, timeouts
    and everything else pass through.  A failed `acquire(blocking=False)`
    is NOT reported — only actual possession enters the held stack.
    """

    def __init__(self, inner, name: str, witness: "LockOrderWitness"):
        self._inner = inner
        self._name = name
        self._witness = witness
        self._tele = lock_telemetry(name)
        # ownership-tracked depth on the PROXY (like _OwnedLock), not
        # threading.local: a plain Lock acquired on one thread and
        # released on another (legal, signal-style module locks) must
        # still close its hold span.  Re-entrant RLock holds time the
        # OUTERMOST span, matching what a contending thread experiences.
        self._owner: int | None = None
        self._count = 0
        self._hold_t0 = 0.0

    def acquire(self, *a, **kw):
        t0 = time.monotonic()
        got = self._inner.acquire(*a, **kw)
        if got:
            now = time.monotonic()
            self._tele.wait.update(now - t0)
            self._owner = threading.get_ident()
            self._count += 1
            if self._count == 1:
                self._hold_t0 = now
            self._witness._note_acquire(self._name)
        return got

    def release(self):
        slow = 0.0
        if self._count > 0:
            self._count -= 1
            if self._count == 0:
                self._owner = None
                held = time.monotonic() - self._hold_t0
                self._tele.hold.update(held)
                if 0.0 < _slow_hold_budget <= held:
                    slow = held
        self._inner.release()
        self._witness._note_release(self._name)
        if slow > 0.0:  # deferred past release — see _note_slow_hold
            _note_slow_hold(self._name, slow)

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._inner.locked()

    def __getattr__(self, name):
        return getattr(self._inner, name)


class LockOrderWitness:
    """Runtime lock-order recorder + checker (the SA013 runtime twin).

    `wrap(obj, attr, name)` swaps [obj].[attr] for a `_WitnessLock`
    whose canonical [name] matches the static analyzer's `Owner.attr`
    naming.  Each thread keeps a stack of held lock names; on every
    acquisition the witness

      * records an observed edge (held -> acquired) for each lock the
        thread already holds (re-entrant re-acquisition of the same
        name is skipped — chainmu is an RLock), and
      * flags a violation if the acquired lock is ranked EARLIER in the
        canonical order than any held lock.  Locks absent from the
        order are recorded (the edge set is still useful triage) but
        never flagged, so partially instrumented runs stay quiet.

    Known blind spots: a `threading.Condition` constructed on a lock
    BEFORE the wrap keeps a reference to the raw inner lock, so waits/
    notifies through the condition bypass the proxy.  None of the locks
    in `CANONICAL_LOCK_ORDER` back a Condition today; the chaos
    conductor wraps at boot, right after construction, to keep it that
    way.  And while hold TIMING survives a cross-thread release (the
    proxy tracks depth by ownership, not thread), the per-thread held
    STACKS here do not: a lock released by a thread that never acquired
    it stays on the acquirer's stack, so signal-style locks should not
    be witness-wrapped where order checking matters.
    """

    def __init__(self, order: Sequence[str] = CANONICAL_LOCK_ORDER):
        self._rank = {name: i for i, name in enumerate(order)}
        self.violations: List[str] = []
        # observed (outer, inner) pairs, for edge-set assertions in tests
        self.edges: set = set()
        self._meta = threading.Lock()
        self._held = threading.local()
        # cross-thread-readable mirror of the per-thread held stacks:
        # ident -> tuple(names).  `threading.local` is invisible from the
        # sampling profiler's thread, so every acquire/release also
        # publishes an immutable snapshot with a single GIL-atomic dict
        # write — no lock on the acquire path.
        self._held_by_ident: Dict[int, Tuple[str, ...]] = {}
        self._wrapped: List[tuple] = []

    def wrap(self, obj, attr: str, name: Optional[str] = None):
        """Swap [obj].[attr] for a witness proxy named `Owner.attr` (or
        [name]).  Idempotent: an already-wrapped lock is left alone."""
        inner = getattr(obj, attr)
        if isinstance(inner, _WitnessLock):
            return inner
        proxy = _WitnessLock(
            inner, name or f"{type(obj).__name__}.{attr}", self)
        setattr(obj, attr, proxy)
        self._wrapped.append((obj, attr, inner))
        if self not in _ACTIVE_WITNESSES:
            _ACTIVE_WITNESSES.append(self)
        return proxy

    def unwrap_all(self) -> None:
        """Restore every wrapped attribute (process-global singletons —
        the metrics registry — must not keep witness proxies after the
        harness that installed them is torn down)."""
        for obj, attr, inner in reversed(self._wrapped):
            try:
                setattr(obj, attr, inner)
            except AttributeError:
                pass
        self._wrapped.clear()
        try:
            _ACTIVE_WITNESSES.remove(self)
        except ValueError:
            pass
        self._held_by_ident.clear()

    def _stack(self) -> List[str]:
        st = getattr(self._held, "stack", None)
        if st is None:
            st = self._held.stack = []
        return st

    def _note_acquire(self, name: str) -> None:
        stack = self._stack()
        if name in stack:  # RLock re-entry: no new edge, no new rank
            stack.append(name)
            self._publish(stack)
            return
        rank = self._rank.get(name)
        with self._meta:
            for held in stack:
                if held != name:
                    self.edges.add((held, name))
            if rank is not None:
                worst = [h for h in stack
                         if self._rank.get(h, -1) > rank]
                if worst:
                    self.violations.append(
                        f"thread {threading.get_ident()} acquired {name} "
                        f"(rank {rank}) while holding "
                        f"{' -> '.join(dict.fromkeys(stack))} "
                        f"(violates canonical order via {worst[-1]})")
        stack.append(name)
        self._publish(stack)

    def _note_release(self, name: str) -> None:
        stack = self._stack()
        # release order need not mirror acquire order; drop the deepest
        # occurrence so re-entrant holds unwind correctly
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == name:
                del stack[i]
                self._publish(stack)
                return

    def _publish(self, stack: List[str]) -> None:
        ident = threading.get_ident()
        if stack:
            self._held_by_ident[ident] = tuple(stack)
        else:
            self._held_by_ident.pop(ident, None)

    def held_by_ident(self) -> Dict[int, Tuple[str, ...]]:
        """Point-in-time copy of which thread holds which witnessed
        locks; safe to call from any thread (the profiler's sampler)."""
        return dict(self._held_by_ident)


# Witnesses with live wraps, so the profiler can tag samples with the
# locks the sampled thread holds without a reference to the harness that
# installed them.  Appended on first wrap, removed in unwrap_all().
_ACTIVE_WITNESSES: List["LockOrderWitness"] = []


def held_locks_snapshot() -> Dict[int, Tuple[str, ...]]:
    """Merged ident -> held-lock-names view across all live witnesses."""
    out: Dict[int, Tuple[str, ...]] = {}
    for w in list(_ACTIVE_WITNESSES):
        for ident, names in w.held_by_ident().items():
            out[ident] = out.get(ident, ()) + names
    return out


class RaceDetector:
    def __init__(self):
        self.violations: List[str] = []
        self._meta = threading.Lock()
        # (group, thread id) -> nesting depth; "any OTHER thread with
        # depth > 0 in my group" IS the overlap condition — one source of
        # truth, so a violation can never be masked by which thread
        # happened to enter first
        self._depth: dict = {}

    def guard(self, obj, methods) -> None:
        """Wrap [methods] of [obj]; overlapping entry from two threads into
        ANY pair of them is a violation (they form one exclusion group)."""
        group = id(obj)
        for name in methods:
            orig = getattr(obj, name)
            setattr(obj, name, self._wrap(group, name, orig))

    def require_lock(self, obj, methods, lock_attr: str) -> None:
        """Assert [obj].[lock_attr] is held by the calling thread on entry
        to each of [methods].  The lock attribute is swapped for an
        owner-tracking proxy (idempotent: re-wrapping reuses the proxy),
        so the object's own `with self.<lock>` blocks keep working and
        feed the ownership record."""
        lock = getattr(obj, lock_attr)
        if not isinstance(lock, _OwnedLock):
            lock = _OwnedLock(lock, name=f"{type(obj).__name__}.{lock_attr}")
            setattr(obj, lock_attr, lock)
        for name in methods:
            orig = getattr(obj, name)
            setattr(obj, name, self._wrap_owned(name, lock_attr, lock, orig))

    def _wrap_owned(self, name, lock_attr, lock: _OwnedLock, fn):
        @functools.wraps(fn)
        def wrapped(*a, **kw):
            if not lock.held_by_me():
                with self._meta:
                    self.violations.append(
                        f"{name} entered by thread {threading.get_ident()} "
                        f"without holding {lock_attr}"
                    )
            return fn(*a, **kw)

        return wrapped

    def _wrap(self, group, name, fn):
        @functools.wraps(fn)
        def wrapped(*a, **kw):
            me = threading.get_ident()
            with self._meta:
                others = [
                    t for (g, t), d in self._depth.items()
                    if g == group and t != me and d > 0
                ]
                if others:
                    self.violations.append(
                        f"{name} entered by thread {me} while threads "
                        f"{others} hold guarded methods"
                    )
                key = (group, me)
                self._depth[key] = self._depth.get(key, 0) + 1
            try:
                return fn(*a, **kw)
            finally:
                with self._meta:
                    key = (group, me)
                    self._depth[key] -= 1
                    if self._depth[key] == 0:
                        del self._depth[key]

        return wrapped
