"""Race-detection harness — the test-time analog of `go test -race`
(SURVEY §5 concurrency discipline).

Go's race detector instruments memory accesses; Python's GIL hides most
word-level races but NOT compound-operation races (check-then-act,
iterate-while-mutate) — exactly the class the chain's locking discipline
must prevent. `RaceDetector.guard(obj, methods)` wraps methods so that
any wall-clock OVERLAP of two guarded calls from different threads is
recorded as a violation: if the owner's locks are correct, guarded
mutators can never overlap no matter how hard tests hammer the object.

Two modes:

* `guard(obj, methods)` — overlap detection: any wall-clock overlap of
  two guarded calls from different threads is a violation.
* `require_lock(obj, methods, lock_attr)` — lock-ownership detection:
  the named lock attribute is replaced with an owner-tracking proxy and
  every guarded method asserts on entry that the CURRENT thread holds
  that lock.  This is strictly stronger than overlap detection (it
  catches a caller that never takes the lock even when no other thread
  happens to be inside) and is the runtime twin of the static SA002
  `# guarded-by:` annotations.

Usage (tests/test_race_discipline.py):

    det = RaceDetector()
    det.guard(triedb, ["update", "commit", "dereference", "cap"])
    det.require_lock(chain, ["_write_block"], "chainmu")
    ... run concurrent chain load ...
    assert det.violations == []
"""

from __future__ import annotations

import functools
import threading
from typing import List


class _OwnedLock:
    """Proxy around a Lock/RLock that records which thread holds it.

    Only the acquire/release surface is intercepted; everything else
    delegates to the wrapped lock, so Conditions built on it and direct
    `acquire(timeout=...)` callers keep working.  Reentrant acquisition
    is counted so RLock owners stay owners until the outermost release.
    """

    def __init__(self, inner):
        self._inner = inner
        self._owner: int | None = None
        self._count = 0

    def acquire(self, *a, **kw):
        got = self._inner.acquire(*a, **kw)
        if got:
            self._owner = threading.get_ident()
            self._count += 1
        return got

    def release(self):
        if self._count > 0:
            self._count -= 1
            if self._count == 0:
                self._owner = None
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def held_by_me(self) -> bool:
        return self._owner == threading.get_ident()

    def locked(self):
        return self._inner.locked()

    def __getattr__(self, name):
        return getattr(self._inner, name)


class RaceDetector:
    def __init__(self):
        self.violations: List[str] = []
        self._meta = threading.Lock()
        # (group, thread id) -> nesting depth; "any OTHER thread with
        # depth > 0 in my group" IS the overlap condition — one source of
        # truth, so a violation can never be masked by which thread
        # happened to enter first
        self._depth: dict = {}

    def guard(self, obj, methods) -> None:
        """Wrap [methods] of [obj]; overlapping entry from two threads into
        ANY pair of them is a violation (they form one exclusion group)."""
        group = id(obj)
        for name in methods:
            orig = getattr(obj, name)
            setattr(obj, name, self._wrap(group, name, orig))

    def require_lock(self, obj, methods, lock_attr: str) -> None:
        """Assert [obj].[lock_attr] is held by the calling thread on entry
        to each of [methods].  The lock attribute is swapped for an
        owner-tracking proxy (idempotent: re-wrapping reuses the proxy),
        so the object's own `with self.<lock>` blocks keep working and
        feed the ownership record."""
        lock = getattr(obj, lock_attr)
        if not isinstance(lock, _OwnedLock):
            lock = _OwnedLock(lock)
            setattr(obj, lock_attr, lock)
        for name in methods:
            orig = getattr(obj, name)
            setattr(obj, name, self._wrap_owned(name, lock_attr, lock, orig))

    def _wrap_owned(self, name, lock_attr, lock: _OwnedLock, fn):
        @functools.wraps(fn)
        def wrapped(*a, **kw):
            if not lock.held_by_me():
                with self._meta:
                    self.violations.append(
                        f"{name} entered by thread {threading.get_ident()} "
                        f"without holding {lock_attr}"
                    )
            return fn(*a, **kw)

        return wrapped

    def _wrap(self, group, name, fn):
        @functools.wraps(fn)
        def wrapped(*a, **kw):
            me = threading.get_ident()
            with self._meta:
                others = [
                    t for (g, t), d in self._depth.items()
                    if g == group and t != me and d > 0
                ]
                if others:
                    self.violations.append(
                        f"{name} entered by thread {me} while threads "
                        f"{others} hold guarded methods"
                    )
                key = (group, me)
                self._depth[key] = self._depth.get(key, 0) + 1
            try:
                return fn(*a, **kw)
            finally:
                with self._meta:
                    key = (group, me)
                    self._depth[key] -= 1
                    if self._depth[key] == 0:
                        del self._depth[key]

        return wrapped
