"""Cooperative per-request deadlines (role of Go's context.Context
deadline threading through the reference's rpc handlers).

Threads cannot be cancelled; a request that must not outlive its budget
has to *check* — so the primitive here is a monotonic-clock `Deadline`
token installed in a thread-local by the RPC dispatch layer
(`rpc/admission.py`) and polled at loop boundaries: the `eth_getLogs`
block scan, the tracers' per-tx replay loop, and EVM frame entry. The
hot EVM step loop is deliberately *not* instrumented (SA003: `# hot-path`
functions read no wall clock); gas bounds a single frame, the frame
boundary bounds a call tree.

`check()` is the universal checkpoint: one thread-local read when no
deadline is armed (the consensus path never arms one), a monotonic
compare when one is. Expiry raises `DeadlineExceeded`, which the
dispatch layer maps to a JSON-RPC timeout error and a freed worker.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

__all__ = ["Deadline", "DeadlineExceeded", "check", "current", "remaining",
           "scope"]


class DeadlineExceeded(Exception):
    """A cooperative checkpoint found the request past its budget.  When
    the expiring thread carries a trace context the trace id is stamped
    on (`trace_id`), so the dispatch layer can attribute the expiry
    end-to-end without re-deriving ambient state."""

    def __init__(self, budget: float, trace_id: Optional[str] = None):
        msg = f"request exceeded its {budget:g}s budget"
        if trace_id:
            msg += f" [trace {trace_id}]"
        super().__init__(msg)
        self.budget = budget
        self.trace_id = trace_id


class Deadline:
    """Monotonic-clock budget token for one request."""

    __slots__ = ("budget", "_expires")

    def __init__(self, budget: float):
        self.budget = budget
        self._expires = time.monotonic() + budget

    def remaining(self) -> float:
        return self._expires - time.monotonic()

    def expired(self) -> bool:
        return time.monotonic() >= self._expires

    def check(self) -> None:
        if time.monotonic() >= self._expires:
            # the import and ambient lookup only run on the expiry path,
            # never on the no-op checkpoint fast path
            from ..metrics import tracectx

            raise DeadlineExceeded(self.budget, tracectx.current_id())


_tls = threading.local()


def current() -> Optional[Deadline]:
    """The calling thread's armed deadline, or None."""
    return getattr(_tls, "deadline", None)


def check() -> None:
    """The cooperative checkpoint: free when nothing is armed."""
    d = getattr(_tls, "deadline", None)
    if d is not None:
        d.check()


def remaining(default: float) -> float:
    """Budget left for one sub-operation: [default] when no deadline is
    armed on this thread, otherwise the armed deadline's remaining time
    clamped to [0, default] — so a per-request-class timeout never
    outlives the caller's overall budget."""
    d = getattr(_tls, "deadline", None)
    if d is None:
        return default
    return max(0.0, min(default, d.remaining()))


class scope:
    """Install [deadline] on this thread for the `with` body (nestable;
    the previous deadline is restored on exit). Pass None for a no-op
    scope so call sites stay unconditional."""

    __slots__ = ("deadline", "_prev")

    def __init__(self, deadline: Optional[Deadline]):
        self.deadline = deadline

    def __enter__(self) -> Optional[Deadline]:
        self._prev = getattr(_tls, "deadline", None)
        if self.deadline is not None:
            _tls.deadline = self.deadline
        return self.deadline

    def __exit__(self, *exc) -> None:
        _tls.deadline = self._prev
