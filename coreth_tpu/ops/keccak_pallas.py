"""Pallas TPU kernel for batched Keccak-f[1600] / Keccak-256.

Same math and host layout contract as coreth_tpu/ops/keccak_jax.py, but the
whole sponge runs inside one Pallas kernel so the 25-lane state lives in VMEM
(registers) across all 24 rounds and all rate blocks — no HBM traffic between
rounds. The batch is laid out with lanes on the last two axes as (8, 128)
tiles to match the TPU VPU shape.

Replaces the CPU hasher fan-out of the reference (/root/reference/trie/
hasher.go:124-139) with a data-parallel device kernel.

Layout (device side):
    words:   uint32[L, 34, R, 128]  -- R*128 lanes, R multiple of 8
    nblocks: int32[R, 128]
    out:     uint32[8, R, 128]
Grid: (R // 8,) over batch tiles; each program hashes 1024 lanes.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .keccak_ref import _ROUND_CONSTANTS, _ROTC

WORDS_PER_BLOCK = 34
_RC_LO = tuple(rc & 0xFFFFFFFF for rc in _ROUND_CONSTANTS)
_RC_HI = tuple(rc >> 32 for rc in _ROUND_CONSTANTS)

# Unroll the rate-block loop when small (trie nodes are 1-5 blocks); fall back
# to fori_loop with dynamic block indexing for large inputs (contract code).
_UNROLL_MAX_BLOCKS = 8


def _rotl_pair(lo, hi, n: int):
    n %= 64
    if n == 0:
        return lo, hi
    if n == 32:
        return hi, lo
    if n > 32:
        lo, hi = hi, lo
        n -= 32
    m = 32 - n
    return (lo << n) | (hi >> m), (hi << n) | (lo >> m)


def _permute(lo, hi):
    for r in range(24):
        c_lo = [lo[x] ^ lo[x + 5] ^ lo[x + 10] ^ lo[x + 15] ^ lo[x + 20] for x in range(5)]
        c_hi = [hi[x] ^ hi[x + 5] ^ hi[x + 10] ^ hi[x + 15] ^ hi[x + 20] for x in range(5)]
        d_lo, d_hi = [], []
        for x in range(5):
            rl, rh = _rotl_pair(c_lo[(x + 1) % 5], c_hi[(x + 1) % 5], 1)
            d_lo.append(c_lo[(x - 1) % 5] ^ rl)
            d_hi.append(c_hi[(x - 1) % 5] ^ rh)
        lo = [lo[i] ^ d_lo[i % 5] for i in range(25)]
        hi = [hi[i] ^ d_hi[i % 5] for i in range(25)]
        b_lo = [None] * 25
        b_hi = [None] * 25
        for x in range(5):
            for y in range(5):
                src = x + 5 * y
                dst = y + 5 * ((2 * x + 3 * y) % 5)
                b_lo[dst], b_hi[dst] = _rotl_pair(lo[src], hi[src], _ROTC[src])
        lo = [
            b_lo[i] ^ (~b_lo[(i % 5 + 1) % 5 + 5 * (i // 5)] & b_lo[(i % 5 + 2) % 5 + 5 * (i // 5)])
            for i in range(25)
        ]
        hi = [
            b_hi[i] ^ (~b_hi[(i % 5 + 1) % 5 + 5 * (i // 5)] & b_hi[(i % 5 + 2) % 5 + 5 * (i // 5)])
            for i in range(25)
        ]
        lo[0] = lo[0] ^ jnp.uint32(_RC_LO[r])
        hi[0] = hi[0] ^ jnp.uint32(_RC_HI[r])
    return lo, hi


def _absorb_permute_snapshot(lo, hi, out, block_words, j, nb):
    """Absorb one masked rate block, permute, snapshot finished lanes."""
    live = j < nb
    zero = jnp.zeros_like(lo[0])
    lo = list(lo)
    hi = list(hi)
    for i in range(17):
        lo[i] = lo[i] ^ jnp.where(live, block_words[2 * i], zero)
        hi[i] = hi[i] ^ jnp.where(live, block_words[2 * i + 1], zero)
    lo, hi = _permute(lo, hi)
    digest = [lo[0], hi[0], lo[1], hi[1], lo[2], hi[2], lo[3], hi[3]]
    is_last = j == nb - 1
    out = [jnp.where(is_last, digest[w], out[w]) for w in range(8)]
    return tuple(lo), tuple(hi), tuple(out)


def _make_kernel(num_blocks: int):
    def kernel(words_ref, nblocks_ref, out_ref):
        nb = nblocks_ref[:]
        zeros = jnp.zeros(nb.shape, jnp.uint32)
        lo = (zeros,) * 25
        hi = (zeros,) * 25
        out = (zeros,) * 8
        if num_blocks <= _UNROLL_MAX_BLOCKS:
            for j in range(num_blocks):
                block = [words_ref[j, w] for w in range(WORDS_PER_BLOCK)]
                lo, hi, out = _absorb_permute_snapshot(
                    lo, hi, out, block, jnp.int32(j), nb
                )
        else:
            def body(j, carry):
                lo, hi, out = carry
                block = [words_ref[j, w] for w in range(WORDS_PER_BLOCK)]
                return _absorb_permute_snapshot(lo, hi, out, block, j, nb)

            lo, hi, out = jax.lax.fori_loop(0, num_blocks, body, (lo, hi, out))
        for w in range(8):
            out_ref[w] = out[w]

    return kernel


@functools.partial(jax.jit, static_argnames=("interpret",))
def keccak256_blocks_pallas(words: jax.Array, nblocks: jax.Array, interpret: bool = False):
    """Pallas drop-in for keccak_jax.keccak256_blocks.

    words: uint32[B, L, 34]; nblocks: int32[B]; B must be a multiple of 1024.
    Returns uint32[B, 8].
    """
    b, num_blocks, _ = words.shape
    assert b % 1024 == 0, "pallas keccak batch must be padded to 1024 lanes"
    rows = b // 128
    w = jnp.transpose(words, (1, 2, 0)).reshape(num_blocks, WORDS_PER_BLOCK, rows, 128)
    nb = nblocks.reshape(rows, 128)

    grid = (rows // 8,)
    out = pl.pallas_call(
        _make_kernel(num_blocks),
        out_shape=jax.ShapeDtypeStruct((8, rows, 128), jnp.uint32),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (num_blocks, WORDS_PER_BLOCK, 8, 128), lambda r: (0, 0, r, 0)
            ),
            pl.BlockSpec((8, 128), lambda r: (r, 0)),
        ],
        out_specs=pl.BlockSpec((8, 8, 128), lambda r: (0, r, 0)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(w, nb)
    return jnp.transpose(out.reshape(8, b), (1, 0))


def pallas_impl(interpret: bool = False):
    """Implementation callable for BatchedKeccak (batch_multiple=1024)."""

    def impl(words, nblocks):
        return keccak256_blocks_pallas(words, nblocks, interpret=interpret)

    return impl


# ---------------------------------------------------------------------------
# Segment kernel for the staged commit (ops/keccak_staged.py)
# ---------------------------------------------------------------------------


def _make_segment_kernel(num_blocks: int):
    """Mask-free variant: every lane has exactly num_blocks rate blocks
    (the native planner buckets segments by exact block count), so there is
    no nblocks input, no live-lane masking, and no digest snapshotting —
    the digest is simply the state after the final permutation."""

    def kernel(words_ref, out_ref):
        shape = words_ref.shape[-2:]  # (8, 128) lane tile
        zeros = jnp.zeros(shape, jnp.uint32)
        lo = [zeros] * 25
        hi = [zeros] * 25

        def absorb_permute(lo, hi, j):
            lo = list(lo)
            hi = list(hi)
            for i in range(17):
                lo[i] = lo[i] ^ words_ref[j, 2 * i]
                hi[i] = hi[i] ^ words_ref[j, 2 * i + 1]
            return _permute(lo, hi)

        if num_blocks <= _UNROLL_MAX_BLOCKS:
            for j in range(num_blocks):
                lo, hi = absorb_permute(lo, hi, j)
        else:
            def body(j, carry):
                lo, hi = carry
                lo, hi = absorb_permute(list(lo), list(hi), j)
                return tuple(lo), tuple(hi)

            lo, hi = jax.lax.fori_loop(
                0, num_blocks, body, (tuple(lo), tuple(hi))
            )
        digest = [lo[0], hi[0], lo[1], hi[1], lo[2], hi[2], lo[3], hi[3]]
        for w in range(8):
            out_ref[w] = digest[w]

    return kernel


def segment_keccak_pallas(words: jax.Array, interpret: bool = False) -> jax.Array:
    """uint32[P, L, 34] -> uint32[P, 8]; P must be a multiple of 1024.

    Drop-in for keccak_staged._segment_keccak on lane counts the TPU grid
    can tile (the staged runner falls back to the XLA scan for smaller
    segments). State lives in VMEM across every round and block — one HBM
    read of the message words, one HBM write of digests."""
    p, num_blocks, _ = words.shape
    assert p % 1024 == 0, "pallas segment batch must be a multiple of 1024 lanes"
    rows = p // 128
    w = jnp.transpose(words, (1, 2, 0)).reshape(
        num_blocks, WORDS_PER_BLOCK, rows, 128
    )
    grid = (rows // 8,)
    out = pl.pallas_call(
        _make_segment_kernel(num_blocks),
        out_shape=jax.ShapeDtypeStruct((8, rows, 128), jnp.uint32),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (num_blocks, WORDS_PER_BLOCK, 8, 128), lambda r: (0, 0, r, 0)
            ),
        ],
        out_specs=pl.BlockSpec((8, 8, 128), lambda r: (0, r, 0)),
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
        ),
        interpret=interpret,
    )(w)
    return jnp.transpose(out.reshape(8, p), (1, 0))


def staged_seg_impl(interpret: bool = False):
    """seg_impl for keccak_staged.StagedCommit: Pallas for big segments,
    XLA scan fallback below the 1024-lane grid minimum (shape decision is
    static at trace time)."""

    def impl(words):
        if words.shape[0] % 1024 == 0:
            return segment_keccak_pallas(words, interpret=interpret)
        from .keccak_staged import _segment_keccak

        return _segment_keccak(words)

    return impl
