"""Staged trie commit: per-segment dispatches, async-pipelined, with the
digest array resident on device.

The fused single-dispatch design (keccak_fused.py) inlines every segment
into one XLA module — minimal dispatch count, but the compile time grows
with segment count (~170s for a 200k-leaf commit's ~30 segments on TPU)
and the whole 50+MB transfer must complete before compute starts.

The staged design instead jits ONE small program per segment *shape*
(blocks, lanes, patch count) and chains them through a donated device
digest buffer:

    dig8 = zeros[G, 32]                        # device-resident
    for seg in plan.segments:                  # host loop, all async
        x    = device_put(seg.bytes)           # h2d overlaps earlier compute
        dig8 = seg_step(dig8, x, patches, gstart)
    root = dig8[root_pos]                      # the only forced sync

Dispatches never synchronize in between, so XLA pipelines transfer of
segment k+1 with compute of segment k; the jit cache is keyed by a small
set of shapes (lane counts pad pow2<=8192 then multiples of 8192) that the
persistent compilation cache reuses across processes.

Within a segment every lane has the SAME rate-block count (the planner
buckets exactly), so the kernel needs no masking or digest snapshotting:
absorb all blocks, final state is the digest. Child digests come from
`dig8` via gather and are scattered into the raw bytes before word
packing — the parent<-child dependency chain never touches the host
(reference contrast: trie/hasher.go:124-139 resolves it with goroutines
and channel joins).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .keccak_fused import _u8_to_words, _words_to_u8
from .keccak_jax import RATE, keccak_f1600_scanned_stacked


def _segment_keccak(words: jax.Array) -> jax.Array:
    """uint32[P, L, 34] -> uint32[P, 8]; all lanes have exactly L blocks."""
    p = words.shape[0]
    lo = jnp.zeros((25, p), jnp.uint32)
    hi = jnp.zeros((25, p), jnp.uint32)
    words_t = jnp.transpose(words, (1, 0, 2))  # [L, P, 34]

    def step(carry, block):
        lo, hi = carry
        absorb_lo = jnp.concatenate(
            [jnp.transpose(block[:, 0:34:2]), jnp.zeros((8, p), jnp.uint32)]
        )
        absorb_hi = jnp.concatenate(
            [jnp.transpose(block[:, 1:34:2]), jnp.zeros((8, p), jnp.uint32)]
        )
        lo, hi = keccak_f1600_scanned_stacked(lo ^ absorb_lo, hi ^ absorb_hi)
        return (lo, hi), None

    (lo, hi), _ = jax.lax.scan(step, (lo, hi), words_t)
    return jnp.stack([lo[0], hi[0], lo[1], hi[1], lo[2], hi[2], lo[3], hi[3]],
                     axis=1)


@functools.partial(jax.jit, static_argnames=("blocks",), donate_argnums=(0,))
def _seg_step_patched(dig8, seg_u8, pl, po, pc, gstart, *, blocks: int):
    """One segment with child-digest patches; dig8 donated (in-place)."""
    vals = dig8[pc]  # [NP, 32] gather from earlier segments
    ar32 = jnp.arange(32)
    seg_u8 = seg_u8.at[pl[:, None], po[:, None] + ar32[None, :]].set(vals)
    out = _segment_keccak(_u8_to_words(seg_u8, blocks))
    return jax.lax.dynamic_update_slice(dig8, _words_to_u8(out), (gstart, 0))


@functools.partial(jax.jit, static_argnames=("blocks",), donate_argnums=(0,))
def _seg_step_plain(dig8, seg_u8, gstart, *, blocks: int):
    """Patch-free segment (leaves)."""
    out = _segment_keccak(_u8_to_words(seg_u8, blocks))
    return jax.lax.dynamic_update_slice(dig8, _words_to_u8(out), (gstart, 0))


class StagedCommit:
    """Execute a CommitPlan's segment layout with pipelined dispatches.

    seg_impl: optional override of the per-segment keccak
    (uint32[P, L, 34] -> uint32[P, 8]) — the Pallas kernel plugs in here.
    """

    def __init__(self, seg_impl=None):
        if seg_impl is None:
            self._patched = _seg_step_patched
            self._plain = _seg_step_plain
        else:
            @functools.partial(jax.jit, static_argnames=("blocks",),
                               donate_argnums=(0,))
            def patched(dig8, seg_u8, pl, po, pc, gstart, *, blocks):
                vals = dig8[pc]
                ar32 = jnp.arange(32)
                seg_u8 = seg_u8.at[pl[:, None], po[:, None] + ar32[None, :]].set(vals)
                out = seg_impl(_u8_to_words(seg_u8, blocks))
                return jax.lax.dynamic_update_slice(
                    dig8, _words_to_u8(out), (gstart, 0))

            @functools.partial(jax.jit, static_argnames=("blocks",),
                               donate_argnums=(0,))
            def plain(dig8, seg_u8, gstart, *, blocks):
                out = seg_impl(_u8_to_words(seg_u8, blocks))
                return jax.lax.dynamic_update_slice(
                    dig8, _words_to_u8(out), (gstart, 0))

            self._patched = patched
            self._plain = plain

    def run(self, specs, flat: np.ndarray, nblocks: np.ndarray,
            patch_lane: np.ndarray, patch_off: np.ndarray,
            patch_child: np.ndarray, root_pos: int,
            want_digests: bool = True) -> Tuple[bytes, Optional[np.ndarray]]:
        """Inputs in the fused_commit array format (CommitPlan.export())."""
        total = int(nblocks.shape[0])
        dig8 = jnp.zeros((total, 32), jnp.uint8)
        byte_base = 0
        patch_pos = 0
        for spec in specs:
            width = spec.blocks * RATE
            size = spec.lanes * width
            seg = flat[byte_base:byte_base + size].reshape(spec.lanes, width)
            byte_base += size
            x = jax.device_put(seg)
            g = jnp.int32(spec.gstart)
            if spec.n_patches:
                pl = jax.device_put(patch_lane[patch_pos:patch_pos + spec.n_patches])
                po = jax.device_put(patch_off[patch_pos:patch_pos + spec.n_patches])
                pc = jax.device_put(patch_child[patch_pos:patch_pos + spec.n_patches])
                patch_pos += spec.n_patches
                dig8 = self._patched(dig8, x, pl, po, pc, g, blocks=spec.blocks)
            else:
                dig8 = self._plain(dig8, x, g, blocks=spec.blocks)
        if want_digests:
            host = np.asarray(dig8)
            return host[root_pos].tobytes(), host
        root = np.asarray(dig8[root_pos])
        return root.tobytes(), None
