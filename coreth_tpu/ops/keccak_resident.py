"""Device-resident incremental trie commits: deferred absorb + template
residency (PERF.md roadmap items #1 and #2, VERDICT r3 next-round #1+#2).

The planned executor (ops/keccak_planned.py) re-ships every dirty node's
full row each commit (~800 B/dirty node at 50k churn) and reads the whole
digest matrix back so the host cache can serve the next plan. At tunnel
bandwidths that transfer IS the bottleneck; the CPU wins below ~150 MB/s.

This executor keeps both halves of that traffic on the device across
commits:

  - a digest STORE uint32[S, 8] holds every node's digest at a persistent
    slot; parents reference children by slot, so digests never return to
    the host (only the 32-byte root, on demand)
  - per-block-class row ARENAS uint32[R, blocks*34] hold each node's
    keccak-padded RLP row at a persistent row index; a commit uploads only
    rows whose TEMPLATE changed (fresh nodes, structural edits) plus the
    patch tables — steady-state h2d is ~tens of bytes per dirty node
  - holes are DELTA-patched: contribution strips of (new - old) child
    digests scatter-add into the arena in wrapping u32 arithmetic. Every
    hole word is a sum of byte-disjoint contributions, so the modular
    update is exact; fresh rows carry zero holes and old = the zero
    sentinel. The old digest is store[slot] *before* this commit's store
    scatter, which runs last.

Because the host plan needs no digest values, planning commit k+1 can
overlap device execution of commit k (JAX async dispatch): steady-state
throughput is nodes/max(plan, transfer) instead of nodes/(plan+transfer).

Index conventions (mirrored by native/mpt_inc.cpp build_plan_res):
  store slot 0 = zero sentinel, slot 1 = pad-lane scratch, real slots >= 2;
  arena row 0 per class = scratch; dig row 0 = zero sentinel (gather index
  0 means "no contribution" for both dig and store).

Reference seam: the warm-trie dirty-walk of /root/reference/trie/trie.go
:573-626 + the hashdb dirty forest (trie/triedb/hashdb/database.go:94-155)
whose "absorb" step here lives permanently in device memory.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from .keccak_staged import _segment_keccak

MAX_SEGMENTS = 64


def _pow2_bucket(n: int, floor: int = 16) -> int:
    """Round n up to a power of two (>= floor). Load-bearing for jit
    cache-key stability: every padded shape must come from this one
    policy so the set of compiled programs stays small and consistent."""
    b = floor
    while b < n:
        b <<= 1
    return b


def _strips(d: jax.Array, shift: jax.Array) -> jax.Array:
    """uint32[P, 8] digests + byte shifts -> uint32[P, 9] contribution
    strips (digest bytes relocated to byte offset shift within the 9-word
    destination window; all other bytes zero)."""
    p = d.shape[0]
    dpad = jnp.concatenate(
        [jnp.zeros((p, 1), jnp.uint32), d, jnp.zeros((p, 1), jnp.uint32)],
        axis=1,
    )  # [P, 10]; dpad[:, j] == D[j-1]
    lsh = (8 * shift)[:, None].astype(jnp.uint32)
    rsh = (32 - 8 * shift)[:, None]
    lo = dpad[:, :9] >> jnp.minimum(rsh, 31).astype(jnp.uint32)
    lo = jnp.where(shift[:, None] == 0, jnp.uint32(0), lo)
    hi = dpad[:, 1:] << lsh
    return lo | hi


# sharding: unsharded fallback only (non-fused run()); mesh commits go
# through the fused program, whose in/out shardings are pinned explicitly
@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_rows(arena, rows, idx):
    """Upload fresh rows into their persistent arena slots."""
    return arena.at[idx].set(rows, mode="drop")


# sharding: unsharded fallback only (non-fused run()); mesh commits go
# through the fused program, whose in/out shardings are pinned explicitly
@functools.partial(jax.jit, donate_argnums=(0,))
def _scatter_store(store, dig, lane_slot):
    """Persist this commit's digests at their slots (pads target the
    scratch slot 1; slot 0 stays the zero sentinel forever)."""
    return store.at[lane_slot].set(dig[1:], mode="drop")


LEAN_WORDS = 18  # 72-byte lean record = 18 uint32 words (native kLeanWidth)


def _make_res_step(seg_impl, donate: bool = True):
    """Jitted per-segment step: delta-patch the arena, gather the
    segment's rows, hash, write digests into dig. Static args are shapes
    only; per-segment offsets travel in the meta row selected by seg_i."""

    # sharding: unsharded fallback only (non-fused run()); mesh commits
    # go through the fused program's explicitly pinned in/out shardings
    @functools.partial(
        jax.jit,
        static_argnames=("lanes", "blocks", "npatch"),
        donate_argnums=(0, 2) if donate else (),
    )
    def step(arena, store, dig, off_all, src_all, oldidx_all,
             rowidx_all, meta, seg_i,
             *, lanes: int, blocks: int, npatch: int):
        row = jax.lax.dynamic_slice(meta, (seg_i, 0), (1, 3))[0]
        patch_off, lane_off, gstart = row[0], row[1], row[2]
        flat = arena.reshape(-1)
        if npatch:
            off = jax.lax.dynamic_slice(off_all, (patch_off,), (npatch,))
            src = jax.lax.dynamic_slice(src_all, (patch_off,), (npatch,))
            oldidx = jax.lax.dynamic_slice(oldidx_all, (patch_off,), (npatch,))
            dstw = off >> 2            # word index + byte shift derived
            shift = off & 3            # on device (12 B/patch h2d)
            # signed source: +k = this commit's dig row k, -k = store
            # slot k, 0 = none (both gathers hit their pinned-zero row 0)
            new = jnp.where(src[:, None] > 0,
                            dig[jnp.maximum(src, 0)],
                            store[jnp.maximum(-src, 0)])  # [P, 8]
            old = store[oldidx]                           # [P, 8]
            delta = _strips(new, shift) - _strips(old, shift)
            idx = dstw[:, None] + jnp.arange(9, dtype=jnp.int32)[None, :]
            flat = flat.at[idx.reshape(-1)].add(delta.reshape(-1),
                                                mode="drop")
        arena = flat.reshape(arena.shape)
        ridx = jax.lax.dynamic_slice(rowidx_all, (lane_off,), (lanes,))
        words = arena[ridx].reshape(lanes, blocks, 34)
        out = seg_impl(words)                            # [lanes, 8]
        dig = jax.lax.dynamic_update_slice(
            dig, out, (gstart + 1, jnp.int32(0)))
        return arena, dig

    return step


class ResidentExecutor:
    """Holds one trie's device-resident state (store + arenas) and runs
    resident commits exported by native/mpt_inc.cpp's resident planner.

    One executor per trie — the store/arena contents ARE that trie's
    digest cache. seg_impl: optional keccak kernel override (the Pallas
    kernel plugs in, as in ops/keccak_planned.py)."""

    def __init__(self, seg_impl=None, sharding=None, fused=None):
        impl = seg_impl if seg_impl is not None else _segment_keccak
        self._impl = impl
        self._step = _make_res_step(impl)
        self.store: Optional[jax.Array] = None
        self.arenas: dict[int, jax.Array] = {}
        self.last_root: Optional[jax.Array] = None  # uint32[8], lazy
        self._owner = None  # weakref to the one trie this store serves
        # multichip: a NamedSharding over the ROW axis (store slots /
        # arena rows) distributes the resident state across a Mesh —
        # capacities round up to the device count and GSPMD partitions
        # the step's gathers/scatters (parallel.resident_executor_over_
        # mesh builds this; dig stays replicated, it is per-commit-sized)
        self.sharding = sharding
        self._row_mult = sharding.mesh.size if sharding is not None else 1
        # explicit upload placement: per-commit payloads (rows/aux/patch
        # tables) are replicated over the mesh while the resident state
        # stays row-sharded — pinning it here (instead of letting
        # device_put infer) is what keeps chained commits reshard-free
        # across processes (SA012 sharding discipline)
        if sharding is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            self._repl = NamedSharding(sharding.mesh, PartitionSpec())
        else:
            self._repl = None
        # fused = ONE dispatch + TWO uploads per commit (VERDICT r4 #3);
        # programs are keyed on the commit's static shape signature, which
        # lane/row bucketing keeps stable in steady state
        if fused is None:
            import os

            fused = os.environ.get("CORETH_TPU_RESIDENT_FUSE", "1") != "0"
        self.fused = fused
        # plan cache: compiled whole-commit programs AND their host
        # staging buffers, keyed by the commit's segment-shape signature.
        # Warm commits (steady-state chain: same dirty-set bucket shapes
        # block after block) skip jit tracing and refill preallocated
        # aux/rows buffers in place instead of re-concatenating.
        # Staging is a RING per signature: with cross-commit pipelining
        # (pipeline_depth > 0) up to depth+1 commits' buffers may be
        # in flight at once, so each ring entry remembers the lazy root
        # of the commit that consumed it and is only rewritten once THAT
        # commit has settled — never the whole pipeline
        self._fused_cache: dict = {}
        self._staging: dict = {}
        # bounded in-flight window for deferred-absorb pipelining: 0 =
        # every dispatch settles the previous commit before staging reuse
        # (the pre-pipelining behaviour); k = up to k commits may still
        # be executing on device while the next one is planned/dispatched
        self.pipeline_depth = 0
        # diagnostics for PERF.md / bench: bytes actually shipped
        self.h2d_bytes = 0
        self.last_transfers = 0
        self.last_dispatches = 0
        self.last_cache_hit = False
        # mesh diagnostics, explicitly zeroed when unsharded so flight-
        # record keys stay un-ragged. Provenance split (PR 18):
        # last_gather_bytes is MEASURED — bytes of replicated digest
        # matrix actually materialized host-side (0 on the per-shard
        # absorb path); last_gather_bytes_modeled is the (n-1)/n
        # all-gather MODEL recorded every sharded commit for the A/B;
        # last_absorb_d2h_bytes counts the shard-local digest readbacks
        # that replace the gather. The trajectory sentinel only ever
        # gates on the measured counters.
        self.last_gather_bytes = 0
        self.last_gather_bytes_modeled = 0
        self.last_absorb_d2h_bytes = 0
        self.last_shard_lanes: list = []
        # lean wire diagnostics: content-only class-1 records in the
        # last commit and their wire bytes (72 content + 4 idx + 4 len)
        self.last_lean_rows = 0
        self.last_lean_wire_bytes = 0
        # full digest matrix of the last run (lazy, includes the zero-
        # sentinel row 0) — template residency absorbs it host-side
        self.last_dig: Optional[jax.Array] = None

    @property
    def shards(self) -> int:
        """Mesh shards holding the resident state (1 = unsharded)."""
        return self._row_mult

    @property
    def spans_processes(self) -> bool:
        """True when the mesh's devices belong to more than one jax
        process — the demotion ladder's local single-device rung is
        unavailable then (a unilateral local rebuild would desync the
        SPMD program on every other process)."""
        if self.sharding is None:
            return False
        return len({d.process_index
                    for d in self.sharding.mesh.devices.flat}) > 1

    def _pin(self, arr: jax.Array) -> jax.Array:
        if self.sharding is None:
            return arr
        return jax.device_put(arr, self.sharding)

    def _put(self, arr):
        """Host->device upload with an EXPLICIT placement: replicated
        over the mesh when sharded (uploads are per-commit-sized; the
        resident state itself stays row-sharded), default placement
        when unsharded (None)."""
        return jax.device_put(arr, self._repl)

    def _note_collectives(self, export) -> None:
        """Per-commit collective accounting for the flight record,
        split by provenance (PR 18). resident/gather_bytes_modeled
        records the (shards-1)/shards digest all-gather MODEL every
        sharded commit — what materializing the replicated dig matrix
        host-side would move. The MEASURED twin resident/gather_bytes
        is reset to 0 here and only incremented by note_dig_gather when
        a full dig readback actually happens; steady-state per-shard-
        absorb commits therefore record 0 measured gather bytes.
        lanes-per-shard comes from each lane's store slot, whose
        contiguous row blocks are what NamedSharding partitions.
        Unsharded commits record explicit zeros so flight-record keys
        stay un-ragged across configs."""
        from ..metrics import default_registry

        total_lanes = int(export["total_lanes"])
        n = self._row_mult
        self.last_gather_bytes = 0
        self.last_absorb_d2h_bytes = 0
        if n > 1:
            self.last_gather_bytes_modeled = total_lanes * 32 * (n - 1) // n
            per = max(1, self.store.shape[0] // n)
            owner = np.minimum(export["lane_slot"] // per, n - 1)
            self.last_shard_lanes = np.bincount(owner, minlength=n).tolist()
        else:
            self.last_gather_bytes_modeled = 0
            self.last_shard_lanes = [total_lanes]
        default_registry.counter("resident/gather_bytes_modeled").inc(
            self.last_gather_bytes_modeled)

    def note_dig_gather(self, export) -> None:
        """A full replicated dig matrix materialized host-side (the
        template full-readback path): count the MEASURED cross-shard
        gather — (shards-1)/shards of every lane's 32-byte digest had
        to cross shards to assemble the replica being read."""
        from ..metrics import default_registry

        n = self._row_mult
        if n <= 1:
            return
        self.last_gather_bytes = int(export["total_lanes"]) * 32 \
            * (n - 1) // n
        default_registry.counter("resident/gather_bytes").inc(
            self.last_gather_bytes)

    def shard_digests(self, export):
        """Per-shard digest readback for the mesh absorb: for each
        store shard, gather this commit's digest rows ON that shard
        (the store scatter already placed them — lane_slot partitions
        by owner) and read back exactly those lanes' digests. Returns
        [(global_lane_idx int32[k], digests uint32[k, 8]), ...] for
        IncrementalTrie's mpt_inc_res_absorb_lanes. No replicated-dig
        materialization, no cross-shard traffic; the d2h total lands in
        resident/absorb_d2h_bytes (measured)."""
        from ..metrics import default_registry

        lane_slot = np.asarray(export["lane_slot"])
        lanes_all = np.arange(lane_slot.shape[0], dtype=np.int32)
        real = lane_slot >= 2  # pad lanes target the scratch slot 1
        n = self._row_mult
        per = max(1, self.store.shape[0] // n)
        owner = np.minimum(lane_slot // per, n - 1)
        shards = sorted(self.store.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        parts = []
        d2h = 0
        for k, sh in enumerate(shards):
            sel = real & (owner == k)
            lanes_k = lanes_all[sel]
            if lanes_k.size == 0:
                parts.append((lanes_k, np.zeros((0, 8), np.uint32)))
                continue
            local = (lane_slot[sel] - k * per).astype(np.int32)
            digs = np.asarray(sh.data[local])  # shard-local gather+d2h
            parts.append((lanes_k, digs))
            d2h += lanes_k.size * 32
        self.last_absorb_d2h_bytes = d2h
        default_registry.counter("resident/absorb_d2h_bytes").inc(d2h)
        return parts

    def store_parts(self):
        """Shard-local store readbacks for the interval absorb:
        [(slot_lo, slot_hi, uint32[rows, 8]), ...] covering the whole
        store, one entry per shard (one entry total when unsharded).
        Pairs with IncrementalTrie.absorb_store_parts — the sharded
        replacement for reading the full store back in one host-side
        gather. Counted under resident/absorb_d2h_bytes (measured)."""
        from ..metrics import default_registry

        if self.store is None:
            return []
        if self._row_mult == 1:
            part = np.asarray(self.store)
            default_registry.counter("resident/absorb_d2h_bytes").inc(
                part.nbytes)
            return [(0, int(self.store.shape[0]), part)]
        parts = []
        d2h = 0
        for sh in sorted(self.store.addressable_shards,
                         key=lambda s: s.index[0].start or 0):
            data = np.asarray(sh.data)
            lo = int(sh.index[0].start or 0)
            parts.append((lo, lo + data.shape[0], data))
            d2h += data.nbytes
        default_registry.counter("resident/absorb_d2h_bytes").inc(d2h)
        return parts

    # ---- ownership: slot/row numbering is per-trie, so a second trie
    # sharing this executor would silently corrupt both stores ----

    def check_binding(self, tree):
        if self._owner is not None and self._owner() is not tree:
            raise RuntimeError(
                "executor already serves another trie (its store/arena "
                "slots are that trie's digest cache); create one "
                "ResidentExecutor per trie")

    def bind(self, tree):
        self.check_binding(tree)
        if self._owner is None:
            import weakref

            self._owner = weakref.ref(tree)

    # ---- capacity management (growth recompiles; keep it geometric) ----

    def _cap(self, n: int) -> int:
        m = self._row_mult
        return -(-n // m) * m

    def _ensure_store(self, slots_needed: int):
        if self.store is None:
            cap = self._cap(max(2 * slots_needed, 4096))
            self.store = self._pin(jnp.zeros((cap, 8), jnp.uint32))
        elif self.store.shape[0] < slots_needed:
            cap = self._cap(max(2 * slots_needed, 2 * self.store.shape[0]))
            pad = jnp.zeros((cap - self.store.shape[0], 8), jnp.uint32)
            self.store = self._pin(
                jnp.concatenate([self.store, pad], axis=0))

    def _ensure_arena(self, cls: int, rows_needed: int):
        width = cls * 34
        a = self.arenas.get(cls)
        if a is None:
            cap = self._cap(max(2 * rows_needed, 1024))
            self.arenas[cls] = self._pin(jnp.zeros((cap, width), jnp.uint32))
        elif a.shape[0] < rows_needed:
            cap = self._cap(max(2 * rows_needed, 2 * a.shape[0]))
            pad = jnp.zeros((cap - a.shape[0], width), jnp.uint32)
            self.arenas[cls] = self._pin(jnp.concatenate([a, pad], axis=0))

    # ---- fused whole-commit program (one dispatch per commit) ----

    def _fused_program(self, key):
        """Build (or fetch) the jitted whole-commit program for a static
        shape signature. The signature bakes in every offset, so the
        program needs only (store, arenas..., rows_packed, aux) and runs
        fresh-row scatters, all segment delta-patch+hash steps, and the
        final store scatter in ONE dispatch."""
        from ..metrics import default_registry

        fn = self._fused_cache.get(key)
        if fn is not None:
            default_registry.counter("resident/plan_cache/hits").inc(1)
            self.last_cache_hit = True
            return fn
        default_registry.counter("resident/plan_cache/misses").inc(1)
        self.last_cache_hit = False
        if len(self._fused_cache) >= 256:
            # bound compiled-program retention (matches the planned
            # builder's lru_cache(256)); dict preserves insertion order,
            # so this evicts the oldest signature (and its staging)
            oldest = next(iter(self._fused_cache))
            self._fused_cache.pop(oldest)
            self._staging.pop(oldest, None)
        (specs_t, fresh_t, classes, _store_cap, _arena_caps,
         g_pad, len_off, len_rowidx, lean_bucket) = key
        impl = self._impl
        narena = len(classes)
        cls_pos = {c: i for i, c in enumerate(classes)}

        jit_kwargs = dict(donate_argnums=tuple(range(1 + narena)))
        if self.sharding is not None:
            # pjit discipline for chained commits: pin matching in/out
            # axis_resources so the store and arenas stay row-sharded
            # edge to edge across every commit — nothing reshards
            # between dispatches — while the per-commit uploads and the
            # dig matrix stay replicated (patches may read any lane).
            # The only cross-shard traffic left is the digest gather.
            from jax.sharding import NamedSharding, PartitionSpec

            repl = NamedSharding(self.sharding.mesh, PartitionSpec())
            res = (self.sharding,) * (1 + narena)
            jit_kwargs.update(in_shardings=res + (repl, repl),
                              out_shardings=res + (repl,))

        @functools.partial(jax.jit, **jit_kwargs)
        def fused(store, *rest):
            arenas = list(rest[:narena])
            rows_packed, aux = rest[narena], rest[narena + 1]
            p = 0
            off_all = aux[p:p + len_off]; p += len_off
            src_all = aux[p:p + len_off]; p += len_off
            oldidx_all = aux[p:p + len_off]; p += len_off
            rowidx_all = aux[p:p + len_rowidx]; p += len_rowidx
            lane_slot = aux[p:p + g_pad]; p += g_pad
            rp = 0
            for cls, n_rows, width in fresh_t:
                ai = cls_pos[cls]
                rows = rows_packed[rp:rp + n_rows * width]
                rows = rows.reshape(n_rows, width); rp += n_rows * width
                idx = aux[p:p + n_rows]; p += n_rows
                arenas[ai] = arenas[ai].at[idx].set(rows, mode="drop")
            if lean_bucket:
                # lean wire records: zero-extend each 18-word content
                # record to a full 34-word class-1 row and re-derive the
                # keccak pad bits from the shipped RLP length (0x01 at
                # byte len, 0x80 at byte 135). Fresh rows carry zero
                # holes, so set == what the full upload would have held;
                # pad records (idx 0, len 0) land in the scratch row.
                lidx = aux[p:p + lean_bucket]; p += lean_bucket
                llen = aux[p:p + lean_bucket]; p += lean_bucket
                lrows = rows_packed[rp:rp + lean_bucket * LEAN_WORDS]
                lrows = lrows.reshape(lean_bucket, LEAN_WORDS)
                rp += lean_bucket * LEAN_WORDS
                full = jnp.zeros((lean_bucket, 34), jnp.uint32)
                full = full.at[:, :LEAN_WORDS].set(lrows)
                full = full.at[jnp.arange(lean_bucket), llen >> 2].add(
                    jnp.uint32(1)
                    << ((llen & 3) * 8).astype(jnp.uint32))
                full = full.at[:, 33].add(jnp.uint32(0x80) << 24)
                ai = cls_pos[1]
                arenas[ai] = arenas[ai].at[lidx].set(full, mode="drop")
            dig = jnp.zeros((1 + g_pad, 8), jnp.uint32)
            for blocks, lanes, gstart, npatch, patch_off, lane_off in specs_t:
                ai = cls_pos[blocks]
                arena = arenas[ai]
                flat = arena.reshape(-1)
                if npatch:
                    off = off_all[patch_off:patch_off + npatch]
                    src = src_all[patch_off:patch_off + npatch]
                    oldidx = oldidx_all[patch_off:patch_off + npatch]
                    dstw = off >> 2
                    shift = off & 3
                    new = jnp.where(src[:, None] > 0,
                                    dig[jnp.maximum(src, 0)],
                                    store[jnp.maximum(-src, 0)])
                    old = store[oldidx]
                    delta = _strips(new, shift) - _strips(old, shift)
                    idx = dstw[:, None] + jnp.arange(9, dtype=jnp.int32)[None]
                    flat = flat.at[idx.reshape(-1)].add(delta.reshape(-1),
                                                        mode="drop")
                arena = flat.reshape(arena.shape)
                ridx = rowidx_all[lane_off:lane_off + lanes]
                words = arena[ridx].reshape(lanes, blocks, 34)
                out = impl(words)                            # [lanes, 8]
                dig = jax.lax.dynamic_update_slice(
                    dig, out, (gstart + 1, 0))
                arenas[ai] = arena
            store = store.at[lane_slot].set(dig[1:], mode="drop")
            return (store, *arenas, dig)

        self._fused_cache[key] = fused
        return fused

    def _run_fused(self, export, specs, g_pad) -> jax.Array:
        from ..metrics import phase_timer

        with phase_timer("resident/phase/scatter"):
            # shape signature first — no padding/concat work until the
            # staging buffers for this signature are resolved
            fresh_shapes = []
            for cls in sorted(export["fresh"]):
                rows, idx = export["fresh"][cls]
                fresh_shapes.append(
                    (cls, rows, idx, _pow2_bucket(idx.shape[0])))
            len_off = export["off"].shape[0]
            len_rowidx = export["rowidx"].shape[0]
            lean = export.get("lean")
            n_lean = lean[1].shape[0] if lean is not None else 0
            lean_bucket = _pow2_bucket(n_lean) if n_lean else 0
            specs_t = tuple(tuple(int(v) for v in s) for s in specs)
            fresh_t = tuple((cls, bucket, rows.shape[1])
                            for cls, rows, _, bucket in fresh_shapes)
            classes = tuple(sorted({s[0] for s in specs_t}
                                   | {cls for cls, _, _ in fresh_t}))
            for cls in classes:
                self._ensure_arena(cls, 1)  # segment-only classes must exist
            key = (specs_t, fresh_t, classes, self.store.shape[0],
                   tuple(self.arenas[c].shape[0] for c in classes),
                   g_pad, len_off, len_rowidx, lean_bucket)

            # staging reuse (the plan cache's host half): warm commits
            # refill this signature's preallocated aux/rows buffers in
            # place instead of re-concatenating ~10 arrays. A dispatched
            # commit's program may still be consuming these exact
            # buffers (device_put can alias host memory on the CPU
            # backend), so each ring entry carries the lazy root of the
            # commit that consumed it and is only rewritten once that
            # commit has settled. Ring size pipeline_depth+1 keeps up to
            # `pipeline_depth` commits in flight without ever blocking
            # on the newest dispatch — the AlDBaran overlap window
            ring = self._staging.get(key)
            if ring is None:
                ring = self._staging[key] = []
            want = max(0, int(self.pipeline_depth)) + 1
            while len(ring) > want:  # depth was lowered: shrink the ring
                ring.pop(0)
            if len(ring) >= want:
                aux, rows_packed, busy = ring.pop(0)
                if busy is not None and hasattr(busy, "block_until_ready"):
                    busy.block_until_ready()
            else:
                n_aux = (3 * len_off + len_rowidx + g_pad
                         + sum(b for _, b, _ in fresh_t)
                         + 2 * lean_bucket)
                n_rows = (sum(b * w for _, b, w in fresh_t)
                          + lean_bucket * LEAN_WORDS)
                aux = np.zeros(n_aux, np.int32)
                rows_packed = np.zeros(max(n_rows, 1), np.uint32)
            p = 0
            aux[p:p + len_off] = export["off"]; p += len_off
            aux[p:p + len_off] = export["src"]; p += len_off
            aux[p:p + len_off] = export["oldidx"]; p += len_off
            aux[p:p + len_rowidx] = export["rowidx"]; p += len_rowidx
            n_ls = export["lane_slot"].shape[0]
            aux[p:p + n_ls] = export["lane_slot"]
            aux[p + n_ls:p + g_pad] = 1  # pad lanes -> scratch slot
            p += g_pad
            rp = 0
            for cls, rows, idx, bucket in fresh_shapes:
                n, w = idx.shape[0], rows.shape[1]
                aux[p:p + n] = idx
                aux[p + n:p + bucket] = 0  # pad rows -> arena scratch
                p += bucket
                rows_packed[rp:rp + n * w] = rows.reshape(-1)
                rows_packed[rp + n * w:rp + bucket * w] = 0
                rp += bucket * w
            if lean_bucket:
                lrows, lidx, llen = lean
                aux[p:p + n_lean] = lidx
                aux[p + n_lean:p + lean_bucket] = 0  # pads -> scratch row
                p += lean_bucket
                aux[p:p + n_lean] = llen
                aux[p + n_lean:p + lean_bucket] = 0  # pad len 0
                p += lean_bucket
                nw = n_lean * LEAN_WORDS
                rows_packed[rp:rp + nw] = lrows.reshape(-1)
                rows_packed[rp + nw:rp + lean_bucket * LEAN_WORDS] = 0
                rp += lean_bucket * LEAN_WORDS
            self.last_lean_rows = n_lean
            self.last_lean_wire_bytes = n_lean * (4 * LEAN_WORDS + 8)

        fn = self._fused_program(key)
        with phase_timer("resident/phase/patch"):
            rows_d = self._put(rows_packed[:rp])
            aux_d = self._put(aux)
            outs = fn(self.store, *(self.arenas[c] for c in classes),
                      rows_d, aux_d)
        with phase_timer("resident/phase/store"):
            self.store = outs[0]
            for i, c in enumerate(classes):
                self.arenas[c] = outs[1 + i]
            dig = outs[-1]
            self.h2d_bytes = rows_packed[:rp].nbytes + aux.nbytes
            self.last_transfers = 2
            self.last_dispatches = 1
            self.last_dig = dig
            self.last_root = dig[int(export["root_lane"]) + 1]
            # return the staging buffers to the ring tagged with THIS
            # commit's lazy root — the reuse gate above blocks on it
            self._staging.setdefault(key, []).append(
                (aux, rows_packed, self.last_root))
            from ..metrics import default_registry

            default_registry.counter("resident/h2d_bytes").inc(
                self.h2d_bytes)
            default_registry.counter("resident/lean_wire_bytes").inc(
                self.last_lean_wire_bytes)
            self._note_collectives(export)
        return self.last_root

    # ---- one commit ----

    def run(self, export) -> jax.Array:
        """Execute one resident commit. `export` is the dict produced by
        native.mpt.IncrementalTrie.export_resident_plan(). Returns the
        root digest as a LAZY uint32[8] device array — call
        np.asarray(...) (or root_bytes) to synchronize."""
        specs = export["specs"]            # [n_seg, 6] int32 host array
        if len(specs) > MAX_SEGMENTS:
            raise ValueError(f"{len(specs)} segments > {MAX_SEGMENTS}")
        self._ensure_store(export["store_slots"])
        for cls, (n_fresh, rows_needed) in export["classes"].items():
            self._ensure_arena(cls, rows_needed)

        if self.fused:
            total_lanes = int(export["total_lanes"])
            g_pad = _pow2_bucket(total_lanes)
            return self._run_fused(export, specs, g_pad)

        h2d = 0
        # fresh-row uploads, one scatter per class
        for cls, (rows, idx) in export["fresh"].items():
            n = idx.shape[0]
            bucket = _pow2_bucket(n)
            if bucket != n:
                rows = np.concatenate(
                    [rows, np.zeros((bucket - n, rows.shape[1]), np.uint32)])
                idx = np.concatenate(
                    [idx, np.zeros(bucket - n, np.int32)])
            self.arenas[cls] = _scatter_rows(
                self.arenas[cls], self._put(rows), self._put(idx))
            h2d += rows.nbytes + idx.nbytes

        # lean class-1 records: the non-fused fallback expands them on
        # the host (zero-extend to 34 words + keccak pad bits) and ships
        # full rows — no wire savings here, so the diagnostics record the
        # bytes actually uploaded, not the fused-path lean envelope
        self.last_lean_rows = 0
        self.last_lean_wire_bytes = 0
        lean = export.get("lean")
        if lean is not None and lean[1].shape[0]:
            lrows, lidx, llen = lean
            n = lidx.shape[0]
            full = np.zeros((n, 34), np.uint32)
            full[:, :LEAN_WORDS] = lrows
            fb = full.view(np.uint8).reshape(n, 136)
            fb[np.arange(n), llen] ^= 0x01
            fb[:, 135] ^= 0x80
            bucket = _pow2_bucket(n)
            idx = lidx
            if bucket != n:
                full = np.concatenate(
                    [full, np.zeros((bucket - n, 34), np.uint32)])
                idx = np.concatenate(
                    [idx, np.zeros(bucket - n, np.int32)])
            self._ensure_arena(1, 1)
            self.arenas[1] = _scatter_rows(
                self.arenas[1], self._put(full), self._put(idx))
            h2d += full.nbytes + idx.nbytes
            self.last_lean_rows = n
            self.last_lean_wire_bytes = full.nbytes + idx.nbytes

        meta = np.zeros((MAX_SEGMENTS, 3), np.int32)
        for i, s in enumerate(specs):
            meta[i] = (s[4], s[5], s[2])   # patch_off, lane_off, gstart
        tables = [self._put(export[k]) for k in
                  ("off", "src", "oldidx", "rowidx")]
        h2d += sum(export[k].nbytes for k in
                   ("off", "src", "oldidx", "rowidx"))
        lane_slot = self._put(export["lane_slot"])
        h2d += export["lane_slot"].nbytes
        mt = self._put(meta)
        seg_ids = self._put(np.arange(MAX_SEGMENTS, dtype=np.int32))
        off, src, oldidx, rowidx = tables

        # bucket the dig height to a power of two: every jitted step is
        # shape-keyed on dig, so an exact per-commit lane total would
        # recompile each program for every distinct commit size
        total_lanes = int(export["total_lanes"])
        g_pad = _pow2_bucket(total_lanes)
        if g_pad != lane_slot.shape[0]:
            lane_slot = jnp.concatenate([
                lane_slot,
                jnp.ones(g_pad - lane_slot.shape[0], jnp.int32)])  # scratch
        dig = jnp.zeros((1 + g_pad, 8), jnp.uint32)
        store = self.store
        for i, s in enumerate(specs):
            blocks, lanes = int(s[0]), int(s[1])
            arena = self.arenas[blocks]
            arena, dig = self._step(
                arena, store, dig, off, src, oldidx,
                rowidx, mt, seg_ids[i],
                lanes=lanes, blocks=blocks, npatch=int(s[3]))
            self.arenas[blocks] = arena
        self.store = _scatter_store(store, dig, lane_slot)
        self.h2d_bytes = h2d
        self.last_transfers = 7 + len(export["fresh"]) * 2
        self.last_dispatches = 1 + len(specs) + len(export["fresh"])
        self.last_dig = dig
        self.last_root = dig[int(export["root_lane"]) + 1]
        from ..metrics import default_registry

        default_registry.counter("resident/h2d_bytes").inc(self.h2d_bytes)
        default_registry.counter("resident/lean_wire_bytes").inc(
            self.last_lean_wire_bytes)
        self._note_collectives(export)
        return self.last_root

    @staticmethod
    def root_bytes(root: jax.Array) -> bytes:
        """Synchronize and render a run() result as the 32-byte root."""
        return np.asarray(root).astype("<u4").tobytes()
