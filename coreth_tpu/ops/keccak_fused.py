"""Fused multi-level trie commitment on device.

The level-synchronized hasher's naive form round-trips host↔device once
per trie level per size bucket (~20 dispatches per commit) — fatal when
device latency is high. The fused design exploits a structural fact of
MPT hashing: a parent's RLP *length* never depends on its children's
digest *values* (a hashed-child reference is always 33 encoded bytes), so
the host can precompute every node's keccak-padded message with zeroed
digest slots plus a patch table (parent lane, byte offset, child lane),
and the device runs the whole dependency chain itself:

    for each (level, bucket) segment:          # unrolled at trace time
        scatter child digests into the segment's messages
        keccak the segment
        append digests to the global digest array

ONE host→device transfer, ONE dispatch, ONE digest readback. Device work
is pure VPU-friendly u32 bit-ops; the sequential depth is the trie depth
(~log16 N), with full batch parallelism inside each level.
"""

from __future__ import annotations

import functools
from typing import List, NamedTuple, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .keccak_jax import RATE, WORDS_PER_BLOCK, keccak_f1600_scanned_stacked


class SegmentSpec(NamedTuple):
    """Static shape descriptor for one (level, bucket) group."""

    blocks: int        # rate blocks per lane in this segment
    lanes: int         # padded lane count
    gstart: int        # start offset in the global digest array
    n_patches: int     # padded patch count


def _u8_to_words(a_u8: jnp.ndarray, blocks: int) -> jnp.ndarray:
    """uint8[P, blocks*136] -> uint32[P, blocks, 34] (little-endian)."""
    p = a_u8.shape[0]
    b4 = a_u8.reshape(p, blocks, WORDS_PER_BLOCK, 4).astype(jnp.uint32)
    return (
        b4[..., 0]
        | (b4[..., 1] << 8)
        | (b4[..., 2] << 16)
        | (b4[..., 3] << 24)
    )


def _words_to_u8(w: jnp.ndarray) -> jnp.ndarray:
    """uint32[P, 8] digest words -> uint8[P, 32]."""
    p = w.shape[0]
    out = jnp.stack(
        [(w >> (8 * i)) & 0xFF for i in range(4)], axis=-1
    )  # [P, 8, 4]
    return out.astype(jnp.uint8).reshape(p, 32)


def _keccak_segment(words: jnp.ndarray, nblocks: jnp.ndarray) -> jnp.ndarray:
    """uint32[P, L, 34] + int32[P] -> uint32[P, 8].

    Double scan (blocks outer, rounds inner) keeps the traced program tiny
    so ~20 segments can inline into one XLA module without minute-long
    compiles."""
    p = words.shape[0]
    lo = jnp.zeros((25, p), jnp.uint32)
    hi = jnp.zeros((25, p), jnp.uint32)
    out = jnp.zeros((p, 8), jnp.uint32)
    words_t = jnp.transpose(words, (1, 0, 2))  # [L, P, 34]
    idx = jnp.arange(words.shape[1], dtype=jnp.int32)

    def step(carry, xs):
        lo, hi, out = carry
        block, j = xs
        live = (j < nblocks).astype(jnp.uint32)
        absorb_lo = jnp.concatenate(
            [jnp.transpose(block[:, 0:34:2]) * live, jnp.zeros((8, p), jnp.uint32)]
        )
        absorb_hi = jnp.concatenate(
            [jnp.transpose(block[:, 1:34:2]) * live, jnp.zeros((8, p), jnp.uint32)]
        )
        lo = lo ^ absorb_lo
        hi = hi ^ absorb_hi
        lo, hi = keccak_f1600_scanned_stacked(lo, hi)
        digest = jnp.stack(
            [lo[0], hi[0], lo[1], hi[1], lo[2], hi[2], lo[3], hi[3]], axis=1
        )
        is_last = (j == nblocks - 1)[:, None]
        out = jnp.where(is_last, digest, out)
        return (lo, hi, out), None

    (lo, hi, out), _ = jax.lax.scan(step, (lo, hi, out), (words_t, idx))
    return out


@functools.partial(jax.jit, static_argnames=("specs",))
def fused_commit(specs: Tuple[SegmentSpec, ...], flat_msgs: jax.Array,
                 nblocks: jax.Array, patch_lane: jax.Array,
                 patch_off: jax.Array, patch_child: jax.Array) -> jax.Array:
    """Run the whole level-synchronized commit in one dispatch.

    flat_msgs:  uint8[sum(lanes*blocks*136)]  segment messages, concatenated
    nblocks:    int32[G]                      per-lane block counts
    patch_*:    int32[sum(n_patches)]         per-segment patch tables
    returns     uint8[G, 32] digests in global lane order
    """
    g = nblocks.shape[0]
    dig8 = jnp.zeros((g, 32), jnp.uint8)
    ar32 = jnp.arange(32)

    msg_off = 0
    patch_pos = 0
    for spec in specs:
        size = spec.lanes * spec.blocks * RATE
        seg = jax.lax.dynamic_slice(flat_msgs, (msg_off,), (size,)).reshape(
            spec.lanes, spec.blocks * RATE
        )
        msg_off += size
        if spec.n_patches:
            pl = jax.lax.dynamic_slice(patch_lane, (patch_pos,), (spec.n_patches,))
            po = jax.lax.dynamic_slice(patch_off, (patch_pos,), (spec.n_patches,))
            pc = jax.lax.dynamic_slice(patch_child, (patch_pos,), (spec.n_patches,))
            patch_pos += spec.n_patches
            vals = dig8[pc]  # [P, 32] gather from earlier levels
            seg = seg.at[pl[:, None], po[:, None] + ar32[None, :]].set(vals)
        words = _u8_to_words(seg, spec.blocks)
        nb = jax.lax.dynamic_slice(nblocks, (spec.gstart,), (spec.lanes,))
        out = _keccak_segment(words, nb)
        dig8 = jax.lax.dynamic_update_slice(dig8, _words_to_u8(out), (spec.gstart, 0))
    return dig8


def _pow2_at_least(v: int, floor: int = 16) -> int:
    t = floor
    while t < v:
        t *= 2
    return t


class FusedBatch:
    """Host-side builder collecting levels of (padded message, patches).

    add_level() takes the level's messages (keccak-padded bytes with zeroed
    digest slots) and patches [(msg_idx_in_level, byte_off, child_gidx)];
    returns the global indices assigned to the level's lanes. run() makes
    one device call and returns all digests.
    """

    def __init__(self):
        self.levels: List[dict] = []
        self.total = 0

    def add_level(self, padded_msgs: List[bytes], nblocks: List[int],
                  patches: List[Tuple[int, int, int]]) -> List[int]:
        gids = list(range(self.total, self.total + len(padded_msgs)))
        self.levels.append({
            "msgs": padded_msgs,
            "nblocks": nblocks,
            "patches": patches,
            "gids": gids,
        })
        self.total += len(padded_msgs)
        return gids

    def run(self, impl=fused_commit) -> List[bytes]:
        """Build segment arrays (bucketed by block count, padded to
        power-of-two lane counts) and execute. Returns digests by gid.

        Packing is vectorized: per segment, messages are joined once and
        scattered with a single fancy-indexed assignment (no per-lane
        Python loop), mirroring keccak_jax.pack_messages."""
        specs: List[SegmentSpec] = []
        seg_msgs: List[np.ndarray] = []
        all_nblocks: List[np.ndarray] = []
        all_pl: List[np.ndarray] = []
        all_po: List[np.ndarray] = []
        all_pc: List[np.ndarray] = []
        remap = np.zeros(max(self.total, 1), dtype=np.int64)
        gpos = 0

        for level in self.levels:
            msgs = level["msgs"]
            if not msgs:
                continue
            nb = np.asarray(level["nblocks"], dtype=np.int32)
            gid0 = level["gids"][0] if level["gids"] else 0
            patches = level["patches"]  # (msg_idx, off, child_gid)

            # bucket by power-of-two block count
            keys = np.asarray([1 << int(b - 1).bit_length() if b > 1 else 1 for b in nb])
            patch_msgs = {mi for mi, _, _ in patches}
            for key in np.unique(keys):
                (idxs,) = np.nonzero(keys == key)
                has_patches = any(int(mi) in patch_msgs for mi in idxs)
                lanes = _pow2_at_least(len(idxs) + (1 if has_patches else 0))
                width = int(key) * RATE
                arr = np.zeros((lanes, width), dtype=np.uint8)
                # vectorized scatter of all bucket messages at once
                lengths = np.asarray([len(msgs[int(mi)]) for mi in idxs], dtype=np.int64)
                if len(idxs):
                    src = np.frombuffer(b"".join(msgs[int(mi)] for mi in idxs), dtype=np.uint8)
                    starts = np.zeros(len(idxs), dtype=np.int64)
                    np.cumsum(lengths[:-1], out=starts[1:])
                    within = np.arange(int(lengths.sum()), dtype=np.int64) - np.repeat(starts, lengths)
                    dest = np.repeat(np.arange(len(idxs), dtype=np.int64) * width, lengths) + within
                    arr.reshape(-1)[dest] = src
                seg_nb = np.ones(lanes, dtype=np.int32)
                seg_nb[: len(idxs)] = nb[idxs]
                remap[np.asarray(level["gids"], dtype=np.int64)[idxs]] = (
                    gpos + np.arange(len(idxs), dtype=np.int64)
                )
                # per-bucket patch tables (msg_idx -> bucket lane)
                lane_of = {int(mi): lane for lane, mi in enumerate(idxs)}
                pl, po, pc = [], [], []
                for mi, off, child in patches:
                    lane = lane_of.get(mi)
                    if lane is not None:
                        pl.append(lane)
                        po.append(off)
                        pc.append(child)
                scratch = lanes - 1
                n_patches = _pow2_at_least(len(pl), 16) if pl else 0
                for _ in range(n_patches - len(pl)):
                    pl.append(scratch)
                    po.append(0)
                    pc.append(-1)
                specs.append(SegmentSpec(int(key), lanes, gpos, n_patches))
                seg_msgs.append(arr)
                all_nblocks.append(seg_nb)
                all_pl.append(np.asarray(pl, dtype=np.int32))
                all_po.append(np.asarray(po, dtype=np.int32))
                all_pc.append(np.asarray(pc, dtype=np.int64))
                gpos += lanes

        # child gids -> packed positions (vectorized; pads (-1) -> lane 0,
        # harmless: their write lands in the scratch lane)
        flat_pc = [
            np.where(pc >= 0, remap[np.maximum(pc, 0)], 0).astype(np.int32)
            for pc in all_pc
        ]

        flat_msgs = (
            np.concatenate([a.reshape(-1) for a in seg_msgs])
            if seg_msgs
            else np.zeros(0, dtype=np.uint8)
        )
        nblocks = (
            np.concatenate(all_nblocks) if all_nblocks else np.zeros(0, np.int32)
        )
        patch_lane = (
            np.concatenate(all_pl) if all_pl else np.zeros(0, np.int32)
        )
        patch_off = (
            np.concatenate(all_po) if all_po else np.zeros(0, np.int32)
        )
        patch_child = (
            np.concatenate(flat_pc) if flat_pc else np.zeros(0, np.int32)
        )

        dig8 = np.asarray(
            impl(
                tuple(specs),
                jnp.asarray(flat_msgs),
                jnp.asarray(nblocks),
                jnp.asarray(patch_lane),
                jnp.asarray(patch_off),
                jnp.asarray(patch_child),
            )
        )
        # one gather puts digests back into gid order; slice lazily
        ordered = dig8[remap[: self.total]]
        raw = ordered.tobytes()
        return [raw[i * 32 : i * 32 + 32] for i in range(self.total)]
