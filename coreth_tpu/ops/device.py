"""Device-hasher resolution for the production chain path.

The reference engages its parallel hasher automatically from the hot path
(/root/reference/trie/trie.go:618-619: >=100 unhashed nodes -> 16
goroutines). The TPU-native equivalent: `get_batch_keccak("auto")` hands
the chain a batched device keccak (ops/keccak_jax.BatchedKeccak) that
Trie.hash() engages above trie/hasher.BATCH_THRESHOLD, with the recursive
C++-keccak hasher below it. "off" keeps everything on the CPU hasher.

Resolution is lazy and fails soft: when JAX/the device backend is
unavailable the chain silently runs CPU-only — hashing is bit-exact either
way, so this is purely a throughput decision.
"""

from __future__ import annotations

from typing import Callable, Optional

_cached: dict = {}


def get_batch_keccak(mode: str = "auto") -> Optional[Callable]:
    """Resolve a `list[bytes] -> list[bytes32]` batched keccak, or None.

    mode: "auto"    — the planned u32 executor when the backend resolves
                      (same as "planned"), silent CPU fallback otherwise
          "planned" — the production fast path: Trie.hash/StateDB commits
                      drain through trie/planned.PlannedGraphBuilder ->
                      ops/keccak_planned.PlannedCommit — ONE bulk u32
                      transfer per commit, child digests AND storage roots
                      patched on device in word space, zero byte-level ops
                      on device. Fails loudly when forced.
          "batched" — level-batched hashing (one dispatch per trie level);
                      unavailability is an error: the operator forced the
                      device path, so degrading quietly would hide a
                      node-wide throughput regression
          "fused"   — single-dispatch commits: Trie.hash ships the whole
                      dirty set in ONE transfer with on-device digest
                      patching (trie/hasher.FusedHasher). Superseded by
                      "planned" (its on-device uint8 unpacking costs ~100x
                      the hashing, PERF.md); kept for A/B comparison.
          "off"     — None (CPU recursive hasher everywhere)
    """
    if mode == "off":
        return None
    if mode not in ("auto", "planned", "batched", "fused"):
        raise ValueError(f"unknown device-hasher mode {mode!r}")
    if "fn" not in _cached:
        try:
            from ..utils import enable_compilation_cache

            enable_compilation_cache()
            from .keccak_jax import BatchedKeccak

            _cached["fn"] = BatchedKeccak().digests
        except Exception as e:  # fail-soft is only legal for "auto"
            import warnings

            warnings.warn(f"device keccak unavailable, chain runs CPU-only: {e!r}")
            _cached["fn"] = None
            _cached["error"] = e
    if _cached["fn"] is None and mode in ("planned", "batched", "fused"):
        raise RuntimeError(
            f"device-hasher forced to {mode!r} but the device keccak failed "
            f"to resolve: {_cached.get('error')!r}"
        )
    if _cached["fn"] is None:
        return None
    if mode == "fused":
        return FusedModeKeccak(_cached["fn"])
    if mode in ("auto", "planned"):
        return PlannedModeKeccak(_cached["fn"])
    return _cached["fn"]


class PlannedModeKeccak:
    """Marker wrapper telling Trie.hash / StateDB.intermediate_root to take
    the planned u32 executor path; still callable as a plain batch keccak
    so every other consumer of the seam (proof verification, precompile)
    works unchanged."""

    planned = True

    def __init__(self, digests):
        self._digests = digests

    def __call__(self, msgs):
        from ..trie.hasher import count_keccak_batch

        count_keccak_batch(len(msgs))
        return self._digests(msgs)


class FusedModeKeccak:
    """Marker wrapper telling Trie.hash to take the single-dispatch
    FusedHasher path; still callable as a plain batch keccak so every
    other consumer of the seam (proof verification, precompile) works
    unchanged."""

    fused = True

    def __init__(self, digests):
        self._digests = digests

    def __call__(self, msgs):
        from ..trie.hasher import count_keccak_batch

        count_keccak_batch(len(msgs))
        return self._digests(msgs)
