"""Device-hasher resolution + the device degradation ladder.

The reference engages its parallel hasher automatically from the hot path
(/root/reference/trie/trie.go:618-619: >=100 unhashed nodes -> 16
goroutines). The TPU-native equivalent: `get_batch_keccak("auto")` hands
the chain a batched device keccak (ops/keccak_jax.BatchedKeccak) that
Trie.hash() engages above trie/hasher.BATCH_THRESHOLD, with the recursive
C++-keccak hasher below it. "off" keeps everything on the CPU hasher.

Resolution is lazy and fails soft: when JAX/the device backend is
unavailable the chain runs CPU-only — hashing is bit-exact either way, so
this is purely a throughput decision. The failure is loud in diagnostics
(structured log + `ops/device/resolve_fail` counter + the cached error in
debug_metrics), just silent to the block pipeline.

The degradation ladder (this PR's robustness layer): the bench artifacts'
standing caveat is an axon tunnel that wedges mid-run, after resolution
succeeded. `DeviceLadder` wraps every laddered device dispatch in a
watchdog with bounded retry/backoff, and on exhaustion demotes the whole
device seam to the host MID-RUN:

    healthy --(timeout / repeated errors)--> demoted
    demoted --(1 healthy background probe)--> probation
    probation --(promote_after consecutive healthy probes)--> healthy
    probation --(any failed probe)--> demoted

Demotion flips `PlannedModeKeccak.planned` (a dynamic property) to False,
which reroutes Trie.hash and StateDB.intermediate_root to their host
paths, and routes the plain-callable seam through the threaded native
batch keccak — roots stay bit-exact through every rung. Events fan out to
listeners (core/blockchain pipes them into the flight recorder) and the
`ops/device/demotions` / `ops/device/promotions` counters.
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

from ..fault import Backoff, FailpointError, failpoint
from ..fault import register as _register_failpoint

_cached: dict = {}

# failpoint sites (fault/__init__.py registry; armed via
# CORETH_TPU_FAILPOINTS or debug_setFailpoint)
FP_RESOLVE = _register_failpoint(
    "ops/device/resolve", "during lazy device-keccak resolution")
FP_DISPATCH = _register_failpoint(
    "ops/device/dispatch",
    "inside every laddered device dispatch (runs on the watchdog worker "
    "thread, so `hang` exercises the deadline)")
FP_PROBE = _register_failpoint(
    "ops/device/probe", "inside the ladder's background health probe")


class DeviceDegradedError(RuntimeError):
    """A laddered device dispatch exhausted its watchdog/retry budget and
    the ladder demoted to host; callers fall back to the host path."""


class DeviceLadder:
    """Process-wide device health state machine (the device, like the
    cached keccak fn, is process-global). Chains configure it from
    CacheConfig at construction and subscribe for flight-recorder
    events; `coreth_tpu.fault`-driven chaos tests drive it directly."""

    HEALTHY = "healthy"
    DEMOTED = "demoted"
    PROBATION = "probation"

    PROBE_MSG = b"coreth-tpu device health probe"
    DEFAULT_PROBE_TIMEOUT = 5.0

    def __init__(self):
        self._lock = threading.Lock()
        self.state = self.HEALTHY  # guarded-by: _lock
        self.last_error: Optional[str] = None  # guarded-by: _lock
        # knobs (configure()): call_timeout None = watchdog off — dispatch
        # runs inline with zero extra threads, the seed behavior
        self.call_timeout: Optional[float] = None
        self.max_retries = 1
        self.retry_base = 0.05
        self.probe_interval = 5.0
        self.promote_after = 3
        self._healthy_probes = 0  # guarded-by: _lock
        self._listeners: List[Callable] = []  # guarded-by: _lock
        self._probe_gen = 0  # guarded-by: _lock; invalidates stale probes
        self._probe_wake = threading.Event()

    # ---- configuration / wiring -----------------------------------------

    def configure(self, call_timeout: Optional[float] = None,
                  max_retries: Optional[int] = None,
                  probe_interval: Optional[float] = None,
                  promote_after: Optional[int] = None) -> None:
        """Apply chain knobs (CacheConfig.device_*). 0 timeouts mean
        'off', matching the resident watchdog's convention."""
        with self._lock:
            if call_timeout is not None:
                self.call_timeout = call_timeout if call_timeout > 0 else None
            if max_retries is not None:
                self.max_retries = max(0, int(max_retries))
            if probe_interval is not None:
                self.probe_interval = float(probe_interval)
            if promote_after is not None:
                self.promote_after = max(1, int(promote_after))

    def add_listener(self, fn: Callable) -> None:
        """fn(kind, fields) on every ladder event: retry/demote/
        probation/promote. Exceptions are counted, never propagated."""
        with self._lock:
            self._listeners.append(fn)

    def remove_listener(self, fn: Callable) -> None:
        with self._lock:
            try:
                self._listeners.remove(fn)
            except ValueError:
                pass

    def _notify(self, kind: str, **fields) -> None:
        with self._lock:
            listeners = list(self._listeners)
        for fn in listeners:
            try:
                fn(kind, dict(fields))
            except Exception:
                from ..metrics import count_drop

                count_drop("ops/device/listener_error")

    # ---- state -----------------------------------------------------------

    @property
    def healthy(self) -> bool:
        return self.state == self.HEALTHY

    def status(self) -> dict:
        with self._lock:
            return {
                "state": self.state,
                "last_error": self.last_error,
                "healthy_probes": self._healthy_probes,
                "call_timeout": self.call_timeout,
                "max_retries": self.max_retries,
                "probe_interval": self.probe_interval,
                "promote_after": self.promote_after,
            }

    def reset(self) -> None:
        """Back to healthy with no listeners; retires any probe thread.
        Test isolation — the ladder is process-global."""
        with self._lock:
            self.state = self.HEALTHY
            self.last_error = None
            self._healthy_probes = 0
            self._listeners.clear()
            self._probe_gen += 1
            self._probe_wake.set()
            self._probe_wake = threading.Event()

    # ---- dispatch (the watchdogged device call) --------------------------

    def dispatch(self, fn: Callable, what: str, *args):
        """Run one device call under the ladder: per-call watchdog
        deadline (call_timeout), bounded retry with capped backoff for
        transient errors, demotion on exhaustion. Raises
        DeviceDegradedError after demoting; callers take the host path."""
        from ..metrics import default_registry

        def run():
            failpoint("ops/device/dispatch")
            return fn(*args)

        timeout = self.call_timeout
        attempts = self.max_retries + 1
        backoff = Backoff(base=self.retry_base, cap=2.0)
        last: Optional[BaseException] = None
        for attempt in range(attempts):
            try:
                if timeout is not None:
                    from ..native.mpt import _run_with_watchdog

                    return _run_with_watchdog(run, timeout, what)
                return run()
            except Exception as e:
                last = e
                default_registry.counter("ops/device/dispatch_errors").inc()
                if attempt + 1 < attempts:
                    self._notify("retry", what=what, attempt=attempt + 1,
                                 error=repr(e))
                    backoff.sleep()
        self.demote(f"{what}: {last!r}")
        raise DeviceDegradedError(
            f"{what} demoted to host after {attempts} attempt(s): {last!r}"
        ) from last

    # ---- demotion / probation / re-promotion -----------------------------

    def demote(self, why: str) -> None:
        """Device -> host, idempotent. Starts the background probe loop
        that earns the way back (probation -> re-promotion)."""
        from ..log import error, get_logger
        from ..metrics import default_registry

        with self._lock:
            if self.state != self.HEALTHY:
                self.last_error = why
                return
            self.state = self.DEMOTED
            self._healthy_probes = 0
            self.last_error = why
        default_registry.counter("ops/device/demotions").inc()
        error(get_logger("ops"),
              "device demoted to host: dispatches run CPU-side until "
              "background probes re-promote", why=why)
        self._notify("demote", why=why)
        self._start_probe_thread()

    def promote(self) -> None:
        from ..log import get_logger, info
        from ..metrics import default_registry

        with self._lock:
            if self.state == self.HEALTHY:
                return
            self.state = self.HEALTHY
            self._healthy_probes = 0
        default_registry.counter("ops/device/promotions").inc()
        info(get_logger("ops"), "device re-promoted after healthy probes")
        self._notify("promote")

    def _probe_fn(self) -> Optional[Callable]:
        return _cached.get("fn")

    def _start_probe_thread(self) -> None:
        with self._lock:
            if (self.probe_interval <= 0 or self.promote_after <= 0
                    or _cached.get("fn") is None):
                return  # no road back: stay demoted (or no device at all)
            self._probe_gen += 1
            gen = self._probe_gen
        threading.Thread(target=self._probe_loop, args=(gen,),
                         name="device-probe", daemon=True).start()

    def _probe_loop(self, gen: int) -> None:
        from ..metrics import default_registry
        from ..native import keccak256 as _host_keccak
        from ..native.mpt import _run_with_watchdog

        expected = _host_keccak(self.PROBE_MSG)
        while True:
            with self._lock:
                if gen != self._probe_gen or self.state == self.HEALTHY:
                    return
                wake = self._probe_wake
                interval = self.probe_interval
                timeout = self.call_timeout or self.DEFAULT_PROBE_TIMEOUT
                fn = _cached.get("fn")
            wake.wait(interval)
            with self._lock:
                if gen != self._probe_gen or self.state == self.HEALTHY:
                    return
            if fn is None:
                return

            def probe():
                failpoint("ops/device/probe")
                return fn([self.PROBE_MSG])

            try:
                out = _run_with_watchdog(probe, timeout, "device health probe")
                ok = bool(out) and bytes(out[0]) == expected
            except Exception:
                default_registry.counter("ops/device/probe_errors").inc()
                ok = False
            self._on_probe(ok)

    def _on_probe(self, ok: bool) -> None:
        with self._lock:
            if ok:
                self._healthy_probes += 1
                entered_probation = self.state == self.DEMOTED
                if entered_probation:
                    self.state = self.PROBATION
                promote = self._healthy_probes >= self.promote_after
                probes = self._healthy_probes
            else:
                self._healthy_probes = 0
                relapsed = self.state == self.PROBATION
                if relapsed:
                    self.state = self.DEMOTED
                entered_probation = promote = False
        if ok and entered_probation:
            self._notify("probation", healthy_probes=probes)
        if ok and promote:
            self.promote()


_ladder = DeviceLadder()


def default_ladder() -> DeviceLadder:
    """The process-wide ladder every laddered seam shares."""
    return _ladder


def resolution_error() -> Optional[str]:
    """The cached device-resolution failure, if any (debug_metrics)."""
    e = _cached.get("error")
    return repr(e) if e is not None else None


def _host_batch_keccak(msgs) -> List[bytes]:
    """Bit-exact host fallback for a demoted device seam: the threaded
    native C++ batch keccak (same engine as trie/hasher.cpu_batch_keccak,
    minus the double-count of the batch counters — the marker wrappers
    already counted the batch)."""
    from ..native import default_cpu_threads, keccak256_batch

    return keccak256_batch(msgs, threads=default_cpu_threads())


def get_batch_keccak(mode: str = "auto") -> Optional[Callable]:
    """Resolve a `list[bytes] -> list[bytes32]` batched keccak, or None.

    mode: "auto"    — the planned u32 executor when the backend resolves
                      (same as "planned"), silent CPU fallback otherwise
          "planned" — the production fast path: Trie.hash/StateDB commits
                      drain through trie/planned.PlannedGraphBuilder ->
                      ops/keccak_planned.PlannedCommit — ONE bulk u32
                      transfer per commit, child digests AND storage roots
                      patched on device in word space, zero byte-level ops
                      on device. Fails loudly when forced.
          "batched" — level-batched hashing (one dispatch per trie level);
                      unavailability is an error: the operator forced the
                      device path, so degrading quietly would hide a
                      node-wide throughput regression
          "fused"   — single-dispatch commits: Trie.hash ships the whole
                      dirty set in ONE transfer with on-device digest
                      patching (trie/hasher.FusedHasher). Superseded by
                      "planned" (its on-device uint8 unpacking costs ~100x
                      the hashing, PERF.md); kept for A/B comparison and
                      NOT laddered — wrapping it would change what the A/B
                      measures.

          "off"     — None (CPU recursive hasher everywhere)

    Every returned callable except "fused" routes through the process
    DeviceLadder: healthy calls dispatch to the device (watchdogged when
    a deadline is configured), demoted calls run the bit-exact native
    host batch keccak.
    """
    if mode == "off":
        return None
    if mode not in ("auto", "planned", "batched", "fused"):
        raise ValueError(f"unknown device-hasher mode {mode!r}")
    if "fn" not in _cached:
        try:
            failpoint("ops/device/resolve")
            from ..utils import enable_compilation_cache

            enable_compilation_cache()
            from .keccak_jax import BatchedKeccak

            _cached["fn"] = BatchedKeccak().digests
        except Exception as e:  # fail-soft is only legal for "auto"
            from ..log import get_logger, warn
            from ..metrics import default_registry

            default_registry.counter("ops/device/resolve_fail").inc()
            warn(get_logger("ops"),
                 "device keccak unavailable, chain runs CPU-only",
                 error=repr(e),
                 failpoint=isinstance(e, FailpointError))
            _cached["fn"] = None
            _cached["error"] = e
    if _cached["fn"] is None and mode in ("planned", "batched", "fused"):
        raise RuntimeError(
            f"device-hasher forced to {mode!r} but the device keccak failed "
            f"to resolve: {_cached.get('error')!r}"
        )
    if _cached["fn"] is None:
        return None
    if mode == "fused":
        return FusedModeKeccak(_cached["fn"])
    if mode in ("auto", "planned"):
        return PlannedModeKeccak(_cached["fn"])
    return LadderedKeccak(_cached["fn"])


class LadderedKeccak:
    """Plain batch-keccak seam behind the degradation ladder: dispatches
    to the device while the ladder is healthy, runs the bit-exact native
    host batch when demoted (mid-call demotion included)."""

    def __init__(self, digests, ladder: Optional[DeviceLadder] = None):
        self._digests = digests
        self._ladder = ladder if ladder is not None else _ladder

    def __call__(self, msgs):
        from ..trie.hasher import count_keccak_batch

        count_keccak_batch(len(msgs))
        lad = self._ladder
        if not lad.healthy:
            return _host_batch_keccak(msgs)
        try:
            return lad.dispatch(self._digests, "device batch keccak", msgs)
        except DeviceDegradedError:
            return _host_batch_keccak(msgs)


class PlannedModeKeccak(LadderedKeccak):
    """Marker wrapper telling Trie.hash / StateDB.intermediate_root to take
    the planned u32 executor path; still callable as a plain batch keccak
    so every other consumer of the seam (proof verification, precompile)
    works unchanged.

    `planned` is a dynamic property, not a class attribute: while the
    ladder is demoted it reads False, which flips both consumers
    (trie/trie.py Trie.hash, state/statedb.py intermediate_root — they
    getattr the marker per call) to their host paths mid-run. Host and
    device hashing are bit-exact, so the only observable change is where
    the keccak runs."""

    @property
    def planned(self) -> bool:
        return self._ladder.healthy


class FusedModeKeccak:
    """Marker wrapper telling Trie.hash to take the single-dispatch
    FusedHasher path; still callable as a plain batch keccak so every
    other consumer of the seam (proof verification, precompile) works
    unchanged. Kept OFF the ladder: the mode exists for A/B comparison
    against "planned", and laddering it would change the measurement."""

    fused = True

    def __init__(self, digests):
        self._digests = digests

    def __call__(self, msgs):
        from ..trie.hasher import count_keccak_batch

        count_keccak_batch(len(msgs))
        return self._digests(msgs)
