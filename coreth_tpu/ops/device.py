"""Device-hasher resolution for the production chain path.

The reference engages its parallel hasher automatically from the hot path
(/root/reference/trie/trie.go:618-619: >=100 unhashed nodes -> 16
goroutines). The TPU-native equivalent: `get_batch_keccak("auto")` hands
the chain a batched device keccak (ops/keccak_jax.BatchedKeccak) that
Trie.hash() engages above trie/hasher.BATCH_THRESHOLD, with the recursive
C++-keccak hasher below it. "off" keeps everything on the CPU hasher.

Resolution is lazy and fails soft: when JAX/the device backend is
unavailable the chain silently runs CPU-only — hashing is bit-exact either
way, so this is purely a throughput decision.
"""

from __future__ import annotations

from typing import Callable, Optional

_cached: dict = {}


def get_batch_keccak(mode: str = "auto") -> Optional[Callable]:
    """Resolve a `list[bytes] -> list[bytes32]` batched keccak, or None.

    mode: "auto" | "batched" — device-batched hashing (same callable; auto
          exists so config files can distinguish "default" from "forced")
          "off" — None (CPU recursive hasher everywhere)
    """
    if mode == "off":
        return None
    if mode not in ("auto", "batched"):
        raise ValueError(f"unknown device-hasher mode {mode!r}")
    if "fn" in _cached:
        return _cached["fn"]
    try:
        from ..utils import enable_compilation_cache

        enable_compilation_cache()
        from .keccak_jax import BatchedKeccak

        fn = BatchedKeccak().digests
    except Exception:
        fn = None
    _cached["fn"] = fn
    return fn
