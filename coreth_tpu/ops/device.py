"""Device-hasher resolution for the production chain path.

The reference engages its parallel hasher automatically from the hot path
(/root/reference/trie/trie.go:618-619: >=100 unhashed nodes -> 16
goroutines). The TPU-native equivalent: `get_batch_keccak("auto")` hands
the chain a batched device keccak (ops/keccak_jax.BatchedKeccak) that
Trie.hash() engages above trie/hasher.BATCH_THRESHOLD, with the recursive
C++-keccak hasher below it. "off" keeps everything on the CPU hasher.

Resolution is lazy and fails soft: when JAX/the device backend is
unavailable the chain silently runs CPU-only — hashing is bit-exact either
way, so this is purely a throughput decision.
"""

from __future__ import annotations

from typing import Callable, Optional

_cached: dict = {}


def get_batch_keccak(mode: str = "auto") -> Optional[Callable]:
    """Resolve a `list[bytes] -> list[bytes32]` batched keccak, or None.

    mode: "auto"    — device-batched hashing when the backend resolves,
                      silent CPU fallback otherwise
          "batched" — same callable, but unavailability is an error: the
                      operator forced the device path, so degrading quietly
                      would hide a node-wide throughput regression
          "off"     — None (CPU recursive hasher everywhere)
    """
    if mode == "off":
        return None
    if mode not in ("auto", "batched"):
        raise ValueError(f"unknown device-hasher mode {mode!r}")
    if "fn" not in _cached:
        try:
            from ..utils import enable_compilation_cache

            enable_compilation_cache()
            from .keccak_jax import BatchedKeccak

            _cached["fn"] = BatchedKeccak().digests
        except Exception as e:  # fail-soft is only legal for "auto"
            import warnings

            warnings.warn(f"device keccak unavailable, chain runs CPU-only: {e!r}")
            _cached["fn"] = None
            _cached["error"] = e
    if _cached["fn"] is None and mode == "batched":
        raise RuntimeError(
            "device-hasher forced to 'batched' but the device keccak failed "
            f"to resolve: {_cached.get('error')!r}"
        )
    return _cached["fn"]
