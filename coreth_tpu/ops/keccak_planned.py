"""Planned trie commit, u32 end-to-end: one bulk transfer, device-resident
chaining, zero byte-level ops on device.

What profiling showed about the previous staged executor
(ops/keccak_staged.py) on the tunneled TPU:
  - per-segment device_put calls dominate: every small h2d pays the
    tunnel round-trip (~75ms floor per synchronized step, 20 segments)
  - uint8 reshaping/scatter inside the jitted steps costs ~100x the
    keccak itself (TPU has no native u8 lanes; XLA relayouts)

This executor removes both:
  - the C++ planner's flat byte buffer IS the little-endian u32 word
    stream keccak absorbs — numpy reinterprets it for free, ONE
    device_put ships the whole commit (plus three patch tables + one
    64-row metadata array)
  - the parent<-child digest dependency resolves on device in word
    space: for each patch, a 9-word contribution strip is built by
    gathering the child's digest words and barrel-shifting them to the
    byte offset (shift = offset%4); strips scatter-ADD into the flat
    words. Template bytes at the destination are zero, and overlapping
    strip boundaries touch disjoint bits, so add == or == exact patch.
  - per-segment steps slice the device-resident flat words
    (lax.dynamic_slice, offsets read from the uploaded metadata row, so
    trie resizing never recompiles), hash with the scanned-block
    segment kernel, and write digests into the donated dig buffer
    (row 0 is an all-zero sentinel: pad patches point there)

Reference seam: this replaces trie/hasher.go:124-139's 16-goroutine
fan-out + channel joins for the whole-trie commit drain
(core/state/statedb.go:952, trie/trie.go:585-626).
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .keccak_jax import RATE
from .keccak_staged import _segment_keccak

WORDS_PER_BLOCK = RATE // 4  # 34
MAX_SEGMENTS = 64


def _strip_contributions(dig: jax.Array, child_row: jax.Array,
                         shift: jax.Array) -> jax.Array:
    """[P] child rows (+1-offset, 0 = zero sentinel) and byte shifts
    -> uint32[P, 9] contribution strips."""
    d = dig[child_row]                       # [P, 8]
    p = d.shape[0]
    dpad = jnp.concatenate(
        [jnp.zeros((p, 1), jnp.uint32), d, jnp.zeros((p, 1), jnp.uint32)],
        axis=1,
    )                                        # [P, 10]; dpad[:, j] == D[j-1]
    lsh = (8 * shift)[:, None]               # [P, 1]
    rsh = (32 - 8 * shift)[:, None]
    lo = dpad[:, :9] >> jnp.minimum(rsh, 31).astype(jnp.uint32)
    lo = jnp.where(shift[:, None] == 0, jnp.uint32(0), lo)
    hi = dpad[:, 1:] << lsh.astype(jnp.uint32)
    return lo | hi


def _make_step(seg_impl, donate: bool = True):
    """Build the jitted per-segment step around one keccak kernel.

    Static args are SHAPES only (lanes, blocks, npatch, all bucketed) —
    the segment's offsets travel in the uploaded metadata row selected by
    the traced scalar `seg_i`, so trie resizing never recompiles.
    donate=False builds a re-invokable variant (driver compile checks)."""

    @functools.partial(
        jax.jit,
        static_argnames=("lanes", "blocks", "npatch"),
        donate_argnums=(0, 1) if donate else (),
    )
    def step(flat_words, dig, dstw_all, child_all, shift_all, meta, seg_i,
             *, lanes: int, blocks: int, npatch: int):
        """flat_words: uint32[W] (donated), dig: uint32[1+G, 8] (donated),
        meta: int32[MAX_SEGMENTS, 3] = (word_off, gstart, patch_off)."""
        row = jax.lax.dynamic_slice(meta, (seg_i, 0), (1, 3))[0]
        word_off, gstart, patch_off = row[0], row[1], row[2]
        if npatch:
            dstw = jax.lax.dynamic_slice(dstw_all, (patch_off,), (npatch,))
            child = jax.lax.dynamic_slice(child_all, (patch_off,), (npatch,))
            shift = jax.lax.dynamic_slice(shift_all, (patch_off,), (npatch,))
            strips = _strip_contributions(dig, child, shift)  # [P, 9]
            idx = dstw[:, None] + jnp.arange(9, dtype=jnp.int32)[None, :]
            flat_words = flat_words.at[idx.reshape(-1)].add(
                strips.reshape(-1), mode="drop"
            )
        n_words = lanes * blocks * WORDS_PER_BLOCK
        words = jax.lax.dynamic_slice(flat_words, (word_off,), (n_words,))
        words = words.reshape(lanes, blocks, WORDS_PER_BLOCK)
        out = seg_impl(words)                                 # [lanes, 8]
        dig = jax.lax.dynamic_update_slice(
            dig, out, (gstart + 1, jnp.int32(0))
        )
        return flat_words, dig

    return step


_default_step = _make_step(_segment_keccak)


def _make_fused_builder(seg_impl, donate: bool = True):
    """Whole-commit fused program builder (VERDICT r4 #3: per-commit
    dispatch count must not scale with segment count on a high-latency
    link).

    One jitted program per STATIC specs tuple runs every segment —
    patch-scatter, slice, keccak, digest write — in a single dispatch.
    Because the program is keyed on the full (blocks, lanes, gstart,
    n_patches) tuple, all word/patch offsets are trace-time constants:
    no metadata upload, no dynamic slicing. Lane bucketing in the native
    planner keeps the set of distinct tuples small in steady state, and
    the persistent compilation cache carries compiled programs across
    processes."""

    @functools.lru_cache(maxsize=256)
    def build(specs):
        total_lanes = sum(s.lanes for s in specs)
        n_pat_total = sum(s.n_patches for s in specs)

        @functools.partial(jax.jit, donate_argnums=(0,) if donate else ())
        def run(flat_words, aux):
            # aux: int32[3 * n_pat_total] = dst_word | child(+1) | shift
            dstw_all = aux[:n_pat_total]
            child_all = aux[n_pat_total:2 * n_pat_total]
            shift_all = aux[2 * n_pat_total:3 * n_pat_total]
            dig = jnp.zeros((1 + total_lanes, 8), jnp.uint32)
            word_off = patch_off = 0
            for s in specs:
                if s.n_patches:
                    dstw = dstw_all[patch_off:patch_off + s.n_patches]
                    child = child_all[patch_off:patch_off + s.n_patches]
                    shift = shift_all[patch_off:patch_off + s.n_patches]
                    strips = _strip_contributions(dig, child, shift)
                    idx = dstw[:, None] + jnp.arange(9, dtype=jnp.int32)[None]
                    flat_words = flat_words.at[idx.reshape(-1)].add(
                        strips.reshape(-1), mode="drop"
                    )
                n_words = s.lanes * s.blocks * WORDS_PER_BLOCK
                words = flat_words[word_off:word_off + n_words]
                words = words.reshape(s.lanes, s.blocks, WORDS_PER_BLOCK)
                out = seg_impl(words)                          # [lanes, 8]
                dig = jax.lax.dynamic_update_slice(
                    dig, out, (s.gstart + 1, 0))
                word_off += n_words
                patch_off += s.n_patches
            return dig

        return run

    return build


def _fuse_default() -> bool:
    import os

    return os.environ.get("CORETH_TPU_PLANNED_FUSE", "1") != "0"


class PlannedCommit:
    """Execute a CommitPlan's word-space export.

    seg_impl: optional override of the per-segment keccak
    (uint32[P, L, 34] -> uint32[P, 8]) — the Pallas kernel plugs in here
    for lane counts its grid can tile.

    fused=True (default, CORETH_TPU_PLANNED_FUSE=0 disables) runs the
    whole commit as ONE device dispatch + TWO uploads; fused=False keeps
    the per-segment shape-keyed steps (no per-workload recompiles — the
    dryrun/compile-check path).

    After every run(): last_h2d_bytes / last_transfers / last_dispatches
    hold the commit's exact link traffic for bench attribution."""

    def __init__(self, seg_impl=None, fused: Optional[bool] = None):
        impl = _segment_keccak if seg_impl is None else seg_impl
        self._step = _default_step if seg_impl is None else _make_step(impl)
        self._fused = _make_fused_builder(impl)
        self.fused = _fuse_default() if fused is None else fused
        self.last_h2d_bytes = 0
        self.last_transfers = 0
        self.last_dispatches = 0

    def run(self, specs: Sequence, flat_words: np.ndarray,
            dst_word: np.ndarray, child_lane: np.ndarray,
            shift: np.ndarray, root_pos: int,
            want_digests: bool = False) -> Tuple[bytes, Optional[np.ndarray]]:  # hot-path
        """Inputs from CommitPlan.export_words(). Returns (root32,
        dig uint32[G, 8] | None)."""
        from ..metrics import phase_timer

        n_seg = len(specs)
        if n_seg > MAX_SEGMENTS:
            raise ValueError(f"{n_seg} segments > MAX_SEGMENTS={MAX_SEGMENTS}")
        total_lanes = sum(s.lanes for s in specs)

        if self.fused:
            with phase_timer("planned/phase/scatter"):
                aux = np.concatenate([
                    dst_word.astype(np.int32),
                    (child_lane + 1).astype(np.int32),
                    shift.astype(np.int32),
                ]) if len(dst_word) else np.zeros(0, np.int32)
                fw = jax.device_put(flat_words)
                ax = jax.device_put(aux)
            self.last_h2d_bytes = flat_words.nbytes + aux.nbytes
            self.last_transfers = 2
            self.last_dispatches = 1
            with phase_timer("planned/phase/patch"):
                dig = self._fused(tuple(specs))(fw, ax)
            with phase_timer("planned/phase/store"):
                if want_digests:
                    host = np.asarray(dig)
                    return (host[root_pos + 1].astype("<u4").tobytes(),
                            host[1:])
                root = np.asarray(dig[root_pos + 1])
                return root.astype("<u4").tobytes(), None

        meta = np.zeros((MAX_SEGMENTS, 3), np.int32)
        word_off = 0
        patch_off = 0
        for i, s in enumerate(specs):
            meta[i] = (word_off, s.gstart, patch_off)
            word_off += s.lanes * s.blocks * WORDS_PER_BLOCK
            patch_off += s.n_patches

        with phase_timer("planned/phase/scatter"):
            # whole commit's h2d: one bulk word stream + patch tables + meta
            fw = jax.device_put(flat_words)
            # +1: sentinel zero row that pad patches (child_lane == -1)
            # gather
            ch = jax.device_put((child_lane + 1).astype(np.int32))
            dw = jax.device_put(dst_word)
            sh = jax.device_put(shift)
            mt = jax.device_put(meta)
            # per-step segment ids sliced on device (no per-step h2d, and
            # the step programs stay shape-keyed only)
            seg_ids = jax.device_put(np.arange(MAX_SEGMENTS, dtype=np.int32))
        dig = jnp.zeros((1 + total_lanes, 8), jnp.uint32)
        self.last_h2d_bytes = (flat_words.nbytes + child_lane.nbytes
                               + dst_word.nbytes + shift.nbytes + meta.nbytes)
        self.last_transfers = 6
        self.last_dispatches = n_seg

        with phase_timer("planned/phase/patch"):
            for i, s in enumerate(specs):
                fw, dig = self._step(
                    fw, dig, dw, ch, sh, mt, seg_ids[i],
                    lanes=s.lanes, blocks=s.blocks, npatch=s.n_patches,
                )
        with phase_timer("planned/phase/store"):
            if want_digests:
                host = np.asarray(dig)
                return host[root_pos + 1].astype("<u4").tobytes(), host[1:]
            root = np.asarray(dig[root_pos + 1])
            return root.astype("<u4").tobytes(), None


_default_commit: Optional[PlannedCommit] = None


def _tpu_backend() -> bool:
    try:
        import jax

        if jax.default_backend() in ("tpu", "axon"):
            return True
        d = jax.devices()[0]
        return "tpu" in getattr(d, "device_kind", "").lower()
    except Exception:
        return False


def default_planned_commit() -> PlannedCommit:
    """Process-wide PlannedCommit singleton (jit caches live on the
    instance's step; sharing it keeps one compiled program per shape).

    Kernel selection (VERDICT r2 #4 — the Pallas kernel is the default
    where it can run): on a real TPU backend, segments whose lane count
    tiles the Pallas grid (%1024) hash through the VMEM-resident kernel
    (ops/keccak_pallas.staged_seg_impl) with the XLA scan below the grid
    minimum; on CPU backends everything stays XLA (Pallas needs interpret
    mode there — minutes per call). CORETH_TPU_SEG_KERNEL=xla|pallas
    overrides."""
    global _default_commit
    if _default_commit is None:
        import os

        mode = os.environ.get("CORETH_TPU_SEG_KERNEL", "auto")
        seg_impl = None
        if mode == "pallas" or (mode == "auto" and _tpu_backend()):
            from .keccak_pallas import staged_seg_impl

            seg_impl = staged_seg_impl()
        _default_commit = PlannedCommit(seg_impl=seg_impl)
    return _default_commit
