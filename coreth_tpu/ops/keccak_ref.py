"""Pure-Python Keccak-256 reference implementation.

This is the golden model for every other keccak backend in the framework
(XLA, Pallas, C++). It implements the original Keccak padding (0x01), i.e.
the variant Ethereum uses (``sha3.NewLegacyKeccak256`` in the reference:
/root/reference/trie/hasher.go:34,51), NOT NIST SHA3 (0x06 padding).

Intentionally simple and slow — it exists for correctness testing only.
Production host-side hashing uses the C++ backend (coreth_tpu/native) and
device hashing uses the Pallas/XLA kernels (coreth_tpu/ops/keccak_jax.py).
"""

from __future__ import annotations

RATE = 136  # bytes: 1088-bit rate for Keccak-256
DIGEST_SIZE = 32

_ROUND_CONSTANTS = (
    0x0000000000000001, 0x0000000000008082, 0x800000000000808A,
    0x8000000080008000, 0x000000000000808B, 0x0000000080000001,
    0x8000000080008081, 0x8000000000008009, 0x000000000000008A,
    0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
    0x000000008000808B, 0x800000000000008B, 0x8000000000008089,
    0x8000000000008003, 0x8000000000008002, 0x8000000000000080,
    0x000000000000800A, 0x800000008000000A, 0x8000000080008081,
    0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
)

# Rotation offsets r[x][y] laid out by lane index (x + 5*y).
_ROTC = (
    0, 1, 62, 28, 27,
    36, 44, 6, 55, 20,
    3, 10, 43, 25, 39,
    41, 45, 15, 21, 8,
    18, 2, 61, 56, 14,
)

_MASK = (1 << 64) - 1


def _rotl(value: int, shift: int) -> int:
    if shift == 0:
        return value
    return ((value << shift) | (value >> (64 - shift))) & _MASK


def keccak_f1600(state: list) -> list:
    """One Keccak-f[1600] permutation over 25 64-bit lanes (x + 5*y order)."""
    a = state
    for rc in _ROUND_CONSTANTS:
        # theta
        c = [a[x] ^ a[x + 5] ^ a[x + 10] ^ a[x + 15] ^ a[x + 20] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        a = [a[i] ^ d[i % 5] for i in range(25)]
        # rho + pi
        b = [0] * 25
        for x in range(5):
            for y in range(5):
                # B[y, 2x+3y] = rot(A[x, y], r[x, y])
                b[y + 5 * ((2 * x + 3 * y) % 5)] = _rotl(a[x + 5 * y], _ROTC[x + 5 * y])
        # chi
        a = [
            b[i] ^ ((~b[(i % 5 + 1) % 5 + 5 * (i // 5)]) & b[(i % 5 + 2) % 5 + 5 * (i // 5)])
            for i in range(25)
        ]
        a = [v & _MASK for v in a]
        # iota
        a[0] ^= rc
    return a


def keccak_pad(data: bytes, rate: int = RATE) -> bytes:
    """Multi-rate padding with Keccak domain bit 0x01 (legacy, as Ethereum)."""
    pad_len = rate - (len(data) % rate)
    if pad_len == 1:
        return data + b"\x81"
    return data + b"\x01" + b"\x00" * (pad_len - 2) + b"\x80"


def keccak256(data: bytes) -> bytes:
    """Keccak-256 digest (Ethereum flavor) of ``data``."""
    padded = keccak_pad(data)
    state = [0] * 25
    for off in range(0, len(padded), RATE):
        block = padded[off:off + RATE]
        for i in range(RATE // 8):
            state[i] ^= int.from_bytes(block[8 * i:8 * i + 8], "little")
        state = keccak_f1600(state)
    out = b"".join(state[i].to_bytes(8, "little") for i in range(4))
    return out[:DIGEST_SIZE]
