"""Batched Keccak-256 for TPU via JAX/XLA.

The reference hashes trie nodes one at a time on CPU with 16-way goroutine
fan-out (/root/reference/trie/hasher.go:124-139). The TPU-native design is
data-parallel instead: thousands of independent messages are hashed as one
batched tensor program. 64-bit lanes are modeled as (lo, hi) uint32 pairs
because TPUs natively operate on 32-bit integers.

Layout
------
Host packs messages (already keccak-padded) into

    words:   uint32[B, L, 34]   -- L rate-blocks of 136 bytes = 34 LE words
    nblocks: int32[B]           -- valid blocks per lane (>= 1)

Lanes with fewer than L blocks are masked: their absorb XOR is zeroed for
j >= nblocks and their digest is snapshotted at j == nblocks - 1, so mixed
lengths share one kernel launch. Digest = first 8 words of the state after
the final permutation (little-endian).

`keccak256_batch` is the convenience host API: it packs, buckets by block
count (to avoid one huge message padding out a million small ones), runs the
jitted core per bucket, and returns 32-byte digests in input order.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .keccak_ref import _ROUND_CONSTANTS, _ROTC

RATE = 136
WORDS_PER_BLOCK = RATE // 4  # 34 uint32 words

_RC_LO = tuple(rc & 0xFFFFFFFF for rc in _ROUND_CONSTANTS)
_RC_HI = tuple(rc >> 32 for rc in _ROUND_CONSTANTS)


def _rotl_pair(lo, hi, n: int):
    """Rotate a 64-bit lane expressed as (lo, hi) uint32 left by static n."""
    n %= 64
    if n == 0:
        return lo, hi
    if n == 32:
        return hi, lo
    if n > 32:
        lo, hi = hi, lo
        n -= 32
    m = 32 - n
    new_lo = (lo << n) | (hi >> m)
    new_hi = (hi << n) | (lo >> m)
    return new_lo, new_hi


def _round(lo, hi, rc_lo: int, rc_hi: int):
    """One Keccak round over 25 (lo, hi) batch vectors (x + 5*y order)."""
    # theta
    c_lo = [lo[x] ^ lo[x + 5] ^ lo[x + 10] ^ lo[x + 15] ^ lo[x + 20] for x in range(5)]
    c_hi = [hi[x] ^ hi[x + 5] ^ hi[x + 10] ^ hi[x + 15] ^ hi[x + 20] for x in range(5)]
    d_lo, d_hi = [], []
    for x in range(5):
        rl, rh = _rotl_pair(c_lo[(x + 1) % 5], c_hi[(x + 1) % 5], 1)
        d_lo.append(c_lo[(x - 1) % 5] ^ rl)
        d_hi.append(c_hi[(x - 1) % 5] ^ rh)
    lo = [lo[i] ^ d_lo[i % 5] for i in range(25)]
    hi = [hi[i] ^ d_hi[i % 5] for i in range(25)]
    # rho + pi
    b_lo = [None] * 25
    b_hi = [None] * 25
    for x in range(5):
        for y in range(5):
            src = x + 5 * y
            dst = y + 5 * ((2 * x + 3 * y) % 5)
            b_lo[dst], b_hi[dst] = _rotl_pair(lo[src], hi[src], _ROTC[src])
    # chi
    lo = [
        b_lo[i] ^ (~b_lo[(i % 5 + 1) % 5 + 5 * (i // 5)] & b_lo[(i % 5 + 2) % 5 + 5 * (i // 5)])
        for i in range(25)
    ]
    hi = [
        b_hi[i] ^ (~b_hi[(i % 5 + 1) % 5 + 5 * (i // 5)] & b_hi[(i % 5 + 2) % 5 + 5 * (i // 5)])
        for i in range(25)
    ]
    # iota
    lo[0] = lo[0] ^ jnp.uint32(rc_lo)
    hi[0] = hi[0] ^ jnp.uint32(rc_hi)
    return lo, hi


def keccak_f1600(lo, hi):
    """Full 24-round permutation; lo/hi are length-25 lists of uint32[B].

    Fully unrolled — largest trace, kept for parity tests. The production
    paths use the scanned variant below (one round body traced once), which
    compiles orders of magnitude faster on the CPU backend and identically
    fast on TPU."""
    for r in range(24):
        lo, hi = _round(lo, hi, _RC_LO[r], _RC_HI[r])
    return lo, hi


_RC_LO_ARR = np.array(_RC_LO, dtype=np.uint32)
_RC_HI_ARR = np.array(_RC_HI, dtype=np.uint32)


def keccak_f1600_scanned_stacked(lo_s, hi_s):
    """Scanned 24-round permutation over stacked state uint32[25, ...].

    The round body is traced ONCE (lax.scan over round constants), keeping
    the XLA graph ~24x smaller than the unrolled form — this is what makes
    multi-chip sharded compiles finish in seconds instead of minutes. This
    is the single shared implementation; keccak_fused/keccak_staged import
    it rather than keeping their own copies."""

    def body(state, rc):
        l, h = state
        l2, h2 = _round(list(l), list(h), rc[0], rc[1])
        return (jnp.stack(l2), jnp.stack(h2)), None

    rcs = jnp.stack([jnp.asarray(_RC_LO_ARR), jnp.asarray(_RC_HI_ARR)], axis=1)
    (lo_s, hi_s), _ = jax.lax.scan(body, (lo_s, hi_s), rcs)
    return lo_s, hi_s


def keccak_f1600_scanned(lo, hi):
    """List-of-25-vectors wrapper over the stacked scanned permutation."""
    lo_s, hi_s = keccak_f1600_scanned_stacked(jnp.stack(lo), jnp.stack(hi))
    return list(lo_s), list(hi_s)


@functools.partial(jax.jit, static_argnames=("unroll",))
def keccak256_blocks(words: jax.Array, nblocks: jax.Array, unroll: int = 1):
    """Digest a packed batch.

    words:   uint32[B, L, 34] padded rate blocks, little-endian words
    nblocks: int32[B] valid block count per lane
    returns: uint32[B, 8] digest words (little-endian)
    """
    b = words.shape[0]
    zeros = jnp.zeros((b,), jnp.uint32)
    lo = [zeros] * 25
    hi = [zeros] * 25
    out = jnp.zeros((b, 8), jnp.uint32)
    # (L, B, 34) so scan walks rate blocks.
    words_t = jnp.transpose(words, (1, 0, 2))
    idx = jnp.arange(words.shape[1], dtype=jnp.int32)

    def step(carry, xs):
        lo, hi, out = carry
        block, j = xs
        live = (j < nblocks).astype(jnp.uint32)  # [B]
        lo = list(lo)
        hi = list(hi)
        for i in range(17):
            lo[i] = lo[i] ^ (block[:, 2 * i] * live)
            hi[i] = hi[i] ^ (block[:, 2 * i + 1] * live)
        lo, hi = keccak_f1600_scanned(lo, hi)
        digest = jnp.stack(
            [lo[0], hi[0], lo[1], hi[1], lo[2], hi[2], lo[3], hi[3]], axis=1
        )
        is_last = (j == nblocks - 1)[:, None]
        out = jnp.where(is_last, digest, out)
        return (tuple(lo), tuple(hi), out), None

    (lo, hi, out), _ = jax.lax.scan(
        step, (tuple(lo), tuple(hi), out), (words_t, idx), unroll=unroll
    )
    return out


# ---------------------------------------------------------------------------
# Host-side packing (vectorized numpy; no per-byte Python loops)
# ---------------------------------------------------------------------------

def pack_messages(msgs: Sequence[bytes], lengths: np.ndarray | None = None):
    """Pack messages into (words uint32[B, L, 34], nblocks int32[B]).

    Fully vectorized: messages are concatenated once (C speed) and scattered
    into the padded layout with one fancy-indexed assignment, so packing a
    million trie nodes costs O(total_bytes) numpy work, not a Python loop
    per byte.
    """
    n = len(msgs)
    if lengths is None:
        lengths = np.fromiter((len(m) for m in msgs), dtype=np.int64, count=n)
    nblocks = (lengths // RATE + 1).astype(np.int32)  # keccak pad always adds >=1 byte
    max_blocks = int(nblocks.max()) if n else 1
    row = max_blocks * RATE

    buf = np.zeros((n, row), dtype=np.uint8)
    total = int(lengths.sum())
    if total:
        src = np.frombuffer(b"".join(msgs), dtype=np.uint8)
        starts = np.zeros(n, dtype=np.int64)
        np.cumsum(lengths[:-1], out=starts[1:])
        within = np.arange(total, dtype=np.int64) - np.repeat(starts, lengths)
        dest = np.repeat(np.arange(n, dtype=np.int64) * row, lengths) + within
        buf.reshape(-1)[dest] = src
    flat = buf.reshape(-1)
    rows = np.arange(n, dtype=np.int64) * row
    # 0x01 at first pad byte, 0x80 at last byte of final block (|= handles the
    # single-byte-pad case where both land on the same byte -> 0x81).
    flat[rows + lengths] = 0x01
    last = rows + nblocks.astype(np.int64) * RATE - 1
    flat[last] |= 0x80
    words = buf.view("<u4").reshape(n, max_blocks, WORDS_PER_BLOCK)
    return words, nblocks


def digest_words_to_bytes(out: np.ndarray) -> list:
    """uint32[B, 8] -> list of 32-byte digests."""
    raw = np.ascontiguousarray(out).astype("<u4", copy=False).tobytes()
    return [raw[i * 32:(i + 1) * 32] for i in range(out.shape[0])]


def _pad_batch(words: np.ndarray, nblocks: np.ndarray, multiple: int = 128):
    """Pad the batch dim to a power-of-two bucket (>= multiple).

    Power-of-two buckets keep the set of compiled shapes logarithmic in the
    batch size — a trie hash drains one differently-sized batch per level,
    and each distinct shape costs a full XLA compile.
    """
    b = words.shape[0]
    target = multiple
    while target < b:
        target *= 2
    pad = target - b
    if pad:
        words = np.concatenate(
            [words, np.zeros((pad,) + words.shape[1:], dtype=words.dtype)]
        )
        # padded lanes get nblocks=1: they absorb one all-zero block and
        # snapshot a garbage digest at j==0, which callers drop via [:real].
        nblocks = np.concatenate([nblocks, np.ones(pad, dtype=nblocks.dtype)])
    return words, nblocks, b


class BatchedKeccak:
    """Host dispatcher: bucket messages by block count, run jitted batches.

    Bucketing avoids one large message (e.g. contract code) forcing the padded
    block dimension up for an entire trie-node batch. Buckets are power-of-two
    block counts so the jit cache stays small.
    """

    def __init__(self, impl=None, batch_multiple: int = 128):
        self._impl = impl if impl is not None else keccak256_blocks
        self._multiple = batch_multiple

    def digests(self, msgs: Sequence[bytes]) -> list:
        n = len(msgs)
        if n == 0:
            return []
        lengths = np.fromiter((len(m) for m in msgs), dtype=np.int64, count=n)
        blocks_needed = lengths // RATE + 1
        out = [None] * n
        # bucket boundary = next power of two of block count
        keys = np.maximum(
            1, 1 << np.ceil(np.log2(np.maximum(blocks_needed, 1))).astype(np.int64)
        )
        for key in np.unique(keys):
            (idx,) = np.nonzero(keys == key)
            sub = [msgs[i] for i in idx]
            words, nblocks = pack_messages(sub, lengths[idx])
            if words.shape[1] < key:  # pad block dim to the bucket size
                extra = np.zeros(
                    (words.shape[0], int(key) - words.shape[1], WORDS_PER_BLOCK),
                    dtype=words.dtype,
                )
                words = np.concatenate([words, extra], axis=1)
            words, nblocks, real = _pad_batch(words, nblocks, self._multiple)
            res = np.asarray(self._impl(jnp.asarray(words), jnp.asarray(nblocks)))
            digs = digest_words_to_bytes(res[:real])
            for i, d in zip(idx, digs):
                out[i] = d
        return out


_default = None


def keccak256_batch(msgs: Sequence[bytes]) -> list:
    """Hash a batch of byte strings on the default JAX backend."""
    global _default
    if _default is None:
        _default = BatchedKeccak()
    return _default.digests(msgs)
