"""On-demand block assembly (role of /root/reference/miner/worker.go).

No PoW and no async mining loops: the VM's buildBlock calls
commit_new_work once per block (worker.go:118-195) — prepare the header,
derive the dynamic base fee, pull pending txs in price-and-nonce order,
apply them, and FinalizeAndAssemble through the engine (which pulls
atomic txs via the VM callback).
"""

from __future__ import annotations

import heapq
import time as _time
from typing import Dict, List, Optional

from .. import params
from ..consensus.dummy import calc_base_fee
from ..core.state_processor import apply_transaction, new_block_context
from ..core.state_transition import GasPool
from ..core.types import Block, Header, Signer, Transaction

BLACKHOLE_ADDR = b"\x01" + b"\x00" * 19


class TxByPriceAndNonce:
    """transactionsByPriceAndNonce: per-account nonce order, price heap
    across accounts (miner/ordering.go)."""

    def __init__(self, pending: Dict[bytes, List[Transaction]], base_fee: Optional[int]):
        self.base_fee = base_fee
        self.heads: list = []
        self.txs = {a: list(txs) for a, txs in pending.items()}
        for i, (addr, txs) in enumerate(sorted(self.txs.items())):
            if txs:
                tx = txs[0]
                heapq.heappush(
                    self.heads, (-tx.effective_gas_tip(base_fee), i, addr)
                )

    def peek(self) -> Optional[Transaction]:
        while self.heads:
            _, _, addr = self.heads[0]
            if self.txs.get(addr):
                return self.txs[addr][0]
            heapq.heappop(self.heads)
        return None

    def shift(self) -> None:
        """Advance to the sender's next tx."""
        if not self.heads:
            return
        neg_tip, i, addr = heapq.heappop(self.heads)
        txs = self.txs.get(addr)
        if txs:
            txs.pop(0)
            if txs:
                heapq.heappush(
                    self.heads,
                    (-txs[0].effective_gas_tip(self.base_fee), i, addr),
                )

    def pop(self) -> None:
        """Drop the sender entirely (tx failed)."""
        if self.heads:
            _, _, addr = heapq.heappop(self.heads)
            self.txs.pop(addr, None)


class Worker:
    def __init__(self, config, engine, chain, tx_pool=None, clock=None):
        self.config = config
        self.engine = engine
        self.chain = chain
        self.tx_pool = tx_pool
        self.clock = clock or (lambda: int(_time.time()))
        self.coinbase = BLACKHOLE_ADDR

    def commit_new_work(self, pending: Optional[Dict[bytes, List[Transaction]]] = None) -> Block:
        """commitNewWork (worker.go:118-195) → assembled block."""
        from ..metrics.spans import span

        with span("miner/build"):
            return self._commit_new_work(pending)

    def _commit_new_work(self, pending: Optional[Dict[bytes, List[Transaction]]] = None) -> Block:
        parent = self.chain.current_block
        timestamp = max(self.clock(), parent.time)

        gas_limit = self._gas_limit(parent.header, timestamp)
        header = Header(
            parent_hash=parent.hash(),
            coinbase=self.coinbase,
            number=parent.number + 1,
            gas_limit=gas_limit,
            time=timestamp,
            difficulty=1,
        )
        if self.config.is_apricot_phase3(timestamp):
            window, base_fee = calc_base_fee(self.config, parent.header, timestamp)
            header.extra = window
            header.base_fee = base_fee

        statedb = self.chain.state_at(parent.root)

        # CheckConfigurePrecompiles (miner/worker.go:170): the block being
        # built must see precompiles activated by its own timestamp
        self.config.check_configure_precompiles(parent.header.time, header, statedb)

        if pending is None:
            pending = self.tx_pool.pending_txs() if self.tx_pool is not None else {}

        txs: List[Transaction] = []
        receipts: list = []
        used_gas = [0]
        gp = GasPool(header.gas_limit)

        from ..evm.evm import EVM, Config, TxContext

        block_ctx = new_block_context(header, self.chain, self.coinbase)
        evm = EVM(block_ctx, TxContext(), statedb, self.config, Config())

        ordered = TxByPriceAndNonce(pending, header.base_fee)
        while True:
            tx = ordered.peek()
            if tx is None:
                break
            if gp.gas < params.TX_GAS:
                break
            statedb.set_tx_context(tx.hash(), len(txs))
            snap = statedb.snapshot()
            try:
                receipt = apply_transaction(
                    self.config, self.chain, evm, gp, statedb, header, tx, used_gas
                )
            except Exception:
                # unminable tx: reverted and skipped — the reference logs
                # every commitTransaction failure; we count them
                from ..metrics import count_drop

                count_drop("miner/tx_apply_error")
                statedb.revert_to_snapshot(snap)
                ordered.pop()
                continue
            txs.append(tx)
            receipts.append(receipt)
            ordered.shift()

        header.gas_used = used_gas[0]
        block = self.engine.finalize_and_assemble(
            self.config, header, parent.header, statedb, txs, receipts
        )
        # persist the assembled block's state so verify can run against it
        root = statedb.commit(self.config.is_eip158(block.number))
        assert root == block.header.root
        return block

    def _gas_limit(self, parent: Header, timestamp: int) -> int:
        if self.config.is_cortina(timestamp):
            return params.CORTINA_GAS_LIMIT
        if self.config.is_apricot_phase1(timestamp):
            return params.APRICOT_PHASE1_GAS_LIMIT
        return parent.gas_limit
