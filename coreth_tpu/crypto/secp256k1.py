"""secp256k1 sign/recover (role of the reference's cgo libsecp256k1 +
decred pure-Go fallback — SURVEY.md §2.6 item 2).

Pure-Python Jacobian-coordinate implementation. Correctness-critical path;
the batched sender-recovery seam (core/sender_cacher.go:88) dispatches here
and can later swap in a native backend without API change.
"""

from __future__ import annotations

from typing import Optional, Tuple

from ..native import keccak256

P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8
A = 0
B = 7


def _inv(a: int, m: int) -> int:
    return pow(a, m - 2, m)


# Jacobian point ops: (X, Y, Z) with x = X/Z^2, y = Y/Z^3; None = infinity.

def _jdouble(p):
    if p is None:
        return None
    X, Y, Z = p
    if Y == 0:
        return None
    S = (4 * X * Y * Y) % P
    M = (3 * X * X) % P  # a == 0
    X2 = (M * M - 2 * S) % P
    Y2 = (M * (S - X2) - 8 * Y * Y * Y * Y) % P
    Z2 = (2 * Y * Z) % P
    return (X2, Y2, Z2)


def _jadd(p, q):
    if p is None:
        return q
    if q is None:
        return p
    X1, Y1, Z1 = p
    X2, Y2, Z2 = q
    Z1Z1 = (Z1 * Z1) % P
    Z2Z2 = (Z2 * Z2) % P
    U1 = (X1 * Z2Z2) % P
    U2 = (X2 * Z1Z1) % P
    S1 = (Y1 * Z2 * Z2Z2) % P
    S2 = (Y2 * Z1 * Z1Z1) % P
    if U1 == U2:
        if S1 != S2:
            return None
        return _jdouble(p)
    H = (U2 - U1) % P
    R = (S2 - S1) % P
    HH = (H * H) % P
    HHH = (H * HH) % P
    V = (U1 * HH) % P
    X3 = (R * R - HHH - 2 * V) % P
    Y3 = (R * (V - X3) - S1 * HHH) % P
    Z3 = (H * Z1 * Z2) % P
    return (X3, Y3, Z3)


def _jmul(p, k: int):
    if k % N == 0 or p is None:
        return None
    result = None
    addend = p
    while k:
        if k & 1:
            result = _jadd(result, addend)
        addend = _jdouble(addend)
        k >>= 1
    return result


def _to_affine(p) -> Optional[Tuple[int, int]]:
    if p is None:
        return None
    X, Y, Z = p
    zi = _inv(Z, P)
    zi2 = (zi * zi) % P
    return (X * zi2) % P, (Y * zi2 * zi) % P


_G = (GX, GY, 1)


def _lift_x(x: int, odd: bool) -> Optional[Tuple[int, int]]:
    y2 = (pow(x, 3, P) + B) % P
    y = pow(y2, (P + 1) // 4, P)
    if (y * y) % P != y2:
        return None
    if (y & 1) != odd:
        y = P - y
    return (x, y)


def ecrecover(msg_hash: bytes, v: int, r: int, s: int) -> Optional[bytes]:
    """Recover the 64-byte uncompressed pubkey (no 0x04 prefix).

    v is the recovery id (0..3). Returns None on invalid signature.
    """
    if not (1 <= r < N and 1 <= s < N and 0 <= v <= 3):
        return None
    x = r + (v >> 1) * N
    if x >= P:
        return None
    Rp = _lift_x(x, bool(v & 1))
    if Rp is None:
        return None
    e = int.from_bytes(msg_hash, "big") % N
    r_inv = _inv(r, N)
    # Q = r^-1 (s*R - e*G)
    pt = _jadd(
        _jmul((Rp[0], Rp[1], 1), s),
        _jmul(_G, (N - e) % N),
    )
    Q = _to_affine(_jmul(pt, r_inv))
    if Q is None:
        return None
    return Q[0].to_bytes(32, "big") + Q[1].to_bytes(32, "big")


def sign(msg_hash: bytes, priv: bytes) -> Tuple[int, int, int]:
    """Deterministic-ish sign: returns (v, r, s) with low-s normalization.

    Nonce is derived RFC-6979-style from keccak (not the HMAC-SHA256 of the
    RFC — this signer exists for tests and local tooling, not consensus).
    """
    d = int.from_bytes(priv, "big")
    if not (1 <= d < N):
        raise ValueError("invalid private key")
    e = int.from_bytes(msg_hash, "big") % N
    k = 0
    counter = 0
    while True:
        k = int.from_bytes(
            keccak256(priv + msg_hash + counter.to_bytes(4, "big")), "big"
        ) % N
        if k == 0:
            counter += 1
            continue
        R = _to_affine(_jmul(_G, k))
        r = R[0] % N
        if r == 0:
            counter += 1
            continue
        s = (_inv(k, N) * (e + r * d)) % N
        if s == 0:
            counter += 1
            continue
        v = (R[1] & 1) | (2 if R[0] >= N else 0)
        if s > N // 2:
            s = N - s
            v ^= 1
        return v, r, s


def pubkey(priv: bytes) -> bytes:
    d = int.from_bytes(priv, "big")
    Q = _to_affine(_jmul(_G, d))
    return Q[0].to_bytes(32, "big") + Q[1].to_bytes(32, "big")


def pubkey_to_address(pub64: bytes) -> bytes:
    return keccak256(pub64)[12:]


def priv_to_address(priv: bytes) -> bytes:
    return pubkey_to_address(pubkey(priv))


def recover_address(msg_hash: bytes, v: int, r: int, s: int) -> Optional[bytes]:
    pub = ecrecover(msg_hash, v, r, s)
    return pubkey_to_address(pub) if pub is not None else None
