"""Cryptography: keccak256 (native C++ / TPU-batched) and secp256k1."""

from ..native import keccak256, keccak256_batch
from .secp256k1 import (
    ecrecover,
    priv_to_address,
    pubkey,
    pubkey_to_address,
    recover_address,
    sign,
)

__all__ = [
    "ecrecover", "keccak256", "keccak256_batch", "priv_to_address",
    "pubkey", "pubkey_to_address", "recover_address", "sign",
]
