"""The `tpu_keccak` stateful precompile — BASELINE config #5.

Contracts submit a batch of byte strings and get their Keccak-256
digests back in one call, priced per message at the EVM's own SHA3
schedule (gas.py KECCAK256_GAS/WORD_GAS) plus a flat batch base.

Backend choice is NOT consensus-relevant (digests are bit-identical on
every backend), so it never appears in chain config: the contract
resolves the node's device keccak lazily ("auto" — the same handle the
trie commit path uses) and falls back to the threaded C++ host keccak
on any device-side failure. Gas is charged from the ABI lengths BEFORE
any message bytes are materialized, so a caller cannot buy cheap memory
amplification with overlapping offsets.

No analog exists in the reference (its precompile/ framework ships no
keccak precompile); the surface is new, registered through the same
config/activation machinery as reference stateful precompiles
(stateful_precompile_config.go:13-56).

ABI (solidity):
    function keccak256Batch(bytes[] calldata msgs)
        external view returns (bytes32[] memory digests);
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property
from typing import List

from .. import vmerrs
from ..crypto import keccak256_batch
from ..evm.gas import KECCAK256_GAS, KECCAK256_WORD_GAS

TPU_KECCAK_ADDR = bytes.fromhex("0100000000000000000000000000000000000010")

# flat cost of entering the precompile (dispatch + ABI walk); per-message
# costs then follow the SHA3 opcode schedule so on-chain pricing is
# familiar: 30 + 6*ceil(len/32) per message (evm/gas.py:19-20)
BATCH_BASE_GAS = 2000

# messages per call cap: bounds ABI-decode work and device batch size
MAX_BATCH_MESSAGES = 65536

# device path engages above this many messages; below it the threaded
# C++ keccak wins (same threshold spirit as trie/hasher.BATCH_THRESHOLD)
DEVICE_THRESHOLD = 64

_WORD = 32


def _u256(data: bytes, off: int) -> int:
    if off + _WORD > len(data):
        raise vmerrs.ErrPrecompileFailure
    return int.from_bytes(data[off:off + _WORD], "big")


def scan_bytes_array(args: bytes) -> List[int]:
    """Walk the ABI `bytes[]` layout returning (start, len) anchors WITHOUT
    copying message bytes — the gas base for charge-before-materialize."""
    head = _u256(args, 0)
    count = _u256(args, head)
    if count > MAX_BATCH_MESSAGES:
        raise vmerrs.ErrPrecompileFailure
    base = head + _WORD
    anchors = []
    for i in range(count):
        rel = _u256(args, base + i * _WORD)
        mlen = _u256(args, base + rel)
        start = base + rel + _WORD
        if start + mlen > len(args):
            raise vmerrs.ErrPrecompileFailure
        anchors.append((start, mlen))
    return anchors


def decode_bytes_array(args: bytes) -> List[bytes]:
    """ABI-decode `bytes[]` (selector already stripped)."""
    return [args[s:s + n] for s, n in scan_bytes_array(args)]


def encode_bytes32_array(vals: List[bytes]) -> bytes:
    """ABI-encode `bytes32[]` return data."""
    out = bytearray()
    out += (_WORD).to_bytes(_WORD, "big")        # offset to array
    out += len(vals).to_bytes(_WORD, "big")      # length
    for v in vals:
        out += v
    return bytes(out)


def _per_msg_gas(length: int) -> int:
    return KECCAK256_GAS + KECCAK256_WORD_GAS * ((length + 31) // 32)


def batch_gas(msgs: List[bytes]) -> int:
    return BATCH_BASE_GAS + sum(_per_msg_gas(len(m)) for m in msgs)


class _Hasher:
    """Lazy device-resolving batch hasher; ALWAYS returns digests.

    Any device-side failure (backend missing, XLA error, OOM) falls back
    to the C++ host keccak — identical digests, so a node-local hardware
    problem can never turn into a consensus split mid-transaction."""

    def __init__(self):
        self._device = None
        self._resolved = False

    def __call__(self, msgs: List[bytes]) -> List[bytes]:
        if len(msgs) >= DEVICE_THRESHOLD:
            if not self._resolved:
                try:
                    from ..ops.device import get_batch_keccak

                    self._device = get_batch_keccak("auto")
                except Exception:
                    # no device hasher: permanent host fallback — one
                    # countable event, not a silent capability loss
                    from ..metrics import count_drop

                    count_drop("precompile/keccak/device_resolve_error")
                    self._device = None
                self._resolved = True
            if self._device is not None:
                try:
                    return self._device(msgs)
                except Exception:
                    # fall through to the host path; a wedged device
                    # would otherwise look like a mere perf regression
                    from ..metrics import count_drop

                    count_drop("precompile/keccak/device_exec_fallback")
        return keccak256_batch(msgs, threads=0 if len(msgs) < 256 else 8)


from . import PrecompileConfig  # noqa: E402  (no cycle: package defines it first)


@dataclass(frozen=True)
class TpuKeccakConfig(PrecompileConfig):
    """Activation config: framework semantics inherited from
    PrecompileConfig; this class only picks the address default and
    builds the contract."""

    address: bytes = TPU_KECCAK_ADDR

    @cached_property
    def _contract(self):
        from . import (PrecompileFunction, SelectorDispatchContract,
                       charge_gas, function_selector)

        hasher = _Hasher()

        def run_batch(evm, caller, addr, args, gas, read_only):
            try:
                anchors = scan_bytes_array(args)
            except vmerrs.VMError:
                raise
            except Exception:
                raise vmerrs.ErrPrecompileFailure
            cost = BATCH_BASE_GAS + sum(_per_msg_gas(n) for _, n in anchors)
            gas = charge_gas(gas, cost)
            msgs = [args[s:s + n] for s, n in anchors]
            digests = hasher(msgs)
            return encode_bytes32_array(list(digests)), gas

        return SelectorDispatchContract([
            PrecompileFunction(
                function_selector("keccak256Batch(bytes[])"), run_batch
            ),
        ])

    def contract(self):
        return self._contract
