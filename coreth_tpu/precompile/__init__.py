"""Pluggable stateful-precompile framework.

Capability parity with /root/reference/precompile/:
  - a config carries WHERE the precompile lives (address) and WHEN it
    activates (timestamp; None = never, 0 = genesis) —
    stateful_precompile_config.go:13-34
  - `check_configure` runs exactly once, on the first block whose
    timestamp crosses the activation boundary: it marks the address
    non-empty (nonce=1, code=0x01 so Solidity extcodesize checks pass)
    and lets the config seed its own state —
    stateful_precompile_config.go:44-56
  - contracts dispatch on 4-byte function selectors with an optional
    fallback — contract.go:71-120

The flagship registration is the TPU keccak batch precompile
(precompile/tpu_keccak.py): contracts hash large byte batches through
the same device keccak that commits the state trie (BASELINE config #5).

Contracts here implement the host EVM's precompile calling convention
(evm/precompiles.py Precompile: run(evm, caller, addr, input, gas,
read_only) -> (ret, remaining_gas), raising vmerrs on failure), so a
registered stateful precompile is indistinguishable from a built-in at
dispatch time (evm/evm.py active_precompiles merge).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, Optional, Sequence, Tuple

from .. import vmerrs
from ..crypto import keccak256
from ..evm.precompiles import Precompile

SELECTOR_LEN = 4


def function_selector(signature: str) -> bytes:
    """keccak256(signature)[:4] — contract.go CalculateFunctionSelector."""
    if "(" not in signature or not signature.endswith(")"):
        raise ValueError(f"invalid function signature {signature!r}")
    return keccak256(signature.encode())[:SELECTOR_LEN]


def is_fork_transition(fork: Optional[int], parent_ts: Optional[int],
                       current_ts: int) -> bool:
    """utils.IsForkTransition: the fork activates within (parent, current].

    parent_ts None means genesis (nothing was active before), so any
    fork <= current activates now.
    """
    if fork is None:
        return False
    parent_active = parent_ts is not None and fork <= parent_ts
    current_active = fork <= current_ts
    return current_active and not parent_active


@dataclass(frozen=True)
class PrecompileConfig:
    """WHERE + WHEN for one stateful precompile
    (stateful_precompile_config.go:13-34).

    Subclasses override `contract()` (required) and `configure()`
    (optional state seeding, must be deterministic)."""

    address: bytes = b"\x00" * 20
    timestamp: Optional[int] = None  # None: never; 0: genesis; n: first ts>=n

    def is_activated(self, block_timestamp: int) -> bool:
        return self.timestamp is not None and self.timestamp <= block_timestamp

    def configure(self, chain_config, statedb, block_header) -> None:
        """State seeding on activation; default none."""

    def contract(self) -> Precompile:
        raise NotImplementedError


def check_configure(chain_config, parent_ts: Optional[int], block_header,
                    config: PrecompileConfig, statedb) -> None:
    """Activate [config] if the parent->block transition crosses its
    timestamp (stateful_precompile_config.go:44-56): mark the address
    non-empty exactly like contract creation does, then let the config
    seed its state."""
    if is_fork_transition(config.timestamp, parent_ts, block_header.time):
        statedb.set_nonce(config.address, 1)
        statedb.set_code(config.address, b"\x01")
        config.configure(chain_config, statedb, block_header)


@dataclass
class PrecompileFunction:
    """One selector-dispatched entry point (contract.go:71-87).

    execute(evm, caller, addr, packed_args, gas, read_only)
        -> (ret, remaining_gas); raises vmerrs on failure.
    packed_args excludes the 4-byte selector.
    """

    selector: bytes
    execute: Callable


class SelectorDispatchContract(Precompile):
    """StatefulPrecompiledContract via 4-byte selectors
    (contract.go:92-141). No input -> fallback (if registered); short or
    unknown selector -> plain error (the EVM burns remaining gas, same
    as a failed built-in)."""

    def __init__(self, functions: Sequence[PrecompileFunction],
                 fallback: Optional[Callable] = None):
        self._functions: Dict[bytes, PrecompileFunction] = {}
        for fn in functions:
            if len(fn.selector) != SELECTOR_LEN:
                raise ValueError(f"selector must be 4 bytes, got {fn.selector!r}")
            if fn.selector in self._functions:
                raise ValueError(f"duplicate selector {fn.selector.hex()}")
            self._functions[fn.selector] = fn
        self._fallback = fallback

    def run(self, evm, caller, addr, input_: bytes, gas: int,
            read_only: bool) -> Tuple[bytes, int]:
        if len(input_) == 0 and self._fallback is not None:
            return self._fallback(evm, caller, addr, b"", gas, read_only)
        if len(input_) < SELECTOR_LEN:
            raise vmerrs.ErrPrecompileFailure
        fn = self._functions.get(input_[:SELECTOR_LEN])
        if fn is None:
            raise vmerrs.ErrPrecompileFailure
        return fn.execute(evm, caller, addr, input_[SELECTOR_LEN:], gas, read_only)


def charge_gas(gas: int, cost: int) -> int:
    """Deduct or raise ErrOutOfGas (contract.go deductGas)."""
    if gas < cost:
        raise vmerrs.ErrOutOfGas
    return gas - cost


from .tpu_keccak import TPU_KECCAK_ADDR, TpuKeccakConfig  # noqa: E402

__all__ = [
    "PrecompileConfig", "PrecompileFunction", "SelectorDispatchContract",
    "check_configure", "is_fork_transition", "function_selector",
    "charge_gas", "TpuKeccakConfig", "TPU_KECCAK_ADDR", "SELECTOR_LEN",
]
