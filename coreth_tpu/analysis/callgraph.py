"""Whole-repo call graph + lock-flow linker (the interprocedural layer).

Single-file AST rules (SA001–SA012) cannot see a lock-order inversion
between `core/blockchain.py` and `core/insert_pipeline.py`, or a
chainmu-taking method reached *transitively* from the read tier.  This
module makes the analyzer see the whole program in two phases:

1. **Extraction** (`extract_file`) — one pass per file producing a
   `FileGraph`: plain-data records (no AST references) of every
   function's call sites, lock acquisitions with the raw held-set at
   each site, lazy imports, and hard-impurity sites.  FileGraphs are
   picklable on purpose: the engine caches them per file keyed by
   (mtime, size), so warm lint runs never re-parse.

2. **Linking** (`build_program`) — resolves raw references across files
   into a `Program`: call edges (self-dispatch through the class/base
   chain, constructor-typed attributes, module aliases, unique-method
   fallback — the same name-based conventions SA010 half-implemented),
   canonical lock identities, per-function may-acquire summaries
   (fixed point over the call graph, with provenance so every derived
   fact can print a witness chain), the global lock-order edge set, and
   cycle detection over it.

Canonical lock identity: a raw expression like `chain.chainmu` or
`self._mu` resolves to `OwnerClass.attr` (`BlockChain.chainmu`,
`InsertPipeline._mu`) via the lock registry — every `self.<attr> =
threading.Lock()/RLock()/Condition()` assignment in the repo.  A lock
attr defined by exactly one class resolves from any receiver; generic
names (`lock`, `_mu`, `_lock`) defined by many classes resolve only
when the receiver's class is known (enclosing class for `self.`,
constructor/annotation-typed attributes, curated receiver-name hints),
otherwise the site is dropped from the order graph rather than risk a
bogus unification cycle.  Module-level locks canonicalize to
`module:NAME`.

Known blind spots (documented in ANALYSIS.md): calls through locals or
containers, `getattr` dispatch, `.acquire()` without a `with` does not
extend the held scope (it still records the acquisition edge), and
decorator-synthesized methods.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, Iterable, List, Optional, Sequence, Set, Tuple

# ------------------------------------------------------------ shared tables

# lock-like attribute names (same heuristic as SA002's `_is_lock_name`)
LOCK_ATTR_HINTS = ("lock", "mu", "cond", "_cv")

LOCK_CTORS = {
    "threading.Lock", "threading.RLock", "threading.Condition",
    "Lock", "RLock", "Condition",
}

# Hard per-call impurities for the interprocedural SA003 promotion (the
# single-file rule keeps richer observability checks; transitive callees
# are held to the unarguable subset: wall clock, randomness, ctypes
# allocation).  rules.py re-exports these so there is one source table.
WALLCLOCK_CALLS = {
    "time.time", "time.time_ns", "time.monotonic", "time.monotonic_ns",
    "time.perf_counter", "time.perf_counter_ns", "time.process_time",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow",
}
RANDOM_ROOTS = ("random.", "np.random.", "numpy.random.", "secrets.")
CTYPES_ALLOC = {"ctypes.create_string_buffer", "ctypes.create_unicode_buffer",
                "create_string_buffer", "create_unicode_buffer"}

REPO_ROOT_PACKAGE = "coreth_tpu"

# receiver-name → class hints for locks/calls through untyped locals
# (name-based, like SA010's `"chain" in recv` convention); a hint only
# applies when the named class exists in the linked program
RECEIVER_HINTS = {
    "chain": "BlockChain",
    "blockchain": "BlockChain",
    "pipeline": "InsertPipeline",
    "snaps": "Tree",
    "txpool": "TxPool",
}

# method names too generic for the unique-definition fallback — a call
# `obj.run()` through an untyped local must not resolve just because one
# repo class happens to define `run`
GENERIC_METHOD_NAMES = frozenset({
    "run", "close", "get", "put", "set", "add", "pop", "start", "stop",
    "send", "recv", "read", "write", "update", "commit", "reset", "clear",
    "append", "items", "keys", "values", "acquire", "release", "check",
    "flush", "join", "wait", "notify", "notify_all", "submit", "result",
    "done", "cancel", "shutdown", "copy", "encode", "decode", "hash",
    "root", "state", "name", "size", "next", "step", "apply", "load",
    "store", "open", "delete", "remove", "insert", "push", "emit",
})

_MAX_WITNESS_DEPTH = 12


def _dotted(node: ast.AST) -> Optional[str]:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_lock_attr(attr: str) -> bool:
    low = attr.lower()
    return any(h in low for h in LOCK_ATTR_HINTS)


def _impure_kind(name: str) -> Optional[str]:
    if name in WALLCLOCK_CALLS:
        return "wall-clock"
    if any(name.startswith(r) for r in RANDOM_ROOTS):
        return "randomness"
    if name in CTYPES_ALLOC:
        return "ctypes-alloc"
    return None


def module_name(relpath: str) -> str:
    """'coreth_tpu/core/blockchain.py' -> 'coreth_tpu.core.blockchain'."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = [s for s in p.split("/") if s]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) or "<anon>"


# --------------------------------------------------------- per-file records

@dataclass(frozen=True)
class CallRef:
    target: str                # raw dotted call expr ("self.chain.accept")
    line: int
    held: Tuple[str, ...]      # raw lock exprs held at the call site


@dataclass(frozen=True)
class AcquireRef:
    lock: str                  # raw dotted lock expr ("self.chainmu")
    line: int
    held: Tuple[str, ...]      # raw lock exprs already held
    scoped: bool = True        # with-statement (True) vs bare .acquire()


@dataclass(frozen=True)
class LazyImport:
    module: str                # resolved dotted repo module
    line: int


@dataclass(frozen=True)
class ImpureSite:
    kind: str                  # "wall-clock" | "randomness" | "ctypes-alloc"
    name: str                  # the call as written
    line: int


@dataclass
class FuncRec:
    qualname: str              # "Class.method" / "fn" (matches Finding keys)
    name: str
    cls: Optional[str]         # enclosing class name (None for functions)
    line: int
    hot: bool = False
    entry_locks: Tuple[str, ...] = ()       # raw exprs from `# guarded-by:`
    calls: Tuple[CallRef, ...] = ()
    acquires: Tuple[AcquireRef, ...] = ()
    lazy_imports: Tuple[LazyImport, ...] = ()
    impure: Tuple[ImpureSite, ...] = ()
    # function-scope import bindings (lazy imports), same shape as the
    # module-level maps; consulted first during call resolution
    mod_aliases: Dict[str, str] = field(default_factory=dict)
    sym_aliases: Dict[str, Tuple[str, str]] = field(default_factory=dict)


@dataclass
class ClassRec:
    name: str                  # possibly dotted for nested classes
    bases: Tuple[str, ...] = ()             # raw base expressions
    attr_types: Dict[str, str] = field(default_factory=dict)  # attr -> raw cls
    lock_attrs: Tuple[str, ...] = ()        # attrs assigned a Lock/RLock/Cond


@dataclass
class FileGraph:
    relpath: str
    module: str
    is_pkg: bool = False
    mod_aliases: Dict[str, str] = field(default_factory=dict)
    sym_aliases: Dict[str, Tuple[str, str]] = field(default_factory=dict)
    module_imports: Tuple[Tuple[str, int], ...] = ()  # repo-internal, modscope
    classes: Dict[str, ClassRec] = field(default_factory=dict)
    module_locks: Tuple[str, ...] = ()
    funcs: Tuple[FuncRec, ...] = ()


# -------------------------------------------------------------- extraction

class _ImportCollector:
    """Shared import-binding logic for module scope and function scope."""

    def __init__(self, module: str, is_pkg: bool):
        self.module = module
        self.is_pkg = is_pkg
        self.mod_aliases: Dict[str, str] = {}
        self.sym_aliases: Dict[str, Tuple[str, str]] = {}
        self.internal: List[Tuple[str, int]] = []

    def _rel_base(self, level: int) -> str:
        parts = self.module.split(".")
        drop = level - 1 if self.is_pkg else level
        if drop > 0:
            parts = parts[: max(0, len(parts) - drop)]
        return ".".join(parts)

    def add(self, node: ast.AST) -> None:
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    self.mod_aliases[a.asname] = a.name
                else:
                    root = a.name.split(".")[0]
                    self.mod_aliases.setdefault(root, root)
                if a.name.split(".")[0] == REPO_ROOT_PACKAGE:
                    self.internal.append((a.name, node.lineno))
        elif isinstance(node, ast.ImportFrom):
            if node.level > 0:
                base = self._rel_base(node.level)
                target = f"{base}.{node.module}" if node.module else base
            else:
                target = node.module or ""
            if not target:
                return
            internal = target.split(".")[0] == REPO_ROOT_PACKAGE
            if internal:
                self.internal.append((target, node.lineno))
            for a in node.names:
                if a.name == "*":
                    continue
                # `from pkg import sub` may bind a submodule; the linker
                # decides (it knows which dotted names are modules), and
                # the closure pass trims `pkg.symbol` back to the longest
                # real module prefix — so record the full candidate too
                if internal:
                    self.internal.append((f"{target}.{a.name}", node.lineno))
                self.sym_aliases[a.asname or a.name] = (target, a.name)


class _FuncWalker(ast.NodeVisitor):
    """One function body: held-lock scopes, call/acquire/import/impure
    sites.  Nested defs and lambdas fold into the enclosing record with
    the held set reset (a closure runs later, on some other thread)."""

    def __init__(self, src, module: str, is_pkg: bool, cls: Optional[str],
                 held: Sequence[str]):
        self.src = src
        self.module = module
        self.is_pkg = is_pkg
        self.cls = cls
        self.held: List[str] = list(held)
        self.calls: List[CallRef] = []
        self.acquires: List[AcquireRef] = []
        self.lazy: List[LazyImport] = []
        self.impure: List[ImpureSite] = []
        self.imports = _ImportCollector(module, is_pkg)
        self.attr_types: Dict[str, str] = {}
        self.attr_locks: Set[str] = set()

    # -- lock scopes -----------------------------------------------------
    def _visit_with(self, node) -> None:
        got = 0
        for item in node.items:
            d = _dotted(item.context_expr)
            if d is not None and _is_lock_attr(d.rsplit(".", 1)[-1]):
                self.acquires.append(AcquireRef(
                    d, item.context_expr.lineno, tuple(self.held), True))
                self.held.append(d)
                got += 1
            elif isinstance(item.context_expr, ast.Call):
                self.visit(item.context_expr)
            if item.optional_vars is not None:
                self.visit(item.optional_vars)
        for stmt in node.body:
            self.visit(stmt)
        if got:
            del self.held[len(self.held) - got:]

    visit_With = _visit_with
    visit_AsyncWith = _visit_with

    # -- nested defs -----------------------------------------------------
    def _visit_func(self, node) -> None:
        lock, _hot = self.src.def_annotation(node)
        entry = [self._entry_raw(lock)] if lock else []
        inner = _FuncWalker(self.src, self.module, self.is_pkg,
                            self.cls, entry)
        for stmt in node.body:
            inner.visit(stmt)
        self._merge(inner)

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func

    def visit_Lambda(self, node: ast.Lambda) -> None:
        inner = _FuncWalker(self.src, self.module, self.is_pkg, self.cls, ())
        inner.visit(node.body)
        self._merge(inner)

    def _merge(self, inner: "_FuncWalker") -> None:
        self.calls.extend(inner.calls)
        self.acquires.extend(inner.acquires)
        self.lazy.extend(inner.lazy)
        self.impure.extend(inner.impure)
        self.imports.mod_aliases.update(inner.imports.mod_aliases)
        self.imports.sym_aliases.update(inner.imports.sym_aliases)
        self.attr_types.update(inner.attr_types)
        self.attr_locks.update(inner.attr_locks)

    def _entry_raw(self, lock: str) -> str:
        return f"self.{lock}" if self.cls else lock

    # -- sites -----------------------------------------------------------
    def visit_Call(self, node: ast.Call) -> None:
        d = _dotted(node.func)
        if d is not None:
            last = d.rsplit(".", 1)[-1]
            if last == "acquire" and "." in d:
                recv = d[: -len(".acquire")]
                if _is_lock_attr(recv.rsplit(".", 1)[-1]):
                    self.acquires.append(AcquireRef(
                        recv, node.lineno, tuple(self.held), False))
            elif last != "release":
                self.calls.append(CallRef(d, node.lineno, tuple(self.held)))
                kind = _impure_kind(d)
                if kind:
                    self.impure.append(ImpureSite(kind, d, node.lineno))
        self.generic_visit(node)

    def visit_Import(self, node: ast.Import) -> None:
        self.imports.add(node)
        self._note_lazy(node)

    def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
        self.imports.add(node)
        self._note_lazy(node)

    def _note_lazy(self, node: ast.AST) -> None:
        while self.imports.internal:
            mod, line = self.imports.internal.pop()
            self.lazy.append(LazyImport(mod, line))

    # -- attribute typing (constructor / annotation inference) -----------
    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._note_attr(t, node.value)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        d = _dotted(node.target)
        if d is not None and d.startswith("self.") and d.count(".") == 1:
            attr = d.split(".", 1)[1]
            ann = self._ann_class(node.annotation)
            if ann:
                self.attr_types.setdefault(attr, ann)
        if node.value is not None:
            self._note_attr(node.target, node.value)
        self.generic_visit(node)

    def _note_attr(self, target: ast.AST, value: ast.AST) -> None:
        d = _dotted(target)
        if d is None or not d.startswith("self.") or d.count(".") != 1:
            return
        attr = d.split(".", 1)[1]
        if isinstance(value, ast.Call):
            ctor = _dotted(value.func)
            if ctor is None:
                return
            if ctor in LOCK_CTORS:
                self.attr_locks.add(attr)
            elif ctor.rsplit(".", 1)[-1][:1].isupper():
                self.attr_types.setdefault(attr, ctor)

    @staticmethod
    def _ann_class(node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value if node.value[:1].isupper() else None
        if isinstance(node, ast.Subscript):  # Optional[X] / "X | None"
            return _FuncWalker._ann_class(node.slice)
        d = _dotted(node)
        if d and d.rsplit(".", 1)[-1][:1].isupper():
            return d
        return None


def _iter_module_stmts(body) -> Iterable[ast.stmt]:
    """Top-level statements, descending into module-level If/Try blocks
    (optional-dependency gating) but skipping TYPE_CHECKING-only arms."""
    for stmt in body:
        if isinstance(stmt, ast.If):
            test = _dotted(stmt.test) or ""
            if "TYPE_CHECKING" not in test:
                yield from _iter_module_stmts(stmt.body)
            yield from _iter_module_stmts(stmt.orelse)
        elif isinstance(stmt, ast.Try):
            yield from _iter_module_stmts(stmt.body)
            for h in stmt.handlers:
                yield from _iter_module_stmts(h.body)
            yield from _iter_module_stmts(stmt.orelse)
            yield from _iter_module_stmts(stmt.finalbody)
        else:
            yield stmt


def extract_file(src) -> FileGraph:
    """SourceFile -> FileGraph (plain data, picklable, AST-free)."""
    module = module_name(src.relpath)
    is_pkg = src.relpath.endswith("__init__.py")
    imports = _ImportCollector(module, is_pkg)
    classes: Dict[str, ClassRec] = {}
    module_locks: List[str] = []
    funcs: List[FuncRec] = []

    def do_func(node, cls: Optional[str], qualname: str) -> _FuncWalker:
        lock, hot = src.def_annotation(node)
        w = _FuncWalker(src, module, is_pkg, cls,
                        [f"self.{lock}" if cls else lock] if lock else [])
        for stmt in node.body:
            w.visit(stmt)
        funcs.append(FuncRec(
            qualname=qualname, name=node.name, cls=cls, line=node.lineno,
            hot=hot,
            entry_locks=tuple([f"self.{lock}" if cls else lock]
                              if lock else []),
            calls=tuple(w.calls), acquires=tuple(w.acquires),
            lazy_imports=tuple(w.lazy), impure=tuple(w.impure),
            mod_aliases=dict(w.imports.mod_aliases),
            sym_aliases=dict(w.imports.sym_aliases)))
        return w

    def do_class(node: ast.ClassDef, prefix: str) -> None:
        cname = f"{prefix}.{node.name}" if prefix else node.name
        bases = tuple(b for b in (_dotted(x) for x in node.bases) if b)
        attr_types: Dict[str, str] = {}
        lock_attrs: Set[str] = set()
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                w = do_func(item, cname, f"{cname}.{item.name}")
                attr_types.update(w.attr_types)
                lock_attrs.update(w.attr_locks)
            elif isinstance(item, ast.ClassDef):
                do_class(item, cname)
            elif isinstance(item, ast.AnnAssign):
                d = _dotted(item.target)
                ann = _FuncWalker._ann_class(item.annotation)
                if d and "." not in d and ann:
                    attr_types.setdefault(d, ann)
            elif isinstance(item, ast.Assign) and isinstance(
                    item.value, ast.Call):
                ctor = _dotted(item.value.func)
                if ctor in LOCK_CTORS:
                    for t in item.targets:
                        d = _dotted(t)
                        if d and "." not in d:
                            lock_attrs.add(d)
        classes[cname] = ClassRec(cname, bases, attr_types,
                                  tuple(sorted(lock_attrs)))

    for stmt in _iter_module_stmts(src.tree.body):
        if isinstance(stmt, (ast.Import, ast.ImportFrom)):
            imports.add(stmt)
        elif isinstance(stmt, ast.ClassDef):
            do_class(stmt, "")
        elif isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            do_func(stmt, None, stmt.name)
        elif isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            ctor = _dotted(stmt.value.func)
            if ctor in LOCK_CTORS:
                for t in stmt.targets:
                    d = _dotted(t)
                    if d and "." not in d:
                        module_locks.append(d)

    return FileGraph(
        relpath=src.relpath, module=module, is_pkg=is_pkg,
        mod_aliases=dict(imports.mod_aliases),
        sym_aliases=dict(imports.sym_aliases),
        module_imports=tuple(imports.internal),
        classes=classes, module_locks=tuple(module_locks),
        funcs=tuple(funcs))


# ------------------------------------------------------------------ linking

@dataclass
class FuncNode:
    key: str                   # "relpath:qualname" (Finding-key shaped)
    relpath: str
    module: str
    rec: FuncRec
    callees: List[Tuple[str, int, FrozenSet[str]]] = field(default_factory=list)
    unresolved: List[Tuple[str, int]] = field(default_factory=list)
    acquires: List[Tuple[str, int, FrozenSet[str], bool]] = field(
        default_factory=list)
    entry_locks: FrozenSet[str] = frozenset()
    callers: List[Tuple[str, int]] = field(default_factory=list)

    @property
    def qualname(self) -> str:
        return self.rec.qualname

    @property
    def line(self) -> int:
        return self.rec.line


@dataclass
class LockEdge:
    src: str                   # canonical lock held
    dst: str                   # canonical lock acquired under it
    witness: List[Tuple[str, int, str]]  # (func key, line, action)


@dataclass
class LockCycle:
    locks: List[str]
    edges: List[LockEdge]
    # every lock in the strongly connected component; the rendered
    # concrete cycle may be a shorter loop through it (transitive
    # may-acquire edges shortcut multi-hop chains)
    scc: List[str] = field(default_factory=list)

    def render(self, funcs: Dict[str, "FuncNode"]) -> str:
        lines = [" -> ".join(self.locks + [self.locks[0]])]
        if len(self.scc) > len(self.locks):
            lines.append(
                f"  (strongly connected with: {', '.join(self.scc)})")
        for e in self.edges:
            lines.append(f"  edge {e.src} -> {e.dst}:")
            for key, ln, action in e.witness:
                node = funcs.get(key)
                where = (f"{node.relpath}:{ln}" if node else f"?:{ln}")
                qn = node.qualname if node else key
                lines.append(f"    {qn} ({where}) {action}")
        return "\n".join(lines)


class Program:
    """The linked whole-repo view handed to Rule.finalize_program()."""

    def __init__(self, files: Dict[str, FileGraph]):
        self.files = files
        self.modules: Dict[str, str] = {fg.module: fg.relpath
                                        for fg in files.values()}
        # class name -> [(module, ClassRec)]; bare names (incl. nested
        # "Outer.Inner") — collisions resolved via import bindings
        self.class_index: Dict[str, List[Tuple[str, ClassRec]]] = {}
        # (module, class, method) -> func key; (module, func) -> key
        self.methods: Dict[Tuple[str, str, str], str] = {}
        self.mod_funcs: Dict[Tuple[str, str], str] = {}
        self.funcs: Dict[str, FuncNode] = {}
        # lock registry: attr -> [(kind, owner)] where owner is a class
        # display name or module dotted name
        self.lock_owners: Dict[str, List[Tuple[str, str]]] = {}
        self._method_defs: Dict[str, List[str]] = {}
        self._lock_summary: Optional[Dict[str, Dict[str, Tuple]]] = None
        self._lock_edges: Optional[Dict[Tuple[str, str], LockEdge]] = None
        self._index()
        self._link()

    # -- indexing --------------------------------------------------------
    def _index(self) -> None:
        for fg in self.files.values():
            for cname, crec in fg.classes.items():
                self.class_index.setdefault(cname, []).append(
                    (fg.module, crec))
            for fr in fg.funcs:
                key = f"{fg.relpath}:{fr.qualname}"
                node = FuncNode(key=key, relpath=fg.relpath,
                                module=fg.module, rec=fr)
                self.funcs[key] = node
                if fr.cls:
                    self.methods[(fg.module, fr.cls, fr.name)] = key
                    self._method_defs.setdefault(fr.name, []).append(key)
                else:
                    self.mod_funcs[(fg.module, fr.qualname)] = key
            for cname, crec in fg.classes.items():
                for attr in crec.lock_attrs:
                    self.lock_owners.setdefault(attr, []).append(
                        ("class", self._class_display(cname, fg.module)))
            for lname in fg.module_locks:
                self.lock_owners.setdefault(lname, []).append(
                    ("module", fg.module))
        for owners in self.lock_owners.values():
            owners.sort()

    def _class_display(self, cname: str, module: str) -> str:
        entries = self.class_index.get(cname, [])
        if len(entries) <= 1:
            return cname
        return f"{module}.{cname}"

    # -- class / method resolution --------------------------------------
    def _resolve_class(self, raw: str, fg: FileGraph,
                       fr: Optional[FuncRec] = None
                       ) -> Optional[Tuple[str, ClassRec]]:
        """Raw class expr from [fg]'s namespace -> (module, ClassRec)."""
        if not raw:
            return None
        parts = raw.split(".")
        sym = dict(fg.sym_aliases)
        mods = dict(fg.mod_aliases)
        if fr is not None:
            sym.update(fr.sym_aliases)
            mods.update(fr.mod_aliases)
        # strip a module-alias head: "mod.Class" / "pkg.mod.Class"
        if parts[0] in mods and len(parts) >= 2:
            target = mods[parts[0]]
            rest = parts[1:]
            for cut in range(len(rest) - 1, -1, -1):
                cand_mod = ".".join([target] + rest[:cut])
                cand_cls = ".".join(rest[cut:])
                if cand_mod in self.modules and cand_cls:
                    hit = self._class_in_module(cand_mod, cand_cls)
                    if hit:
                        return hit
            return None
        head = parts[0]
        if head in sym:
            tmod, tsym = sym[head]
            cand = ".".join([tsym] + parts[1:])
            sub = f"{tmod}.{tsym}"
            if sub in self.modules and len(parts) >= 2:
                hit = self._class_in_module(sub, ".".join(parts[1:]))
                if hit:
                    return hit
            hit = self._class_in_module(tmod, cand)
            if hit:
                return hit
            return None
        # same module
        hit = self._class_in_module(fg.module, raw)
        if hit:
            return hit
        # globally unique bare name
        entries = self.class_index.get(raw, [])
        if len(entries) == 1:
            return entries[0]
        return None

    def _class_in_module(self, module: str,
                         cname: str) -> Optional[Tuple[str, ClassRec]]:
        for mod, crec in self.class_index.get(cname, []):
            if mod == module:
                return (mod, crec)
        return None

    def _mro(self, module: str, crec: ClassRec,
             _seen=None) -> List[Tuple[str, ClassRec]]:
        if _seen is None:
            _seen = set()
        if (module, crec.name) in _seen:
            return []
        _seen.add((module, crec.name))
        out = [(module, crec)]
        fg = self.files.get(self.modules.get(module, ""), None)
        for braw in crec.bases:
            hit = self._resolve_class(braw, fg) if fg else None
            if hit:
                out.extend(self._mro(hit[0], hit[1], _seen))
        return out

    def _method_on(self, module: str, crec: ClassRec,
                   name: str) -> Optional[str]:
        for mod, c in self._mro(module, crec):
            key = self.methods.get((mod, c.name, name))
            if key:
                return key
        return None

    def _unique_method(self, name: str) -> Optional[str]:
        if name.startswith("__") or name in GENERIC_METHOD_NAMES:
            return None
        keys = self._method_defs.get(name, [])
        return keys[0] if len(keys) == 1 else None

    def _hinted_class(self, recv: str) -> Optional[Tuple[str, ClassRec]]:
        cname = RECEIVER_HINTS.get(recv)
        if cname is None:
            # auto hint: receiver name == class name lowercased
            for cand, entries in self.class_index.items():
                if cand.lower() == recv and len(entries) == 1:
                    return entries[0]
            return None
        entries = self.class_index.get(cname, [])
        return entries[0] if len(entries) == 1 else None

    # -- lock canonicalization -------------------------------------------
    def canonical_lock(self, raw: str, fg: FileGraph,
                       fr: Optional[FuncRec]) -> Optional[str]:
        parts = raw.split(".")
        attr = parts[-1]
        recv = parts[:-1]
        owners = self.lock_owners.get(attr, [])
        if not recv:
            # bare name: module-level lock (local module wins)
            if attr in fg.module_locks:
                return f"{fg.module}:{attr}"
            mods = [o for k, o in owners if k == "module"]
            if len(mods) == 1 and not any(k == "class" for k, _ in owners):
                return f"{mods[0]}:{attr}"
            # guarded-by annotation on a method names the attr bare;
            # fall through to owner resolution
        cls_owners = [o for k, o in owners if k == "class"]
        if len(cls_owners) == 1 and not recv:
            return f"{cls_owners[0]}.{attr}"
        if recv and recv[0] == "self" and fr is not None and fr.cls:
            if len(recv) == 1:
                hit = self._class_in_module(fg.module, fr.cls)
                if hit:
                    for mod, c in self._mro(hit[0], hit[1]):
                        if attr in c.lock_attrs:
                            return (f"{self._class_display(c.name, mod)}"
                                    f".{attr}")
            elif len(recv) == 2:
                hit = self._typed_attr(fg, fr, recv[1])
                if hit:
                    mod, c = hit
                    for m2, c2 in self._mro(mod, c):
                        if attr in c2.lock_attrs:
                            return (f"{self._class_display(c2.name, m2)}"
                                    f".{attr}")
        if recv and recv[-1] != "self":
            hit = self._hinted_class(recv[-1])
            if hit:
                mod, c = hit
                for m2, c2 in self._mro(mod, c):
                    if attr in c2.lock_attrs:
                        return f"{self._class_display(c2.name, m2)}.{attr}"
        if len(cls_owners) == 1:
            return f"{cls_owners[0]}.{attr}"
        return None

    def _typed_attr(self, fg: FileGraph, fr: FuncRec,
                    attr: str) -> Optional[Tuple[str, ClassRec]]:
        hit = self._class_in_module(fg.module, fr.cls) if fr.cls else None
        if not hit:
            return None
        for mod, c in self._mro(hit[0], hit[1]):
            raw = c.attr_types.get(attr)
            if raw:
                mfg = self.files.get(self.modules.get(mod, ""))
                return self._resolve_class(raw, mfg or fg, fr)
        return None

    # -- call resolution -------------------------------------------------
    def _resolve_call(self, fg: FileGraph, fr: FuncRec,
                      target: str) -> Optional[str]:
        parts = target.split(".")
        sym = dict(fg.sym_aliases)
        sym.update(fr.sym_aliases)
        mods = dict(fg.mod_aliases)
        mods.update(fr.mod_aliases)
        name = parts[-1]

        if parts[0] == "self" and fr.cls:
            hit = self._class_in_module(fg.module, fr.cls)
            if len(parts) == 2 and hit:
                return self._method_on(hit[0], hit[1], name)
            if len(parts) == 3 and hit:
                thit = self._typed_attr(fg, fr, parts[1])
                if thit:
                    return self._method_on(thit[0], thit[1], name)
            return self._fallback(parts)

        if len(parts) == 1:
            key = self.mod_funcs.get((fg.module, name))
            if key:
                return key
            if name in sym:
                tmod, tsym = sym[name]
                key = self.mod_funcs.get((tmod, tsym))
                if key:
                    return key
                hit = self._class_in_module(tmod, tsym)
                if hit:
                    return self._method_on(hit[0], hit[1], "__init__")
                sub = f"{tmod}.{tsym}"
                if sub in self.modules:
                    return None  # bare call of a module alias — not a call
            hit = self._class_in_module(fg.module, name)
            if hit:
                return self._method_on(hit[0], hit[1], "__init__")
            return None  # builtin / stdlib

        # dotted: module alias head?
        if parts[0] in mods:
            target_mod = mods[parts[0]]
            rest = parts[1:]
            for cut in range(len(rest) - 1, -1, -1):
                cand_mod = ".".join([target_mod] + rest[:cut])
                if cand_mod not in self.modules:
                    continue
                tail = rest[cut:]
                if len(tail) == 1:
                    key = self.mod_funcs.get((cand_mod, tail[0]))
                    if key:
                        return key
                    hit = self._class_in_module(cand_mod, tail[0])
                    if hit:
                        return self._method_on(hit[0], hit[1], "__init__")
                elif len(tail) == 2:
                    hit = self._class_in_module(cand_mod, tail[0])
                    if hit:
                        return self._method_on(hit[0], hit[1], tail[1])
                break
            return self._fallback(parts)

        if parts[0] in sym:
            tmod, tsym = sym[parts[0]]
            sub = f"{tmod}.{tsym}"
            if sub in self.modules:
                # `from pkg import sub` then sub.f() / sub.C.m()
                if len(parts) == 2:
                    key = self.mod_funcs.get((sub, parts[1]))
                    if key:
                        return key
                    hit = self._class_in_module(sub, parts[1])
                    if hit:
                        return self._method_on(hit[0], hit[1], "__init__")
                elif len(parts) == 3:
                    hit = self._class_in_module(sub, parts[1])
                    if hit:
                        return self._method_on(hit[0], hit[1], parts[2])
            hit = self._class_in_module(tmod, tsym)
            if hit and len(parts) == 2:
                return self._method_on(hit[0], hit[1], parts[1])
            return self._fallback(parts)

        return self._fallback(parts)

    def _fallback(self, parts: List[str]) -> Optional[str]:
        """Receiver-hint then unique-method resolution for calls through
        untyped locals (`chain.accept(...)`)."""
        if len(parts) < 2:
            return None
        name = parts[-1]
        hit = self._hinted_class(parts[-2])
        if hit:
            key = self._method_on(hit[0], hit[1], name)
            if key:
                return key
        return self._unique_method(name)

    # -- linking ---------------------------------------------------------
    def _link(self) -> None:
        for key in sorted(self.funcs):
            node = self.funcs[key]
            fg = self.files[node.relpath]
            fr = node.rec
            entry = set()
            for raw in fr.entry_locks:
                c = self.canonical_lock(raw, fg, fr)
                if c:
                    entry.add(c)
            node.entry_locks = frozenset(entry)

            def canon_held(held_raw: Tuple[str, ...]) -> FrozenSet[str]:
                out = set(entry)
                for raw in held_raw:
                    c = self.canonical_lock(raw, fg, fr)
                    if c:
                        out.add(c)
                return frozenset(out)

            for acq in fr.acquires:
                c = self.canonical_lock(acq.lock, fg, fr)
                if c:
                    node.acquires.append(
                        (c, acq.line, canon_held(acq.held), acq.scoped))
            for call in fr.calls:
                ck = self._resolve_call(fg, fr, call.target)
                if ck and ck != key:
                    node.callees.append((ck, call.line,
                                         canon_held(call.held)))
                elif ck is None:
                    node.unresolved.append((call.target, call.line))
        for key in sorted(self.funcs):
            for ck, line, _held in self.funcs[key].callees:
                self.funcs[ck].callers.append((key, line))

    # -- lock summaries / edges / cycles ---------------------------------
    def lock_summaries(self) -> Dict[str, Dict[str, Tuple]]:
        """key -> {lock -> provenance}; provenance is ("acq", line) or
        ("call", callee_key, line). May-acquire, transitively."""
        if self._lock_summary is not None:
            return self._lock_summary
        summary: Dict[str, Dict[str, Tuple]] = {
            key: {} for key in self.funcs}
        for key in sorted(self.funcs):
            for lock, line, _held, _scoped in self.funcs[key].acquires:
                summary[key].setdefault(lock, ("acq", line))
        changed = True
        while changed:
            changed = False
            for key in sorted(self.funcs):
                mine = summary[key]
                for ck, line, _held in self.funcs[key].callees:
                    for lock in summary[ck]:
                        if lock not in mine:
                            mine[lock] = ("call", ck, line)
                            changed = True
        self._lock_summary = summary
        return summary

    def _expand_witness(self, key: str, lock: str,
                        depth: int = 0) -> List[Tuple[str, int, str]]:
        if depth > _MAX_WITNESS_DEPTH:
            return [(key, 0, f"... (witness truncated at depth {depth})")]
        prov = self.lock_summaries()[key].get(lock)
        if prov is None:
            return []
        if prov[0] == "acq":
            return [(key, prov[1], f"acquires {lock}")]
        _kind, ck, line = prov
        callee = self.funcs[ck]
        return ([(key, line, f"calls {callee.qualname}")]
                + self._expand_witness(ck, lock, depth + 1))

    def lock_edges(self) -> Dict[Tuple[str, str], LockEdge]:
        """Observed lock-order edges: held -> acquired-under-it.  Edges
        to a lock already in the held set are skipped (RLock
        reentrancy), as are self-edges."""
        if self._lock_edges is not None:
            return self._lock_edges
        summary = self.lock_summaries()
        edges: Dict[Tuple[str, str], LockEdge] = {}

        def add(a: str, b: str, witness) -> None:
            if a == b:
                return
            if (a, b) not in edges:
                edges[(a, b)] = LockEdge(a, b, witness)

        for key in sorted(self.funcs):
            node = self.funcs[key]
            for lock, line, held, _scoped in node.acquires:
                for h in sorted(held):
                    if h != lock:
                        add(h, lock, [(key, line, f"acquires {lock}")])
            for ck, line, held in node.callees:
                if not held:
                    continue
                for lock in sorted(summary[ck]):
                    if lock in held:
                        continue
                    for h in sorted(held):
                        add(h, lock,
                            [(key, line,
                              f"calls {self.funcs[ck].qualname}")]
                            + self._expand_witness(ck, lock))
        self._lock_edges = edges
        return edges

    def lock_cycles(self) -> List[LockCycle]:
        """SCCs of size >= 2 in the lock-order graph, each rendered as a
        deterministic concrete cycle with per-edge witnesses."""
        edges = self.lock_edges()
        adj: Dict[str, List[str]] = {}
        for (a, b) in edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        for v in adj.values():
            v.sort()
        sccs = _tarjan(adj)
        out: List[LockCycle] = []
        for scc in sccs:
            if len(scc) < 2:
                continue
            nodes = sorted(scc)
            start = nodes[0]
            cycle = _cycle_through(adj, set(scc), start)
            if not cycle:
                continue
            cyc_edges = [edges[(cycle[i], cycle[(i + 1) % len(cycle)])]
                         for i in range(len(cycle))]
            out.append(LockCycle(cycle, cyc_edges, nodes))
        out.sort(key=lambda c: c.locks)
        return out

    def lock_order(self) -> List[str]:
        """Deterministic topological order of the lock-order graph
        (stable Kahn); only meaningful when lock_cycles() is empty."""
        edges = self.lock_edges()
        nodes = sorted({n for e in edges for n in e})
        indeg = {n: 0 for n in nodes}
        for (_a, b) in edges:
            indeg[b] += 1
        order: List[str] = []
        ready = sorted(n for n in nodes if indeg[n] == 0)
        while ready:
            n = ready.pop(0)
            order.append(n)
            for (a, b) in sorted(edges):
                if a == n:
                    indeg[b] -= 1
                    if indeg[b] == 0 and b not in order:
                        ready.append(b)
            ready.sort()
        return order

    # -- reachability ------------------------------------------------------
    def reachable(self, seeds: Iterable[str],
                  skip: Optional[Sequence[str]] = None
                  ) -> Dict[str, Tuple[Optional[str], int]]:
        """BFS over call edges from [seeds] (func keys).  Returns
        {key: (parent_key, call_line)}; seeds map to (None, 0).  [skip]:
        relpath prefixes never entered."""
        skip = tuple(skip or ())
        seen: Dict[str, Tuple[Optional[str], int]] = {}
        queue: List[str] = []
        for s in seeds:
            if s in self.funcs and s not in seen:
                seen[s] = (None, 0)
                queue.append(s)
        while queue:
            key = queue.pop(0)
            for ck, line, _held in self.funcs[key].callees:
                if ck in seen:
                    continue
                node = self.funcs[ck]
                if any(node.relpath.startswith(p) for p in skip):
                    continue
                seen[ck] = (key, line)
                queue.append(ck)
        return seen

    def chain_to(self, seen: Dict[str, Tuple[Optional[str], int]],
                 key: str) -> List[str]:
        """Render the BFS parent chain seed -> ... -> key as qualnames."""
        chain: List[str] = []
        cur: Optional[str] = key
        while cur is not None and len(chain) <= _MAX_WITNESS_DEPTH + 2:
            node = self.funcs[cur]
            chain.append(f"{node.qualname} ({node.relpath}:{node.line})")
            cur = seen[cur][0]
        return list(reversed(chain))

    # -- module import closure (SA011 promotion) --------------------------
    def module_scope_imports(self, module: str) -> List[Tuple[str, int]]:
        rel = self.modules.get(module)
        if rel is None:
            return []
        out = []
        for target, line in self.files[rel].module_imports:
            out.append((self._nearest_module(target), line))
        return out

    def _nearest_module(self, dotted_target: str) -> str:
        """'coreth_tpu.core.blockchain.BlockChain' -> the longest prefix
        that is a known module (an import of a symbol still executes the
        whole module)."""
        parts = dotted_target.split(".")
        for cut in range(len(parts), 0, -1):
            cand = ".".join(parts[:cut])
            if cand in self.modules:
                return cand
        return dotted_target

    # -- lookup for the CLI ----------------------------------------------
    def find(self, fragment: str) -> List[FuncNode]:
        """Functions whose key/qualname contains [fragment] (exact
        qualname match wins when present)."""
        exact = [n for n in self.funcs.values()
                 if n.qualname == fragment
                 or f"{n.relpath}:{n.qualname}" == fragment]
        if exact:
            return sorted(exact, key=lambda n: n.key)
        return sorted((n for n in self.funcs.values()
                       if fragment in n.key), key=lambda n: n.key)


def build_program(filegraphs: Iterable[FileGraph]) -> Program:
    return Program({fg.relpath: fg for fg in filegraphs})


# ---------------------------------------------------------------- plumbing

def _tarjan(adj: Dict[str, List[str]]) -> List[List[str]]:
    """Iterative Tarjan SCC (deterministic given sorted adjacency)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    for root in sorted(adj):
        if root in index:
            continue
        work = [(root, iter(adj[root]))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(adj[w])))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == v:
                        break
                sccs.append(scc)
    return sccs


def _cycle_through(adj: Dict[str, List[str]], scc: Set[str],
                   start: str) -> Optional[List[str]]:
    """A concrete directed cycle within [scc] starting at [start]."""
    # BFS back to start restricted to the SCC
    parent: Dict[str, str] = {}
    queue = [start]
    seen = {start}
    while queue:
        v = queue.pop(0)
        for w in adj.get(v, []):
            if w == start and v != start:
                path = [start]
                cur = v
                back = []
                while cur != start:
                    back.append(cur)
                    cur = parent[cur]
                path.extend(reversed(back))
                return path
            if w in scc and w not in seen:
                seen.add(w)
                parent[w] = v
                queue.append(w)
    return None
