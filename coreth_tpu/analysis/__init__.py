"""Repo-native static analysis (the `go vet` role, SURVEY §5).

`python -m coreth_tpu.analysis` walks the package with the SA001–SA005
rule set and exits non-zero on any finding outside the checked-in
allowlist (`coreth_tpu/analysis/baseline.txt`).  Tier-1 gate:
tests/test_static_analysis.py runs the same entry point.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Optional, Tuple

from .engine import (BaselineError, Engine, Finding, SourceFile,
                     apply_baseline, load_baseline)
from .rules import ALL_RULES, default_rules

PACKAGE_ROOT = Path(__file__).resolve().parent.parent
BASELINE_PATH = Path(__file__).resolve().parent / "baseline.txt"

__all__ = [
    "ALL_RULES", "BASELINE_PATH", "BaselineError", "Engine", "Finding",
    "PACKAGE_ROOT", "SourceFile", "apply_baseline", "default_rules",
    "load_baseline", "run_repo",
]


def run_repo(package_root: Optional[Path] = None,
             baseline_path: Optional[Path] = None,
             cache: bool = True,
             engine: Optional[Engine] = None,
             ) -> Tuple[List[Finding], List[Finding], List[str], Dict[str, str]]:
    """Analyze the package. Returns (new, suppressed, unused_baseline_keys,
    baseline) — `new` non-empty means the gate is red.  With cache=True
    (default) unchanged files replay from the per-file result cache (see
    cache.py); pass an Engine to inspect `engine.program` afterwards."""
    engine = engine if engine is not None else Engine(default_rules())
    root = package_root or PACKAGE_ROOT
    fc = None
    if cache:
        from .cache import FileCache, default_cache_path
        fc = FileCache.load(default_cache_path(root))
    findings = engine.check_package(root, cache=fc)
    bp = baseline_path if baseline_path is not None else BASELINE_PATH
    baseline = load_baseline(bp) if bp.exists() else {}
    new, suppressed, unused = apply_baseline(findings, baseline)
    return new, suppressed, unused, baseline
