"""Per-file analysis cache: warm lint runs skip parse + rule passes.

The cold pipeline costs ~3s of parse/tokenize + ~3s of rule visitors on
this repo — too slow for a tier-1 gate that runs on every lint.  The
profile says re-loading pickled ASTs costs nearly as much as re-parsing
them, so the cache deliberately stores *results*, not trees: per file,
the findings list, each stateful rule's picklable summary (replayed via
`Rule.absorb`), and the `callgraph.FileGraph` extraction — everything
downstream of the AST.  A warm run re-does only the cheap whole-repo
work: baseline matching, cross-file finalize, and the call-graph link.

Keying: a file entry is valid iff its (st_mtime_ns, st_size) pair is
unchanged.  The whole cache is additionally fingerprinted by the
analyzer's own sources (every .py in this directory, same mtime/size
pair) and a schema number — editing a rule invalidates everything.

The store is one pickle under the system temp dir, keyed by the package
path and uid so parallel checkouts and users never collide.  Corrupt or
stale caches are ignored, never trusted; writes go through a temp file
+ os.replace so a crashed run can't leave a torn cache.  Set
CORETH_TPU_ANALYSIS_CACHE to a path to relocate it, or to "off"/"0" to
disable (the CLI's --no-cache does the same).
"""

from __future__ import annotations

import hashlib
import os
import pickle
import tempfile
from pathlib import Path
from typing import Dict, Optional, Tuple

CACHE_SCHEMA = 1


def analyzer_token() -> Tuple:
    """Fingerprint of the analyzer itself: rule edits invalidate the
    whole cache (cached findings were produced by different code)."""
    here = Path(__file__).resolve().parent
    parts = []
    for p in sorted(here.glob("*.py")):
        try:
            st = p.stat()
        except OSError:
            continue
        parts.append((p.name, st.st_mtime_ns, st.st_size))
    return tuple(parts)


def default_cache_path(package_root: Path) -> Optional[Path]:
    env = os.environ.get("CORETH_TPU_ANALYSIS_CACHE")
    if env is not None:
        if env.strip().lower() in ("", "0", "off", "no"):
            return None
        return Path(env)
    digest = hashlib.md5(str(package_root).encode()).hexdigest()[:10]
    uid = getattr(os, "getuid", lambda: 0)()
    return (Path(tempfile.gettempdir())
            / f"coreth-tpu-analysis-{digest}-{uid}.pkl")


class FileCache:
    """mtime/size-keyed store of (findings, summaries, FileGraph)."""

    def __init__(self, path: Path, token: Tuple):
        self.path = path
        self.token = token
        self.files: Dict[str, dict] = {}
        self._touched: set = set()
        self._dirty = False

    @classmethod
    def load(cls, path: Optional[Path]) -> Optional["FileCache"]:
        if path is None:
            return None
        token = analyzer_token()
        cache = cls(path, token)
        try:
            with path.open("rb") as fh:
                blob = pickle.load(fh)
            if (blob.get("schema") == CACHE_SCHEMA
                    and blob.get("token") == token):
                cache.files = blob.get("files", {})
        except (OSError, pickle.UnpicklingError, EOFError, AttributeError,
                ImportError, IndexError, ValueError, KeyError):
            pass  # absent/corrupt/stale caches start empty
        return cache

    def lookup(self, path: Path, rel: str):
        try:
            st = path.stat()
        except OSError:
            return None
        entry = self.files.get(rel)
        if entry is None or entry["meta"] != (st.st_mtime_ns, st.st_size):
            return None
        self._touched.add(rel)
        return entry["findings"], entry["summaries"], entry["graph"]

    def store(self, path: Path, rel: str, findings, summaries, graph) -> None:
        try:
            st = path.stat()
        except OSError:
            return
        self.files[rel] = {"meta": (st.st_mtime_ns, st.st_size),
                           "findings": findings, "summaries": summaries,
                           "graph": graph}
        self._touched.add(rel)
        self._dirty = True

    def save(self) -> None:
        stale = set(self.files) - self._touched
        if stale:
            for rel in stale:  # deleted/renamed files fall out
                del self.files[rel]
            self._dirty = True
        if not self._dirty:
            return
        blob = {"schema": CACHE_SCHEMA, "token": self.token,
                "files": self.files}
        try:
            fd, tmp = tempfile.mkstemp(dir=str(self.path.parent),
                                       prefix=self.path.name + ".")
            try:
                with os.fdopen(fd, "wb") as fh:
                    pickle.dump(blob, fh, protocol=pickle.HIGHEST_PROTOCOL)
                os.replace(tmp, self.path)
            except BaseException:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                raise
        except OSError:
            pass  # a read-only temp dir degrades to cold runs, not errors
