"""AST lint engine — the repo-native analog of `go vet` (SURVEY §5).

The reference coreth keeps its concurrency and error-handling discipline
honest with `go vet` + `go test -race`; this package is the Python port's
equivalent: a small AST walker (`Engine`) over pluggable `Rule` visitors,
each encoding one repo-specific invariant (silent excepts, lock
discipline, hot-path purity, consensus float-freedom, unordered
iteration into hashing).  Findings carry file:line + rule id + the
enclosing qualname, and are keyed `RULE:relpath:qualname` so the
checked-in baseline (`analysis/baseline.txt`) survives line drift.

Source-level annotations the rules understand (scanned from comments):

    # guarded-by: <lockattr>   on an attribute assignment → that
                               attribute must only be mutated with
                               self.<lockattr> held
    # guarded-by: <lockattr>   on a `def` line → the method's CALLER
                               holds the lock (helper-under-lock), so
                               writes inside it count as guarded
    # hot-path                 on a `def` line → SA003 purity rules
                               apply to the function body
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
HOT_PATH_RE = re.compile(r"#\s*hot-path\b")


@dataclass(frozen=True)
class Finding:
    rule: str            # "SA001"
    path: str            # repo-relative posix path
    line: int
    qualname: str        # enclosing Class.method / function / "<module>"
    message: str

    @property
    def key(self) -> str:
        """Baseline key: stable across line drift within one function."""
        return f"{self.rule}:{self.path}:{self.qualname}"

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.rule} [{self.qualname}] {self.message}"


@dataclass
class SourceFile:
    relpath: str
    text: str
    tree: ast.Module
    # line -> comment text (comments only; from tokenize, so string
    # literals containing '#' can never masquerade as annotations)
    comments: Dict[int, str] = field(default_factory=dict)
    # line -> lock name from a `# guarded-by: <lock>` annotation
    guarded_by: Dict[int, str] = field(default_factory=dict)
    # lines carrying a `# hot-path` marker
    hot_lines: frozenset = frozenset()

    @classmethod
    def from_source(cls, text: str, relpath: str = "<fixture>") -> "SourceFile":
        tree = ast.parse(text)
        comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    comments[tok.start[0]] = tok.string
        except tokenize.TokenError:
            pass  # a truncated final line still yields earlier comments
        guarded = {}
        hot = set()
        for line, c in comments.items():
            m = GUARDED_BY_RE.search(c)
            if m:
                guarded[line] = m.group(1)
            if HOT_PATH_RE.search(c):
                hot.add(line)
        return cls(relpath=relpath, text=text, tree=tree,
                   comments=comments, guarded_by=guarded,
                   hot_lines=frozenset(hot))

    def def_annotation(self, node: ast.AST) -> Tuple[Optional[str], bool]:
        """(guarded-by lock, hot?) for a def: the annotation comment may sit
        anywhere on the signature (multi-line defs included), the line above
        the def, or a decorator line."""
        body = getattr(node, "body", None)
        sig_end = body[0].lineno - 1 if body else node.lineno
        lines = list(range(node.lineno, max(node.lineno, sig_end) + 1))
        if getattr(node, "decorator_list", None):
            lines.extend(d.lineno for d in node.decorator_list)
        lines.append(min(lines) - 1)
        lock = None
        hot = False
        for ln in lines:
            if ln in self.guarded_by and lock is None:
                lock = self.guarded_by[ln]
            if ln in self.hot_lines:
                hot = True
        return lock, hot


class Rule:
    """One invariant. Subclasses set `id`/`title` and implement check()."""

    id: str = "SA000"
    title: str = ""

    def check(self, src: SourceFile) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    def summarize(self, src: SourceFile):
        """Optional picklable per-file digest for cross-file state.  The
        engine calls this right after check() on a fresh parse, caches
        the result alongside the file's findings, and replays it through
        absorb() on cache hits — so stateful rules stay correct when the
        per-file passes are skipped entirely.  Contract: summarize() runs
        only after check() on the same SourceFile."""
        return None

    def absorb(self, relpath: str, summary) -> None:
        """Feed back a (possibly cached) per-file summary before
        finalize().  Files arrive in sorted-relpath order."""

    def finalize(self) -> Iterator[Finding]:
        """Cross-file pass, called once after every file has been
        check()ed/absorb()ed.  Stateful rules (SA006 failpoint registry)
        report whole-package invariants here; the default has none."""
        return iter(())

    def finalize_program(self, program) -> Iterator[Finding]:
        """Interprocedural pass over the linked whole-repo
        `callgraph.Program` (call edges, lock summaries, import
        closure), called once after finalize().  SA013 and the
        promoted SA003/SA010/SA011 live here; the default has none."""
        return iter(())

    def finding(self, src: SourceFile, node: ast.AST, qualname: str,
                message: str) -> Finding:
        return Finding(self.id, src.relpath, getattr(node, "lineno", 0),
                       qualname, message)


class QualnameVisitor(ast.NodeVisitor):
    """NodeVisitor that tracks the enclosing Class.method qualname."""

    def __init__(self):
        self._stack: List[str] = []

    @property
    def qualname(self) -> str:
        return ".".join(self._stack) if self._stack else "<module>"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def _visit_func(self, node) -> None:
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


# --------------------------------------------------------------- baseline

class BaselineError(ValueError):
    pass


def load_baseline(path: Path) -> Dict[str, str]:
    """Parse the allowlist: one `RULE path:qualname — justification` per
    line; '#' comments and blanks skipped.  A missing justification is an
    error — the allowlist must say WHY each site is exempt."""
    entries: Dict[str, str] = {}
    for n, raw in enumerate(path.read_text().splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        m = re.match(r"^(SA\d{3})\s+(\S+)\s+[—-]+\s*(.+)$", line)
        if not m:
            raise BaselineError(f"{path.name}:{n}: unparseable entry: {raw!r}")
        rule, site, why = m.groups()
        if not why.strip():
            raise BaselineError(f"{path.name}:{n}: missing justification")
        entries[f"{rule}:{site}"] = why.strip()
    return entries


def apply_baseline(findings: List[Finding], baseline: Dict[str, str]):
    """Split into (new, suppressed, unused-baseline-keys)."""
    new: List[Finding] = []
    suppressed: List[Finding] = []
    used = set()
    for f in findings:
        if f.key in baseline:
            suppressed.append(f)
            used.add(f.key)
        else:
            new.append(f)
    unused = sorted(set(baseline) - used)
    return new, suppressed, unused


# ----------------------------------------------------------------- engine

class Engine:
    def __init__(self, rules: Iterable[Rule]):
        self.rules = list(rules)
        # the linked whole-repo Program from the last
        # check_package()/check_program() run (for the --graph CLI)
        self.program = None

    def _check_one(self, src: SourceFile):
        """(findings, {rule_id: summary}) for one parsed file."""
        findings: List[Finding] = []
        summaries: Dict[str, object] = {}
        for rule in self.rules:
            findings.extend(rule.check(src))
            s = rule.summarize(src)
            if s is not None:
                summaries[rule.id] = s
        return findings, summaries

    def check_source(self, text: str, relpath: str = "<fixture>") -> List[Finding]:
        """Single-file pass (per-file rules only; cross-file state is
        absorbed so a later finalize() on this engine sees it)."""
        src = SourceFile.from_source(text, relpath)
        findings, summaries = self._check_one(src)
        for rule in self.rules:
            if rule.id in summaries:
                rule.absorb(relpath, summaries[rule.id])
        return findings

    def check_file(self, path: Path, root: Path) -> List[Finding]:
        rel = path.relative_to(root.parent).as_posix()
        try:
            text = path.read_text()
        except (OSError, UnicodeDecodeError) as exc:
            return [Finding("SA000", rel, 0, "<module>", f"unreadable: {exc}")]
        try:
            return self.check_source(text, rel)
        except SyntaxError as exc:
            return [Finding("SA000", rel, exc.lineno or 0, "<module>",
                            f"syntax error: {exc.msg}")]

    def check_program(self, sources: Iterable[Tuple[str, str]]
                      ) -> List[Finding]:
        """Full pipeline over in-memory (text, relpath) pairs: per-file
        rules, cross-file finalize, and the interprocedural
        finalize_program over the linked call graph.  This is what the
        multi-file fixture tests drive; check_package is the same flow
        plus the on-disk walk and cache."""
        from . import callgraph

        out: List[Finding] = []
        graphs = []
        for text, relpath in sources:
            src = SourceFile.from_source(text, relpath)
            findings, summaries = self._check_one(src)
            out.extend(findings)
            for rule in self.rules:
                if rule.id in summaries:
                    rule.absorb(relpath, summaries[rule.id])
            graphs.append(callgraph.extract_file(src))
        for rule in self.rules:
            out.extend(rule.finalize())
        self.program = callgraph.build_program(graphs)
        for rule in self.rules:
            out.extend(rule.finalize_program(self.program))
        out.sort(key=lambda f: (f.path, f.line, f.rule))
        return out

    def check_package(self, package_root: Path,
                      cache=None) -> List[Finding]:
        """Walk every .py under [package_root] (the coreth_tpu dir).
        With a `cache.FileCache`, unchanged files skip parse + per-file
        rules + graph extraction entirely (findings, summaries, and the
        FileGraph replay from the cache); the cross-file finalize and
        the interprocedural link always run fresh."""
        from . import callgraph

        out: List[Finding] = []
        graphs = []
        for path in sorted(package_root.rglob("*.py")):
            rel = path.relative_to(package_root.parent).as_posix()
            entry = cache.lookup(path, rel) if cache is not None else None
            if entry is None:
                findings: List[Finding]
                summaries: Dict[str, object] = {}
                graph = None
                try:
                    text = path.read_text()
                except (OSError, UnicodeDecodeError) as exc:
                    findings = [Finding("SA000", rel, 0, "<module>",
                                        f"unreadable: {exc}")]
                else:
                    try:
                        src = SourceFile.from_source(text, rel)
                    except SyntaxError as exc:
                        findings = [Finding("SA000", rel, exc.lineno or 0,
                                            "<module>",
                                            f"syntax error: {exc.msg}")]
                    else:
                        findings, summaries = self._check_one(src)
                        graph = callgraph.extract_file(src)
                if cache is not None:
                    cache.store(path, rel, findings, summaries, graph)
            else:
                findings, summaries, graph = entry
            out.extend(findings)
            if graph is not None:
                graphs.append(graph)
            for rule in self.rules:
                if rule.id in summaries:
                    rule.absorb(rel, summaries[rule.id])
        for rule in self.rules:
            out.extend(rule.finalize())
        self.program = callgraph.build_program(graphs)
        for rule in self.rules:
            out.extend(rule.finalize_program(self.program))
        if cache is not None:
            cache.save()
        out.sort(key=lambda f: (f.path, f.line, f.rule))
        return out
