"""AST lint engine — the repo-native analog of `go vet` (SURVEY §5).

The reference coreth keeps its concurrency and error-handling discipline
honest with `go vet` + `go test -race`; this package is the Python port's
equivalent: a small AST walker (`Engine`) over pluggable `Rule` visitors,
each encoding one repo-specific invariant (silent excepts, lock
discipline, hot-path purity, consensus float-freedom, unordered
iteration into hashing).  Findings carry file:line + rule id + the
enclosing qualname, and are keyed `RULE:relpath:qualname` so the
checked-in baseline (`analysis/baseline.txt`) survives line drift.

Source-level annotations the rules understand (scanned from comments):

    # guarded-by: <lockattr>   on an attribute assignment → that
                               attribute must only be mutated with
                               self.<lockattr> held
    # guarded-by: <lockattr>   on a `def` line → the method's CALLER
                               holds the lock (helper-under-lock), so
                               writes inside it count as guarded
    # hot-path                 on a `def` line → SA003 purity rules
                               apply to the function body
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

GUARDED_BY_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_][A-Za-z0-9_]*)")
HOT_PATH_RE = re.compile(r"#\s*hot-path\b")


@dataclass(frozen=True)
class Finding:
    rule: str            # "SA001"
    path: str            # repo-relative posix path
    line: int
    qualname: str        # enclosing Class.method / function / "<module>"
    message: str

    @property
    def key(self) -> str:
        """Baseline key: stable across line drift within one function."""
        return f"{self.rule}:{self.path}:{self.qualname}"

    def render(self) -> str:
        return f"{self.path}:{self.line} {self.rule} [{self.qualname}] {self.message}"


@dataclass
class SourceFile:
    relpath: str
    text: str
    tree: ast.Module
    # line -> comment text (comments only; from tokenize, so string
    # literals containing '#' can never masquerade as annotations)
    comments: Dict[int, str] = field(default_factory=dict)
    # line -> lock name from a `# guarded-by: <lock>` annotation
    guarded_by: Dict[int, str] = field(default_factory=dict)
    # lines carrying a `# hot-path` marker
    hot_lines: frozenset = frozenset()

    @classmethod
    def from_source(cls, text: str, relpath: str = "<fixture>") -> "SourceFile":
        tree = ast.parse(text)
        comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(io.StringIO(text).readline):
                if tok.type == tokenize.COMMENT:
                    comments[tok.start[0]] = tok.string
        except tokenize.TokenError:
            pass  # a truncated final line still yields earlier comments
        guarded = {}
        hot = set()
        for line, c in comments.items():
            m = GUARDED_BY_RE.search(c)
            if m:
                guarded[line] = m.group(1)
            if HOT_PATH_RE.search(c):
                hot.add(line)
        return cls(relpath=relpath, text=text, tree=tree,
                   comments=comments, guarded_by=guarded,
                   hot_lines=frozenset(hot))

    def def_annotation(self, node: ast.AST) -> Tuple[Optional[str], bool]:
        """(guarded-by lock, hot?) for a def: the annotation comment may sit
        anywhere on the signature (multi-line defs included), the line above
        the def, or a decorator line."""
        body = getattr(node, "body", None)
        sig_end = body[0].lineno - 1 if body else node.lineno
        lines = list(range(node.lineno, max(node.lineno, sig_end) + 1))
        if getattr(node, "decorator_list", None):
            lines.extend(d.lineno for d in node.decorator_list)
        lines.append(min(lines) - 1)
        lock = None
        hot = False
        for ln in lines:
            if ln in self.guarded_by and lock is None:
                lock = self.guarded_by[ln]
            if ln in self.hot_lines:
                hot = True
        return lock, hot


class Rule:
    """One invariant. Subclasses set `id`/`title` and implement check()."""

    id: str = "SA000"
    title: str = ""

    def check(self, src: SourceFile) -> Iterator[Finding]:  # pragma: no cover
        raise NotImplementedError

    def finalize(self) -> Iterator[Finding]:
        """Cross-file pass, called once after every file has been
        check()ed.  Stateful rules (SA006 failpoint registry) report
        whole-package invariants here; the default has none."""
        return iter(())

    def finding(self, src: SourceFile, node: ast.AST, qualname: str,
                message: str) -> Finding:
        return Finding(self.id, src.relpath, getattr(node, "lineno", 0),
                       qualname, message)


class QualnameVisitor(ast.NodeVisitor):
    """NodeVisitor that tracks the enclosing Class.method qualname."""

    def __init__(self):
        self._stack: List[str] = []

    @property
    def qualname(self) -> str:
        return ".".join(self._stack) if self._stack else "<module>"

    def visit_ClassDef(self, node: ast.ClassDef) -> None:
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    def _visit_func(self, node) -> None:
        self._stack.append(node.name)
        self.generic_visit(node)
        self._stack.pop()

    visit_FunctionDef = _visit_func
    visit_AsyncFunctionDef = _visit_func


# --------------------------------------------------------------- baseline

class BaselineError(ValueError):
    pass


def load_baseline(path: Path) -> Dict[str, str]:
    """Parse the allowlist: one `RULE path:qualname — justification` per
    line; '#' comments and blanks skipped.  A missing justification is an
    error — the allowlist must say WHY each site is exempt."""
    entries: Dict[str, str] = {}
    for n, raw in enumerate(path.read_text().splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        m = re.match(r"^(SA\d{3})\s+(\S+)\s+[—-]+\s*(.+)$", line)
        if not m:
            raise BaselineError(f"{path.name}:{n}: unparseable entry: {raw!r}")
        rule, site, why = m.groups()
        if not why.strip():
            raise BaselineError(f"{path.name}:{n}: missing justification")
        entries[f"{rule}:{site}"] = why.strip()
    return entries


def apply_baseline(findings: List[Finding], baseline: Dict[str, str]):
    """Split into (new, suppressed, unused-baseline-keys)."""
    new: List[Finding] = []
    suppressed: List[Finding] = []
    used = set()
    for f in findings:
        if f.key in baseline:
            suppressed.append(f)
            used.add(f.key)
        else:
            new.append(f)
    unused = sorted(set(baseline) - used)
    return new, suppressed, unused


# ----------------------------------------------------------------- engine

class Engine:
    def __init__(self, rules: Iterable[Rule]):
        self.rules = list(rules)

    def check_source(self, text: str, relpath: str = "<fixture>") -> List[Finding]:
        src = SourceFile.from_source(text, relpath)
        out: List[Finding] = []
        for rule in self.rules:
            out.extend(rule.check(src))
        return out

    def check_file(self, path: Path, root: Path) -> List[Finding]:
        rel = path.relative_to(root.parent).as_posix()
        try:
            text = path.read_text()
        except (OSError, UnicodeDecodeError) as exc:
            return [Finding("SA000", rel, 0, "<module>", f"unreadable: {exc}")]
        try:
            return self.check_source(text, rel)
        except SyntaxError as exc:
            return [Finding("SA000", rel, exc.lineno or 0, "<module>",
                            f"syntax error: {exc.msg}")]

    def check_package(self, package_root: Path) -> List[Finding]:
        """Walk every .py under [package_root] (the coreth_tpu dir)."""
        out: List[Finding] = []
        for path in sorted(package_root.rglob("*.py")):
            out.extend(self.check_file(path, package_root))
        for rule in self.rules:
            out.extend(rule.finalize())
        out.sort(key=lambda f: (f.path, f.line, f.rule))
        return out
