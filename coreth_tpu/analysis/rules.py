"""Repo-specific lint rules (the `go vet` analyzers this port needs).

SA001 silent-except    broad `except` that neither re-raises, logs, nor
                       counts — consensus-relevant failures must be loud
SA002 lock-discipline  attributes written under `self.<lock>` (or
                       annotated `# guarded-by: <lock>`) must never be
                       mutated outside it
SA003 hot-path-purity  `# hot-path` functions must not read wall-clock,
                       draw randomness, allocate ctypes buffers, or
                       construct metrics/spans per call (only the gated
                       phase_timer/expensive_timer/span helpers)
SA004 consensus-float  no float arithmetic where bit-exactness is the
                       product: trie/, rlp, evm gas, state hashing
SA005 unordered-iter   no set-order-dependent iteration feeding RLP or
                       hashing (bytes/str hashes are salted per process:
                       set order is not reproducible across nodes)
SA006 failpoint-hygiene  failpoint names are unique string literals
                       registered at module import; `failpoint()` only
                       fires registered names; no naked `time.sleep`
                       outside coreth_tpu/fault/ (use fault.Backoff)
SA007 serving-bounded  no unbounded `queue.Queue()` / `SimpleQueue()` or
                       un-capped `ThreadPoolExecutor()` in serving-path
                       modules — bounded queues ARE the admission control
SA008 backend-isolation  trie/ and bintrie/ may not import each other —
                       commitment backends meet only at the
                       state/commitment.py seam
SA009 fold-order       fold-step loops in the optimistic executor must
                       iterate in tx-index order (range/sorted only) —
                       completion-order folds break deterministic commit
SA010 read-tier-locks  read-only RPC handler modules (eth/api,
                       eth/filters, eth/gasprice, eth/backend) must not
                       touch `chainmu` or call chainmu-taking chain
                       methods — reads resolve against the published
                       ReadView, never the write path's lock
SA011 shard-worker-isolation  modules imported inside forked execution
                       shards (core/shard_worker.py) must stay fork-clean:
                       no metrics/blockchain imports, no `chainmu`, no
                       `default_registry`, no module-level mutable state —
                       module scope is stdlib + coreth_tpu.fault only,
                       EVM machinery is imported lazily per request
SA012 sharding-discipline  jitted commit entries in the mesh-sharded
                       modules (ops/keccak_resident, coreth_tpu/parallel)
                       must pin explicit in_shardings/out_shardings (or
                       carry a `# sharding:` justification), and no
                       single-argument `device_put` — implicit placement
                       reshards chained commits across processes
SA013 lock-order       the whole-program may-acquire graph must stay
                       acyclic — a cycle is a potential deadlock; the
                       acyclic order is mirrored at runtime by
                       racecheck.CANONICAL_LOCK_ORDER and its witness
SA014 metrics-family   Counter/Gauge/Meter/Timer/Histogram names created
                       outside metrics/ must match the documented
                       `^[a-z0-9_/]+$` namespace grammar (literal
                       f-string/concat fragments: charset only) and one
                       family name must never register under two
                       different metric types
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from .engine import Finding, QualnameVisitor, Rule, SourceFile

__all__ = ["ALL_RULES", "default_rules"]


def dotted(node: ast.AST) -> Optional[str]:
    """'time.time' for Attribute chains / Names; None for anything else."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def self_attr_base(node: ast.AST) -> Optional[str]:
    """The `X` in self.X / self.X[...] / self.X.setdefault(...)[...]:
    unwraps subscripts and call chains down to an attribute on `self`."""
    while True:
        if isinstance(node, ast.Subscript):
            node = node.value
        elif isinstance(node, ast.Call):
            node = node.func
        elif isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and node.value.id == "self":
                return node.attr
            node = node.value
        else:
            return None


# ------------------------------------------------------------------ SA001

BROAD_EXC_NAMES = {"Exception", "BaseException"}
LOG_ATTRS = {"trace", "debug", "info", "warning", "warn", "error",
             "exception", "critical", "fatal", "log", "print_exc"}
METRIC_ATTRS = {"inc", "dec", "mark", "observe"}
HANDLER_NAME_HINTS = ("count", "drop", "error", "metric", "record",
                      "violation", "reject")
CAPTURE_NAME_HINTS = ("error", "err", "failed", "drop", "violation")


def _is_broad_handler(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Tuple):
        names = [dotted(e) for e in t.elts]
    else:
        names = [dotted(t)]
    return any(n is not None and n.split(".")[-1] in BROAD_EXC_NAMES
               for n in names)


def _target_name(t: ast.AST) -> str:
    """Dotted name of an assignment target; for subscripts a constant
    string key joins in, so `out["error"] = …` reads as handling."""
    if isinstance(t, ast.Subscript):
        key = t.slice
        key_s = key.value if (isinstance(key, ast.Constant)
                              and isinstance(key.value, str)) else ""
        return f"{_target_name(t.value)}.{key_s}"
    return dotted(t) or ""


def _handler_is_loud(handler: ast.ExceptHandler) -> bool:
    """Does the handler body re-raise, log, count, capture, or answer the
    error in-band (a response carrying an `error` field)?"""
    for node in ast.walk(ast.Module(body=handler.body, type_ignores=[])):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else "")
            low = name.lower()
            if isinstance(fn, ast.Attribute) and name in LOG_ATTRS:
                return True
            if isinstance(fn, ast.Attribute) and name in METRIC_ATTRS:
                return True
            if any(h in low for h in HANDLER_NAME_HINTS):
                return True
            # error-collection idiom: errors.append(...) / errs.add(...)
            if isinstance(fn, ast.Attribute) and name in ("append", "add"):
                recv = dotted(fn.value) or ""
                if any(h in recv.lower() for h in CAPTURE_NAME_HINTS):
                    return True
            # in-band error replies: Response(error=...) keywords or a
            # dict-literal payload with an "error" key
            if any(kw.arg and "error" in kw.arg.lower()
                   for kw in node.keywords):
                return True
            for arg in node.args:
                if isinstance(arg, ast.Dict) and any(
                        isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                        and "error" in k.value.lower()
                        for k in arg.keys):
                    return True
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) else [node.target]
            for t in targets:
                tname = _target_name(t)
                if any(h in tname.lower() for h in CAPTURE_NAME_HINTS):
                    return True
    return False


class SilentExceptRule(Rule):
    id = "SA001"
    title = "broad except neither re-raises, logs, nor counts"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        rule = self
        findings: List[Finding] = []

        class V(QualnameVisitor):
            def visit_Try(self, node: ast.Try) -> None:
                for h in node.handlers:
                    if _is_broad_handler(h) and not _handler_is_loud(h):
                        exc = "except" if h.type is None else (
                            f"except {ast.unparse(h.type)}")
                        findings.append(rule.finding(
                            src, h, self.qualname,
                            f"`{exc}` swallows silently: re-raise, log, "
                            f"or bump a metrics counter"))
                self.generic_visit(node)

            visit_TryStar = visit_Try  # 3.11 except* groups

        V().visit(src.tree)
        return iter(findings)


# ------------------------------------------------------------------ SA002

LOCK_ATTR_HINTS = ("lock", "mu", "cond", "_cv")
# methods mutating their receiver in place (queue put/get excluded:
# queues synchronize themselves)
MUTATOR_ATTRS = {"append", "appendleft", "add", "remove", "discard", "pop",
                 "popleft", "popitem", "clear", "extend", "insert",
                 "setdefault", "sort", "reverse"}
ALL_LOCKS = "<all>"


def _is_lock_name(attr: str) -> bool:
    low = attr.lower()
    return any(h in low for h in LOCK_ATTR_HINTS)


class _Write:
    __slots__ = ("qualname", "line", "locks", "in_init")

    def __init__(self, qualname: str, line: int, locks: frozenset, in_init: bool):
        self.qualname = qualname
        self.line = line
        self.locks = locks
        self.in_init = in_init


class _MethodWalker(ast.NodeVisitor):
    """Collect self-attribute writes in one method with the set of
    self-locks held (via `with self.<lock>:`) at each write site."""

    def __init__(self, src: SourceFile, cls: str, method: str,
                 entry_locks: frozenset, writes: Dict[str, List["_Write"]]):
        self.src = src
        self.cls = cls
        self.method = method
        self.locks = set(entry_locks)
        self.writes = writes
        self.in_init = method == "__init__"
        self._annotations: Dict[str, str] = {}

    # -- lock scope ------------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        held = []
        for item in node.items:
            ctx = item.context_expr
            base = self_attr_base(ctx)
            if base is not None and _is_lock_name(base):
                held.append(base)
        self.locks.update(held)
        for stmt in node.body:
            self.visit(stmt)
        for h in held:
            self.locks.discard(h)

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # a closure runs later, on whatever thread calls it: the lock the
        # enclosing method holds is NOT held there
        lock, _hot = self.src.def_annotation(node)
        entry = frozenset([lock]) if lock else frozenset()
        inner = _MethodWalker(self.src, self.cls,
                              f"{self.method}.{node.name}", entry, self.writes)
        for stmt in node.body:
            inner.visit(stmt)
        self._annotations.update(inner._annotations)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass  # no statements, no writes

    # -- writes ----------------------------------------------------------
    def _record(self, node: ast.AST, attr: str) -> None:
        if _is_lock_name(attr):
            return  # the locks themselves are assigned freely in __init__
        self.writes.setdefault(attr, []).append(_Write(
            f"{self.cls}.{self.method}", getattr(node, "lineno", 0),
            frozenset(self.locks), self.in_init))
        ann = self.src.guarded_by.get(getattr(node, "lineno", -1))
        if ann:
            self._annotations[attr] = ann

    def _record_target(self, node: ast.AST, target: ast.AST) -> None:
        if isinstance(target, (ast.Tuple, ast.List)):
            for e in target.elts:
                self._record_target(node, e)
            return
        base = self_attr_base(target)
        if base is not None:
            self._record(node, base)

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            self._record_target(node, t)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._record_target(node, node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._record_target(node, node.target)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for t in node.targets:
            self._record_target(node, t)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        fn = node.func
        if isinstance(fn, ast.Attribute) and fn.attr in MUTATOR_ATTRS:
            base = self_attr_base(fn.value)
            if base is not None:
                self._record(node, base)
        self.generic_visit(node)


class LockDisciplineRule(Rule):
    id = "SA002"
    title = "guarded attribute mutated outside its lock"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        findings: List[Finding] = []
        for cls in [n for n in ast.walk(src.tree)
                    if isinstance(n, ast.ClassDef)]:
            findings.extend(self._check_class(src, cls))
        return iter(findings)

    def _check_class(self, src: SourceFile, cls: ast.ClassDef) -> List[Finding]:
        writes: Dict[str, List[_Write]] = {}
        annotations: Dict[str, str] = {}
        for item in cls.body:
            if not isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            lock, _hot = src.def_annotation(item)
            if lock:
                entry = frozenset([lock])
            elif item.name.endswith("_locked"):
                # `_locked` naming convention: caller holds *a* lock; which
                # one is not recoverable statically, so trust the name
                entry = frozenset([ALL_LOCKS])
            else:
                entry = frozenset()
            walker = _MethodWalker(src, cls.name, item.name, entry, writes)
            for stmt in item.body:
                walker.visit(stmt)
            annotations.update(walker._annotations)

        out: List[Finding] = []
        for attr, ws in sorted(writes.items()):
            live = [w for w in ws if not w.in_init]
            if not live:
                continue
            if attr in annotations:
                lock = annotations[attr]
                for w in live:
                    if lock not in w.locks and ALL_LOCKS not in w.locks:
                        out.append(Finding(
                            self.id, src.relpath, w.line, w.qualname,
                            f"`self.{attr}` is `# guarded-by: {lock}` but "
                            f"written without holding it"))
                continue
            inside = [w for w in live if w.locks]
            outside = [w for w in live
                       if not w.locks and ALL_LOCKS not in w.locks]
            if inside and outside:
                lock_names = sorted({l for w in inside for l in w.locks
                                     if l != ALL_LOCKS})
                for w in outside:
                    out.append(Finding(
                        self.id, src.relpath, w.line, w.qualname,
                        f"`self.{attr}` is written under "
                        f"{'/'.join(lock_names) or 'a lock'} elsewhere but "
                        f"mutated here without it"))
        return out


# ------------------------------------------------------------------ SA003

# hard-impurity tables live in callgraph.py (the interprocedural
# extractor shares them); re-exported here so fixtures/tests keep one
# import path
from .callgraph import CTYPES_ALLOC, RANDOM_ROOTS, WALLCLOCK_CALLS  # noqa: E402
# Observability in a hot path must go through the gated helpers (they are
# no-ops when tracing/metrics are off); constructing/looking-up a metric
# or span object per call defeats the gate and allocates in the hot loop.
OBSERVABILITY_ALLOWED = {"phase_timer", "expensive_timer", "span", "mint"}
OBSERVABILITY_FLAGGED = {
    "timer", "histogram", "meter", "get_or_register_timer",
    "get_or_register_meter", "get_or_register_gauge", "Timer", "Histogram",
    "Meter", "Span", "Tracer", "start_span",
}
# Every call that takes a metric/span NAME as an argument: an f-string
# there allocates a fresh string per call AND defeats the registry's
# name-keyed lookup — even through the gated helpers. Trace-id formatting
# belongs in tracectx.mint (gated, %-formatted, off the hot path).
OBSERVABILITY_NAME_CALLS = OBSERVABILITY_ALLOWED | OBSERVABILITY_FLAGGED | {
    "counter", "gauge", "observe_slo", "count_drop",
    "get_or_register_counter",
}


class HotPathPurityRule(Rule):
    id = "SA003"
    title = "hot-path function is impure per call"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        rule = self
        findings: List[Finding] = []

        class V(QualnameVisitor):
            def _visit_func(self, node) -> None:
                _lock, hot = src.def_annotation(node)
                if hot:
                    self._stack.append(node.name)
                    qn = self.qualname
                    for sub in ast.walk(node):
                        msg = rule._impurity(sub)
                        if msg:
                            findings.append(rule.finding(src, sub, qn, msg))
                    self._stack.pop()
                else:
                    QualnameVisitor._visit_func(self, node)

            visit_FunctionDef = _visit_func
            visit_AsyncFunctionDef = _visit_func

        V().visit(src.tree)
        return iter(findings)

    def _impurity(self, node: ast.AST) -> Optional[str]:
        if not isinstance(node, ast.Call):
            return None
        name = dotted(node.func)
        if name is None:
            # `(ctypes.c_uint8 * n)(...)` — array-type instantiation
            if isinstance(node.func, ast.BinOp):
                parts = " ".join(filter(None, (dotted(node.func.left),
                                               dotted(node.func.right))))
                if "ctypes" in parts or ".c_" in parts or parts.startswith("c_"):
                    return ("allocates a ctypes buffer per call — hoist it "
                            "(see the PR-2 keccak buffer hoist)")
            return None
        if name in WALLCLOCK_CALLS:
            return f"reads wall-clock (`{name}`) inside a hot path"
        if any(name.startswith(r) for r in RANDOM_ROOTS):
            return f"draws randomness (`{name}`) inside a hot path"
        if name in CTYPES_ALLOC:
            return (f"allocates a ctypes buffer per call (`{name}`) — "
                    f"hoist it out of the hot loop")
        last = name.rsplit(".", 1)[-1]
        if last in OBSERVABILITY_NAME_CALLS and any(
                isinstance(a, ast.JoinedStr) for a in node.args):
            return (f"builds a metric/span name with an f-string per call "
                    f"(`{name}`) inside a hot path — hoist the formatted "
                    f"name out of the loop; trace ids come from the gated "
                    f"tracectx.mint helper, not inline formatting")
        if last in OBSERVABILITY_FLAGGED and last not in OBSERVABILITY_ALLOWED:
            return (f"constructs a metric/span per call (`{name}`) inside a "
                    f"hot path — hoist the registry lookup to module scope, "
                    f"or use the gated phase_timer/expensive_timer/span "
                    f"helpers")
        return None

    # -- interprocedural promotion ---------------------------------------
    # A `# hot-path` marker covers the whole call tree, not one frame:
    # a helper that reads the wall clock is just as impure when reached
    # through two calls.  Transitive callees are held to the HARD subset
    # only (wall clock / randomness / ctypes alloc) — the observability
    # style checks stay single-file, where the hot marker is visible.
    # Exempt: the gated observability packages themselves, and the
    # cooperative-deadline checkpoint (its monotonic read at EVM frame
    # entry is the sanctioned PR-7 design — never in step loops).
    HOT_REACH_EXEMPT = (
        "coreth_tpu/metrics/",
        "coreth_tpu/fault/",
        "coreth_tpu/log.py",
        "coreth_tpu/utils/deadline.py",
    )

    def finalize_program(self, program) -> Iterator[Finding]:
        seeds = sorted(k for k, n in program.funcs.items() if n.rec.hot)
        if not seeds:
            return
        seen = program.reachable(seeds, skip=self.HOT_REACH_EXEMPT)
        for key in sorted(seen):
            parent, _line = seen[key]
            if parent is None:
                continue  # the seed itself — the single-file pass owns it
            node = program.funcs[key]
            if not node.rec.impure:
                continue
            chain = " -> ".join(program.chain_to(seen, key))
            for site in node.rec.impure:
                yield Finding(
                    self.id, node.relpath, site.line, node.rec.qualname,
                    f"{site.kind} (`{site.name}`) reached from a "
                    f"# hot-path function: {chain}")


# ------------------------------------------------------------------ SA004

# Where bit-exactness is the product.  Device-orchestration files under
# trie/ (resident_mirror, planned) keep float *timings*; their roots are
# verified bit-exact against the host path elsewhere, so they are listed
# out of scope rather than baselined line-by-line.
CONSENSUS_FLOAT_PATHS = (
    "coreth_tpu/trie/", "coreth_tpu/rlp.py", "coreth_tpu/evm/gas.py",
    "coreth_tpu/params/", "coreth_tpu/core/types.py",
    "coreth_tpu/bintrie/",
    # the mesh helpers feed the real commit path now (resident-mesh-
    # devices): sharded digests are consensus bytes
    "coreth_tpu/parallel/",
)
CONSENSUS_FLOAT_EXCLUDE = (
    "coreth_tpu/trie/resident_mirror.py", "coreth_tpu/trie/planned.py",
    "coreth_tpu/trie/triedb.py",
)


def _in_scope(relpath: str, paths, exclude=()) -> bool:
    if any(relpath == e or relpath.startswith(e) for e in exclude):
        return False
    return any(relpath == p or relpath.startswith(p) for p in paths)


class ConsensusFloatRule(Rule):
    id = "SA004"
    title = "float arithmetic in a bit-exact module"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if not _in_scope(src.relpath, CONSENSUS_FLOAT_PATHS,
                         CONSENSUS_FLOAT_EXCLUDE):
            return iter(())
        rule = self
        findings: List[Finding] = []

        class V(QualnameVisitor):
            def visit_Constant(self, node: ast.Constant) -> None:
                if isinstance(node.value, float):
                    findings.append(rule.finding(
                        src, node, self.qualname,
                        f"float literal {node.value!r} in a consensus "
                        f"module (bit-exactness)"))

            def visit_BinOp(self, node: ast.BinOp) -> None:
                if isinstance(node.op, ast.Div):
                    findings.append(rule.finding(
                        src, node, self.qualname,
                        "true division `/` yields float — use `//` in "
                        "consensus arithmetic"))
                self.generic_visit(node)

            def visit_Call(self, node: ast.Call) -> None:
                name = dotted(node.func)
                if name == "float" or (name or "").startswith("math."):
                    findings.append(rule.finding(
                        src, node, self.qualname,
                        f"`{name}` produces floats in a consensus module"))
                self.generic_visit(node)

        V().visit(src.tree)
        return iter(findings)


# ------------------------------------------------------------------ SA005

UNORDERED_ITER_PATHS = CONSENSUS_FLOAT_PATHS + (
    "coreth_tpu/state/statedb.py", "coreth_tpu/state/snapshot.py",
    "coreth_tpu/trie/resident_mirror.py", "coreth_tpu/trie/planned.py",
    "coreth_tpu/trie/triedb.py", "coreth_tpu/core/parallel_exec.py",
)
ITER_UNWRAP = {"list", "tuple", "iter", "enumerate", "reversed"}
SET_BINOPS = (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)


class UnorderedIterationRule(Rule):
    id = "SA005"
    title = "set-order-dependent iteration feeding RLP/hashing"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if not _in_scope(src.relpath, UNORDERED_ITER_PATHS):
            return iter(())
        rule = self
        findings: List[Finding] = []

        class V(QualnameVisitor):
            def __init__(self):
                super().__init__()
                self._set_locals: List[Set[str]] = [set()]

            def _visit_func(self, node) -> None:
                self._set_locals.append(set())
                QualnameVisitor._visit_func(self, node)
                self._set_locals.pop()

            visit_FunctionDef = _visit_func
            visit_AsyncFunctionDef = _visit_func

            def visit_Assign(self, node: ast.Assign) -> None:
                if rule._is_set_expr(node.value, self._set_locals[-1]):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self._set_locals[-1].add(t.id)
                else:
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self._set_locals[-1].discard(t.id)
                self.generic_visit(node)

            def _check_iter(self, it: ast.AST, where: ast.AST) -> None:
                if rule._is_set_expr(it, self._set_locals[-1], unwrap=True):
                    findings.append(rule.finding(
                        src, where, self.qualname,
                        "iterating a set here is not reproducible across "
                        "processes (salted hashes) — wrap in sorted()"))

            def visit_For(self, node: ast.For) -> None:
                self._check_iter(node.iter, node)
                self.generic_visit(node)

            def _visit_comp(self, node) -> None:
                for gen in node.generators:
                    self._check_iter(gen.iter, node)
                self.generic_visit(node)

            visit_ListComp = _visit_comp
            visit_SetComp = _visit_comp
            visit_DictComp = _visit_comp
            visit_GeneratorExp = _visit_comp

        V().visit(src.tree)
        return iter(findings)

    def _is_set_expr(self, node: ast.AST, set_locals: Set[str],
                     unwrap: bool = False) -> bool:
        if unwrap:
            while (isinstance(node, ast.Call)
                   and dotted(node.func) in ITER_UNWRAP and node.args):
                node = node.args[0]
        if isinstance(node, (ast.Set, ast.SetComp)):
            return True
        if isinstance(node, ast.Call):
            name = dotted(node.func)
            if name in ("set", "frozenset"):
                return True
            # dict-view algebra (`a.keys() - b.keys()`) returns a set
            return False
        if isinstance(node, ast.Name):
            return node.id in set_locals
        if isinstance(node, ast.BinOp) and isinstance(node.op, SET_BINOPS):
            return (self._is_set_expr(node.left, set_locals)
                    or self._is_set_expr(node.right, set_locals)
                    or self._is_keys_call(node.left)
                    or self._is_keys_call(node.right))
        return False

    @staticmethod
    def _is_keys_call(node: ast.AST) -> bool:
        return (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("keys", "items"))


# ------------------------------------------------------------------ SA006

# The one sanctioned home for a raw sleep: fault.Backoff centralizes
# retry pacing (capped exponential + jitter) so chaos tests can reason
# about every wait in the system.
SLEEP_EXEMPT_PATHS = ("coreth_tpu/fault/",)
FAILPOINT_FUNCS = {"register", "failpoint"}


class FailpointHygieneRule(Rule):
    """Failpoint names are part of the debug/chaos API surface: they must
    be unique string literals registered at import time so
    `debug_listFailpoints` is the complete, greppable catalogue and an
    env spec can never silently name a point that does not exist.  The
    companion check bans naked `time.sleep` outside the fault package —
    ad-hoc sleeps are unbounded, unjittered, and invisible to the
    degradation ladder (use `fault.Backoff`)."""

    id = "SA006"
    title = "failpoint hygiene / naked time.sleep"

    def __init__(self):
        # cross-file state, fed by absorb() (directly or replayed from
        # the cache) and reported in finalize(); check() only stashes
        # the current file's events for summarize() to hand back
        self._pending: List[Tuple] = []
        self._events: List[Tuple[str, Tuple]] = []  # (relpath, event)

    def summarize(self, src: SourceFile):
        events, self._pending = self._pending, []
        return events or None

    def absorb(self, relpath: str, summary) -> None:
        for ev in summary:
            self._events.append((relpath, ev))

    def check(self, src: SourceFile) -> Iterator[Finding]:
        rule = self
        findings: List[Finding] = []
        # alias maps for this file: local-name -> canonical function
        fp_aliases: Dict[str, str] = {}   # e.g. {"register": "register"}
        mod_aliases: Set[str] = set()     # modules exposing .register/.failpoint
        sleep_names: Set[str] = set()     # `from time import sleep [as x]`
        sleep_ok = any(src.relpath == p or src.relpath.startswith(p)
                       for p in SLEEP_EXEMPT_PATHS)

        for node in ast.walk(src.tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod == "fault" or mod.endswith(".fault"):
                    for a in node.names:
                        if a.name in FAILPOINT_FUNCS:
                            fp_aliases[a.asname or a.name] = a.name
                if mod == "time":
                    for a in node.names:
                        if a.name == "sleep":
                            sleep_names.add(a.asname or a.name)
                for a in node.names:  # `from .. import fault [as f]`
                    if a.name == "fault":
                        mod_aliases.add(a.asname or a.name)
            elif isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "fault" or a.name.endswith(".fault"):
                        mod_aliases.add(a.asname or a.name.split(".")[0])

        def resolve(call: ast.Call) -> Optional[str]:
            """Canonical 'register'/'failpoint' if this call targets the
            fault package through any import shape, else None."""
            fn = call.func
            if isinstance(fn, ast.Name):
                return fp_aliases.get(fn.id)
            if isinstance(fn, ast.Attribute) and fn.attr in FAILPOINT_FUNCS:
                recv = dotted(fn.value)
                if recv is not None and (recv in mod_aliases
                                         or recv.split(".")[-1] == "fault"):
                    return fn.attr
            return None

        class V(QualnameVisitor):
            def visit_Call(self, node: ast.Call) -> None:
                name = dotted(node.func)
                if not sleep_ok and (
                        name == "time.sleep"
                        or (isinstance(node.func, ast.Name)
                            and node.func.id in sleep_names)):
                    findings.append(rule.finding(
                        src, node, self.qualname,
                        "naked time.sleep — retry pacing goes through "
                        "fault.Backoff (capped exponential + jitter), "
                        "visible to chaos tooling"))
                which = resolve(node)
                if which is not None:
                    findings.extend(
                        rule._check_failpoint_call(src, node, self.qualname,
                                                   which))
                self.generic_visit(node)

        V().visit(src.tree)
        return iter(findings)

    def _check_failpoint_call(self, src: SourceFile, node: ast.Call,
                              qualname: str, which: str) -> List[Finding]:
        out: List[Finding] = []
        arg = node.args[0] if node.args else None
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            out.append(self.finding(
                src, node, qualname,
                f"`{which}(...)` needs a literal string name — computed "
                f"names defeat the greppable failpoint catalogue"))
            return out
        name = arg.value
        if which == "register":
            if qualname != "<module>":
                out.append(self.finding(
                    src, node, qualname,
                    f"failpoint {name!r} registered inside {qualname} — "
                    f"registration must run at import (module scope) so "
                    f"debug_listFailpoints is complete at boot"))
            self._pending.append(("reg", name, getattr(node, "lineno", 0),
                                  qualname))
        else:
            self._pending.append(("fire", name, getattr(node, "lineno", 0),
                                  qualname))
        return out

    def finalize(self) -> Iterator[Finding]:
        registered: Dict[str, Tuple[str, str]] = {}
        fired: List[Tuple[str, str, int, str]] = []
        for relpath, (kind, name, line, qualname) in self._events:
            if kind == "reg":
                prior = registered.get(name)
                if prior is not None and prior != (relpath, qualname):
                    yield Finding(
                        self.id, relpath, line, qualname,
                        f"failpoint {name!r} already registered at "
                        f"{prior[0]} [{prior[1]}] — names are global and "
                        f"must be unique")
                else:
                    registered[name] = (relpath, qualname)
            else:
                fired.append((name, relpath, line, qualname))
        for name, path, line, qualname in fired:
            if name not in registered:
                yield Finding(
                    self.id, path, line, qualname,
                    f"failpoint({name!r}) fires a name no module "
                    f"registers — arm via debug_setFailpoint would "
                    f"KeyError; add a module-scope register()")


# ------------------------------------------------------------------ SA007

# The serving tier's overload story *is* its bounded queues (PR 7,
# ROBUSTNESS.md "Serving under overload"): an unbounded queue or an
# uncapped worker source in a request-serving module quietly
# reintroduces collapse-under-saturation. Only modules that accept or
# fan out remote work are listed: peer/ (gossip + request fan-out pools)
# and sync/ (segment + hedge pools driven by remote responses) joined in
# PR 9 — a Byzantine peer set must not be able to balloon either.
# core/insert_pipeline.py joins in PR 13: its stage queue IS the
# pipeline depth bound — an unbounded queue there would let speculation
# run arbitrarily far ahead of commit.
# ethdb/ joins in PR 15 (storage fault armor): the degraded read-only
# rung keeps reads serving while writes fail, so the storage boundary
# is itself a serving path — a retry queue or helper pool growing
# without bound under persistent disk failure would turn a survivable
# fault into a memory-pressure collapse.
SERVING_PATHS = (
    "coreth_tpu/rpc/",
    "coreth_tpu/vm/api.py",
    "coreth_tpu/eth/filters.py",
    "coreth_tpu/metrics/http.py",
    "coreth_tpu/peer/",
    "coreth_tpu/sync/",
    "coreth_tpu/core/insert_pipeline.py",
    "coreth_tpu/ethdb/",
)
_QUEUE_CTORS = {"Queue", "LifoQueue", "PriorityQueue"}


class ServingBoundednessRule(Rule):
    """Serving-path modules must construct only *bounded* work buffers:
    `queue.Queue()` with no maxsize (or maxsize=0) is unbounded, as is
    `SimpleQueue()`; a `ThreadPoolExecutor()` without max_workers sizes
    itself from the host, not from an admission budget. Genuinely
    justified cases go in the baseline with a reason."""

    id = "SA007"
    title = "unbounded queue/executor in serving path"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if not any(src.relpath == p or src.relpath.startswith(p)
                   for p in SERVING_PATHS):
            return iter(())
        rule = self
        findings: List[Finding] = []
        queue_names: Set[str] = set()   # bare names bound to queue ctors
        simple_names: Set[str] = set()  # bare names for SimpleQueue
        exec_names: Set[str] = set()    # bare names for ThreadPoolExecutor
        for node in ast.walk(src.tree):
            if isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if mod == "queue":
                    for a in node.names:
                        if a.name in _QUEUE_CTORS:
                            queue_names.add(a.asname or a.name)
                        elif a.name == "SimpleQueue":
                            simple_names.add(a.asname or a.name)
                elif mod == "concurrent.futures":
                    for a in node.names:
                        if a.name == "ThreadPoolExecutor":
                            exec_names.add(a.asname or a.name)

        def kind_of(call: ast.Call) -> Optional[str]:
            name = dotted(call.func)
            if name is None:
                return None
            head, _, _ = name.partition(".")
            last = name.split(".")[-1]
            if name in queue_names or (head == "queue"
                                       and last in _QUEUE_CTORS):
                return "queue"
            if name in simple_names or (head == "queue"
                                        and last == "SimpleQueue"):
                return "simple"
            if name in exec_names or last == "ThreadPoolExecutor":
                return "executor"
            return None

        def bound_arg(call: ast.Call, kw: str) -> Optional[ast.AST]:
            if call.args:
                return call.args[0]
            for k in call.keywords:
                if k.arg == kw:
                    return k.value
            return None

        class V(QualnameVisitor):
            def visit_Call(self, node: ast.Call) -> None:
                kind = kind_of(node)
                if kind == "simple":
                    findings.append(rule.finding(
                        src, node, self.qualname,
                        "SimpleQueue is always unbounded — serving paths "
                        "use a bounded queue.Queue(maxsize=...) so a full "
                        "buffer sheds instead of growing"))
                elif kind == "queue":
                    arg = bound_arg(node, "maxsize")
                    unbounded = arg is None or (
                        isinstance(arg, ast.Constant) and arg.value == 0)
                    if unbounded:
                        findings.append(rule.finding(
                            src, node, self.qualname,
                            "unbounded queue in a serving module "
                            "(maxsize absent or 0) — bounded admission "
                            "queues are the overload control; pass a "
                            "positive maxsize or baseline with a reason"))
                elif kind == "executor":
                    arg = bound_arg(node, "max_workers")
                    if arg is None or (isinstance(arg, ast.Constant)
                                       and arg.value is None):
                        findings.append(rule.finding(
                            src, node, self.qualname,
                            "ThreadPoolExecutor without max_workers sizes "
                            "itself from the host — serving-path "
                            "concurrency comes from an explicit budget"))
                self.generic_visit(node)

        V().visit(src.tree)
        return iter(findings)


# ------------------------------------------------------------------ SA008

# Commitment-backend isolation (COMMITMENT.md): the MPT and the bintrie
# implementations sit behind the state/commitment.py seam and may not
# import each other — in either direction, by absolute or relative
# import. Shared machinery goes through the interface or scheme-agnostic
# layers (ops/, metrics/, native). The seam module itself is exempt: it
# exists to know both.
BACKEND_ISOLATION = (
    # (package whose files are checked, banned import prefix)
    ("coreth_tpu/bintrie/", "coreth_tpu.trie"),
    ("coreth_tpu/trie/", "coreth_tpu.bintrie"),
)


class BackendIsolationRule(Rule):
    id = "SA008"
    title = "commitment backend reaches around the interface"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        findings: List[Finding] = []
        for pkg, banned in BACKEND_ISOLATION:
            if not _in_scope(src.relpath, (pkg,)):
                continue
            # module path of this file, for resolving relative imports:
            # "coreth_tpu/bintrie/tree.py" -> [coreth_tpu, bintrie, tree]
            parts = src.relpath[:-3].split("/")
            if parts[-1] == "__init__":
                parts = parts[:-1]
            for node in ast.walk(src.tree):
                if isinstance(node, ast.Import):
                    for alias in node.names:
                        self._flag(findings, src, node, alias.name, banned)
                elif isinstance(node, ast.ImportFrom):
                    if node.level == 0:
                        full = node.module or ""
                    else:
                        base = parts[: len(parts) - node.level]
                        full = ".".join(
                            base + ([node.module] if node.module else []))
                    self._flag(findings, src, node, full, banned)
        return iter(findings)

    def _flag(self, findings, src, node, module: str, banned: str) -> None:
        if module == banned or module.startswith(banned + "."):
            findings.append(self.finding(
                src, node, "<module>",
                f"imports {module} across the commitment-backend "
                f"boundary — go through state/commitment.py instead"))


# ------------------------------------------------------------------ SA009

# Deterministic commit (PERF.md r9): the optimistic executor may finish
# transactions in any order, but the fold that applies write-sets to the
# real StateDB is the consensus boundary — it must walk the versioned
# results strictly in tx-index order. A loop over a dict of completion
# events or a worker-local list would be timing-dependent and fork the
# state root. Enforced structurally: inside fold-named functions in the
# executor, every for-loop (and comprehension) iterates an explicitly
# ordered source — range()/sorted(), optionally wrapped in enumerate/
# list/tuple — never a raw container or set.
FOLD_ORDER_PATHS = ("coreth_tpu/core/parallel_exec.py",)
FOLD_ORDER_WRAPPERS = {"enumerate", "list", "tuple", "iter"}
FOLD_ORDER_SOURCES = {"range", "sorted"}


class FoldOrderRule(Rule):
    id = "SA009"
    title = "fold-step iteration must be tx-index ordered"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if src.relpath not in FOLD_ORDER_PATHS:
            return iter(())
        rule = self
        findings: List[Finding] = []

        class V(QualnameVisitor):
            def __init__(self):
                super().__init__()
                self._fold_depth = 0

            def _visit_func(self, node) -> None:
                folding = "fold" in node.name
                self._fold_depth += folding
                QualnameVisitor._visit_func(self, node)
                self._fold_depth -= folding

            visit_FunctionDef = _visit_func
            visit_AsyncFunctionDef = _visit_func

            def _check_iter(self, it: ast.AST, where: ast.AST) -> None:
                if self._fold_depth and not rule._ordered_iter(it):
                    findings.append(rule.finding(
                        src, where, self.qualname,
                        "fold-step loop must iterate range()/sorted() "
                        "(tx-index order) — container iteration here is "
                        "completion-order and forks the state root"))

            def visit_For(self, node: ast.For) -> None:
                self._check_iter(node.iter, node)
                self.generic_visit(node)

            def _visit_comp(self, node) -> None:
                for gen in node.generators:
                    self._check_iter(gen.iter, node)
                self.generic_visit(node)

            visit_ListComp = _visit_comp
            visit_SetComp = _visit_comp
            visit_DictComp = _visit_comp
            visit_GeneratorExp = _visit_comp

        V().visit(src.tree)
        return iter(findings)

    @staticmethod
    def _ordered_iter(node: ast.AST) -> bool:
        while (isinstance(node, ast.Call)
               and dotted(node.func) in FOLD_ORDER_WRAPPERS and node.args):
            node = node.args[0]
        return (isinstance(node, ast.Call)
                and dotted(node.func) in FOLD_ORDER_SOURCES)


# ------------------------------------------------------------------ SA010

# The lock-free read tier (PR 16, ROBUSTNESS.md "Read-path lock
# discipline"): read-only RPC handler modules resolve heads and state
# against the chain's atomically published ReadView. Touching `chainmu`
# from any of them — directly or by calling a chain method that takes it
# — re-couples read latency to the write pipeline, which is exactly the
# regression the storm bench measures. The list of chainmu-taking chain
# methods is curated (they are few and stable); receiver matching is
# name-based ("chain" in the dotted receiver) so unrelated objects with
# an `accept` method don't trip it.
READ_TIER_PATHS = (
    "coreth_tpu/eth/api.py",
    "coreth_tpu/eth/filters.py",
    "coreth_tpu/eth/gasprice.py",
    "coreth_tpu/eth/backend.py",
)
CHAINMU_TAKING_METHODS = {
    "insert_block", "insert_block_manual", "accept", "reject",
    "set_preference", "last_consensus_accepted_block",
}


class ReadTierLockRule(Rule):
    """Read-only RPC handlers must be chainmu-free: no `chainmu`
    attribute access (with-statements, acquire/release, passing the lock
    around) and no calls to the curated chainmu-taking chain methods.
    Justified exceptions go in the baseline with a reason."""

    id = "SA010"
    title = "read-tier module touches chainmu"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if src.relpath not in READ_TIER_PATHS:
            return iter(())
        rule = self
        findings: List[Finding] = []

        class V(QualnameVisitor):
            def visit_Attribute(self, node: ast.Attribute) -> None:
                if node.attr == "chainmu":
                    findings.append(rule.finding(
                        src, node, self.qualname,
                        "read-tier module touches `chainmu` — read-only "
                        "RPC paths resolve against chain.read_view(), "
                        "never the write path's lock"))
                self.generic_visit(node)

            def visit_Call(self, node: ast.Call) -> None:
                fn = node.func
                if (isinstance(fn, ast.Attribute)
                        and fn.attr in CHAINMU_TAKING_METHODS):
                    recv = dotted(fn.value) or ""
                    if "chain" in recv.lower():
                        findings.append(rule.finding(
                            src, node, self.qualname,
                            f"read-tier module calls chainmu-taking "
                            f"`{recv}.{fn.attr}()` — reads must not "
                            f"enter the write path"))
                self.generic_visit(node)

        V().visit(src.tree)
        return iter(findings)

    # -- interprocedural promotion ---------------------------------------
    # The single-file pass only sees `chainmu` named IN the read tier; a
    # read-tier entry that calls a helper in core/ that takes chainmu is
    # the same bug one hop removed.  BFS every read-tier function's
    # transitive callees; flag any reached function that acquires
    # `BlockChain.chainmu` or IS one of the curated chainmu-taking chain
    # methods.  Findings anchor at the read-tier entry (stable baseline
    # key inside eth/), with the full call chain in the message.
    def finalize_program(self, program) -> Iterator[Finding]:
        entries = sorted(k for k, n in program.funcs.items()
                         if n.relpath in READ_TIER_PATHS)
        if not entries:
            return
        seen = program.reachable(entries)
        for key in sorted(seen):
            node = program.funcs[key]
            if node.relpath in READ_TIER_PATHS:
                continue  # direct uses are the single-file rule's job
            if any(lock == "BlockChain.chainmu"
                   for lock, _l, _h, _s in node.acquires):
                culprit = f"`{node.rec.qualname}` acquires `chainmu`"
            elif (node.rec.cls == "BlockChain"
                    and node.rec.name in CHAINMU_TAKING_METHODS):
                culprit = (f"`BlockChain.{node.rec.name}` is a curated "
                           f"chainmu-taking method")
            else:
                continue
            root = key
            while seen[root][0] is not None:
                root = seen[root][0]
            entry_node = program.funcs[root]
            chain = " -> ".join(program.chain_to(seen, key))
            yield Finding(
                self.id, entry_node.relpath, entry_node.rec.line,
                entry_node.rec.qualname,
                f"read-tier entry transitively reaches the write path: "
                f"{chain} — {culprit}; reads resolve against "
                f"chain.read_view(), never chainmu")


# ------------------------------------------------------------------ SA011

# Execution-shard workers (core/exec_shards.py) fork long-lived children
# whose import graph is whatever core/shard_worker.py pulls in at module
# scope. Anything mutable that crosses the fork silently diverges from
# the parent: counters bumped into a registry nobody scrapes, a chainmu
# whose other holders don't exist in the child, dicts that look shared
# but aren't. The contract: worker modules keep module scope down to
# stdlib + coreth_tpu.fault (which re-arms itself via child_after_fork),
# never name the metrics registry or the chain lock, hold no module-level
# mutable state, and import the EVM machinery lazily inside handlers —
# pickle-clean and side-effect-free by construction.
SHARD_WORKER_PATHS = (
    "coreth_tpu/core/shard_worker.py",
)
# internal packages a worker file may not import at ANY level — each one
# drags in a parent-process singleton (metrics registry, chain + chainmu)
SHARD_WORKER_BANNED_MODULES = {"metrics", "blockchain"}
# the ONE sanctioned exception inside a banned package:
# metrics/shardstats.py is fork-clean by construction (pure stdlib, no
# registry, no locks, no threads, no module-level mutable state) and
# exists precisely so workers can accumulate telemetry deltas and ship
# them over the pipe instead of bumping parent singletons
SHARD_WORKER_IMPORT_ALLOWLIST = frozenset({"metrics.shardstats"})
# documented exceptions for module-level mutable bindings (none today;
# additions need a reason next to the name)
SHARD_WORKER_MUTABLE_ALLOWLIST: frozenset = frozenset()
_MUTABLE_CTOR_NAMES = {"dict", "list", "set", "bytearray", "defaultdict",
                       "deque", "Counter", "OrderedDict"}


def _worker_allowlist_tail(mod: str) -> str:
    return mod[len("coreth_tpu."):] if mod.startswith("coreth_tpu.") else mod


def _import_is_allowlisted(node: ast.AST) -> bool:
    """True iff the statement imports ONLY allowlisted modules, under any
    spelling: `from ..metrics.shardstats import ShardStats`,
    `from ..metrics import shardstats`, `import
    coreth_tpu.metrics.shardstats`."""
    mods: List[str] = []
    if isinstance(node, ast.Import):
        mods = [a.name for a in node.names]
    elif isinstance(node, ast.ImportFrom):
        base = node.module or ""
        if _worker_allowlist_tail(base) == "metrics":
            # `from ..metrics import X, Y` — each alias is a module
            mods = [f"{base}.{a.name}" for a in node.names]
        else:
            mods = [base]
    if not mods:
        return False
    return all(_worker_allowlist_tail(m) in SHARD_WORKER_IMPORT_ALLOWLIST
               for m in mods)


def _import_segments(node: ast.AST) -> List[str]:
    """All dotted segments named by an import statement."""
    segs: List[str] = []
    if isinstance(node, ast.Import):
        for alias in node.names:
            segs.extend(alias.name.split("."))
    elif isinstance(node, ast.ImportFrom):
        if node.module:
            segs.extend(node.module.split("."))
        # `from .. import fault` names the target in the alias list
        if node.level > 0:
            segs.extend(a.name for a in node.names)
    return segs


def _is_mutable_value(node: ast.AST) -> bool:
    if isinstance(node, (ast.Dict, ast.List, ast.Set,
                         ast.DictComp, ast.ListComp, ast.SetComp)):
        return True
    if isinstance(node, ast.Call):
        name = dotted(node.func) or ""
        return name.split(".")[-1] in _MUTABLE_CTOR_NAMES
    return False


class ShardWorkerIsolationRule(Rule):
    """Shard-worker-importable modules must be fork-clean: no imports of
    the metrics or blockchain packages anywhere in the file, no `chainmu`
    attribute access, no `default_registry`, module-level imports limited
    to stdlib + coreth_tpu.fault, and no module-level mutable bindings
    outside the (empty) allowlist."""

    id = "SA011"
    title = "shard-worker module breaks fork isolation"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if src.relpath not in SHARD_WORKER_PATHS:
            return iter(())
        rule = self
        findings: List[Finding] = []

        def _relative_is_fault_only(node: ast.ImportFrom) -> bool:
            if node.module in (None, ""):
                return all(a.name == "fault" for a in node.names)
            parts = node.module.split(".")
            return parts[0] == "fault"

        # module-scope statements: imports + bindings
        for stmt in src.tree.body:
            if isinstance(stmt, (ast.Import, ast.ImportFrom)):
                relative = (isinstance(stmt, ast.ImportFrom)
                            and stmt.level > 0)
                internal = relative or any(
                    s == "coreth_tpu" for s in _import_segments(stmt))
                ok = (not internal) or (
                    isinstance(stmt, ast.ImportFrom) and relative
                    and _relative_is_fault_only(stmt)) \
                    or _import_is_allowlisted(stmt)
                if not ok:
                    findings.append(rule.finding(
                        src, stmt, "<module>",
                        "shard-worker module imports project code at "
                        "module scope — only stdlib and coreth_tpu.fault "
                        "may load at fork time; import the EVM machinery "
                        "lazily inside the request handler"))
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
                targets = (stmt.targets if isinstance(stmt, ast.Assign)
                           else [stmt.target])
                value = stmt.value
                names = [dotted(t) or "" for t in targets]
                if (value is not None and _is_mutable_value(value)
                        and not all(n in SHARD_WORKER_MUTABLE_ALLOWLIST
                                    for n in names)):
                    findings.append(rule.finding(
                        src, stmt, "<module>",
                        f"module-level mutable binding "
                        f"`{', '.join(names)}` in a shard-worker module "
                        f"— state copied through fork diverges silently; "
                        f"keep it per-request or thread it through the "
                        f"pipe protocol"))

        class V(QualnameVisitor):
            def visit_Import(self, node: ast.Import) -> None:
                self._check_import(node)

            def visit_ImportFrom(self, node: ast.ImportFrom) -> None:
                self._check_import(node)

            def _check_import(self, node: ast.AST) -> None:
                banned = SHARD_WORKER_BANNED_MODULES.intersection(
                    _import_segments(node))
                if banned and not _import_is_allowlisted(node):
                    findings.append(rule.finding(
                        src, node, self.qualname,
                        f"shard-worker module imports "
                        f"`{'`, `'.join(sorted(banned))}` — forked "
                        f"workers must never touch the parent's metrics "
                        f"registry or chain singletons, even lazily"))

            def visit_Attribute(self, node: ast.Attribute) -> None:
                if node.attr == "chainmu":
                    findings.append(rule.finding(
                        src, node, self.qualname,
                        "shard-worker module touches `chainmu` — the "
                        "child's copy of the lock has no other holders; "
                        "workers are lock-free by construction"))
                self.generic_visit(node)

            def visit_Name(self, node: ast.Name) -> None:
                if node.id == "default_registry":
                    findings.append(rule.finding(
                        src, node, self.qualname,
                        "shard-worker module names `default_registry` — "
                        "counts bumped in a forked child are invisible "
                        "to the parent's scrapes; ship facts over the "
                        "pipe instead"))
                self.generic_visit(node)

        V().visit(src.tree)
        return iter(findings)

    # -- interprocedural promotion ---------------------------------------
    # The single-file pass pins shard_worker.py's own module scope; the
    # promotion chases what actually executes in the forked child: every
    # function reachable from the worker via the call graph, every lazy
    # import those functions perform, and the transitive MODULE-SCOPE
    # import closure of every module so pulled in (importing a module
    # executes its module scope, which imports more).  A banned package
    # (metrics, blockchain) anywhere in that closure means the child's
    # import image carries a parent-process singleton — the finding
    # anchors at the chain's root (the import that starts the pull) and
    # renders the full module chain.
    def finalize_program(self, program) -> Iterator[Finding]:
        worker_files = [program.files[rel] for rel in sorted(program.files)
                        if rel in SHARD_WORKER_PATHS]
        if not worker_files:
            return
        worker_keys = sorted(k for k, n in program.funcs.items()
                             if n.relpath in SHARD_WORKER_PATHS)
        seen = program.reachable(worker_keys)

        # module -> (why, (relpath, qualname, line), parent_module|None)
        origin: Dict[str, Tuple[str, Tuple[str, str, int], Optional[str]]] = {}
        queue: List[str] = []

        def add(target: str, why: str, anchor: Tuple[str, str, int],
                parent: Optional[str]) -> None:
            mod = program._nearest_module(target)
            rel = program.modules.get(mod)
            if rel in SHARD_WORKER_PATHS or mod in origin:
                return
            head = mod.rsplit(".", 1)[0]
            if ("." in mod and head in origin
                    and origin[head][1] == anchor):
                # `from X import y` records both X and X.y; when X isn't
                # in the analyzed set, X.y can't be trimmed to a known
                # module — one tracked entry per import is enough
                return
            origin[mod] = (why, anchor, parent)
            queue.append(mod)

        for fg in worker_files:
            for target, line in fg.module_imports:
                add(target, "module-scope import",
                    (fg.relpath, "<module>", line), None)
        for key in sorted(seen):
            node = program.funcs[key]
            for li in node.rec.lazy_imports:
                add(li.module,
                    f"lazy import inside `{node.rec.qualname}` "
                    f"(runs in the forked child)",
                    (node.relpath, node.rec.qualname, li.line), None)
            if node.relpath not in SHARD_WORKER_PATHS:
                parent_key, line = seen[key]
                pnode = (program.funcs[parent_key]
                         if parent_key is not None else node)
                add(node.module,
                    f"defines `{node.rec.qualname}`, called from the "
                    f"worker",
                    (pnode.relpath, pnode.rec.qualname, line), None)
        while queue:
            mod = queue.pop(0)
            rel = program.modules.get(mod)
            if rel is None:
                continue
            for target, line in program.files[rel].module_imports:
                add(target, "module-scope import",
                    (rel, "<module>", line), mod)

        for mod in sorted(origin):
            banned = SHARD_WORKER_BANNED_MODULES.intersection(
                mod.split("."))
            if not banned:
                continue
            tail = _worker_allowlist_tail(mod)
            if any(tail == a or tail.startswith(a + ".")
                   for a in SHARD_WORKER_IMPORT_ALLOWLIST):
                continue
            # walk back to the chain's root for the anchor + witness
            chain: List[str] = []
            cur: Optional[str] = mod
            anchor = origin[mod][1]
            while cur is not None:
                why, anc, parent = origin[cur]
                chain.append(f"{cur} ({why} at {anc[0]}:{anc[2]})")
                anchor = anc
                cur = parent
            chain.reverse()
            yield Finding(
                self.id, anchor[0], anchor[2], anchor[1],
                f"shard-worker import/call closure pulls in `{mod}` "
                f"(banned: {', '.join(sorted(banned))}) — the forked "
                f"child's import image carries a parent singleton: "
                f"{' -> '.join(chain)}")


# ------------------------------------------------------------------ SA012

# The pjit multi-process recipe: on a mesh spanning processes, every
# process runs the same program, and argument placement must be decided
# by the PROGRAM (explicit in/out shardings), never re-inferred per call
# — an inferred placement that differs between chained commits inserts a
# resharding collective between dispatches, which is both the perf bug
# (cross-shard traffic the per-shard absorb just removed) and, across
# processes, a correctness hazard (each process infers from its own
# addressable shards). The commit-path modules therefore pin shardings
# on every jitted entry and never call single-argument device_put.
# A `# sharding:` comment on/above the jit site documents the justified
# exceptions (e.g. the unsharded fallback path).
SHARDING_DISCIPLINE_PATHS = (
    "coreth_tpu/ops/keccak_resident.py",
    "coreth_tpu/parallel/__init__.py",
)
_SHARDING_KWARGS = {"in_shardings", "out_shardings"}
_JIT_NAMES = {"jax.jit", "jit", "jax.pjit", "pjit"}


def _as_jit_call(node: ast.Call) -> Optional[ast.Call]:
    """The Call carrying jit options: [node] itself for `jax.jit(...)`,
    the partial call for `functools.partial(jax.jit, ...)`; None when
    [node] is not a jit entry."""
    name = dotted(node.func) or ""
    if name in _JIT_NAMES:
        return node
    if name.split(".")[-1] == "partial" and node.args:
        inner = dotted(node.args[0]) or ""
        if inner in _JIT_NAMES:
            return node
    return None


class ShardingDisciplineRule(Rule):
    """Mesh commit-path modules must declare jit placement explicitly:
    every `jax.jit` / `functools.partial(jax.jit, ...)` entry needs
    in_shardings AND out_shardings (a `**kwargs` splat is trusted — the
    options were assembled elsewhere), or a `# sharding:` comment
    justifying why placement is out of scope (unsharded fallbacks).
    `device_put` must always carry an explicit placement argument."""

    id = "SA012"
    title = "commit-path jit/device_put without explicit sharding"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if src.relpath not in SHARDING_DISCIPLINE_PATHS:
            return iter(())
        rule = self
        findings: List[Finding] = []

        def annotated(lo: int, hi: int) -> bool:
            # `# sharding: ...` on any line in [lo-2, hi] (same line,
            # the two lines above, or between decorator and def)
            return any("sharding:" in src.comments.get(ln, "")
                       for ln in range(max(1, lo - 2), hi + 1))

        def jit_missing_shardings(call: ast.Call) -> bool:
            names = {kw.arg for kw in call.keywords}
            if None in names:  # **splat: assembled kwargs are trusted
                return False
            return not _SHARDING_KWARGS.issubset(names)

        handled: Set[int] = set()

        class V(QualnameVisitor):
            def _check_decorators(self, node) -> None:
                for dec in node.decorator_list:
                    lo = min(d.lineno for d in node.decorator_list)
                    if isinstance(dec, ast.Call):
                        call = _as_jit_call(dec)
                        if call is None:
                            continue
                        handled.add(id(dec))
                        if (jit_missing_shardings(call)
                                and not annotated(lo, node.lineno)):
                            findings.append(rule.finding(
                                src, dec, self.qualname,
                                f"jitted entry `{node.name}` declares no "
                                f"in_shardings/out_shardings — pin both "
                                f"(or justify with a `# sharding:` "
                                f"comment): inferred placement reshards "
                                f"chained commits across processes"))
                    elif (dotted(dec) or "") in _JIT_NAMES:
                        if not annotated(lo, node.lineno):
                            findings.append(rule.finding(
                                src, dec, self.qualname,
                                f"bare @jit on `{node.name}` — pin "
                                f"in_shardings/out_shardings (or justify "
                                f"with a `# sharding:` comment)"))

            def visit_FunctionDef(self, node) -> None:
                self._check_decorators(node)
                QualnameVisitor.visit_FunctionDef(self, node)

            def visit_AsyncFunctionDef(self, node) -> None:
                self._check_decorators(node)
                QualnameVisitor.visit_AsyncFunctionDef(self, node)

            def visit_Call(self, node: ast.Call) -> None:
                name = dotted(node.func) or ""
                if id(node) not in handled:
                    call = _as_jit_call(node)
                    if (call is not None and jit_missing_shardings(call)
                            and not annotated(node.lineno, node.lineno)):
                        findings.append(rule.finding(
                            src, node, self.qualname,
                            "jit call without in_shardings/out_shardings "
                            "— pin both (or justify with a `# sharding:` "
                            "comment)"))
                    if (name.split(".")[-1] == "device_put"
                            and len(node.args) < 2 and not node.keywords
                            and not annotated(node.lineno, node.lineno)):
                        findings.append(rule.finding(
                            src, node, self.qualname,
                            "single-argument device_put on the commit "
                            "path — implicit placement reshards; pass an "
                            "explicit Sharding (replicated for uploads)"))
                self.generic_visit(node)

        V().visit(src.tree)
        return iter(findings)


# ------------------------------------------------------------------ SA013

class LockOrderRule(Rule):
    """Global lock-order deadlock lint.  The linker canonicalizes every
    `with <lock>` / `.acquire()` site and every `# guarded-by:` entry
    annotation to an owner-qualified lock identity, propagates
    may-acquire sets through the call graph, and builds the lock-order
    edge set (`held -> acquired-under-it`).  A cycle in that graph is a
    potential AB/BA deadlock: two threads entering the cycle from
    different locks can block each other forever.  The finding carries
    the full witness — the function chain, with files and lines, for
    every edge of the cycle.  Reentrant re-acquisition of a held RLock
    is not an edge (no self-edges), and a lock whose identity cannot be
    resolved (generic attr name through an untyped receiver) is dropped
    from the graph rather than risk a bogus unification cycle.

    The acyclic order this rule certifies is mirrored at runtime by
    `coreth_tpu.utils.racecheck.CANONICAL_LOCK_ORDER` (the lock-order
    witness asserts observed acquisitions against it under the chaos
    conductor); tests/test_static_analysis.py pins the two against each
    other."""

    id = "SA013"
    title = "lock-order cycle (potential deadlock)"

    def check(self, src: SourceFile) -> Iterator[Finding]:
        return iter(())

    def finalize_program(self, program) -> Iterator[Finding]:
        for cycle in program.lock_cycles():
            key, line, _action = cycle.edges[0].witness[0]
            node = program.funcs[key]
            yield Finding(
                self.id, node.relpath, line, node.rec.qualname,
                "lock-order cycle (potential deadlock):\n  "
                + cycle.render(program.funcs).replace("\n", "\n  "))


# ------------------------------------------------------------------ SA014

# The /metrics exposition sanitizes every registry name down to
# `[a-zA-Z_][a-zA-Z0-9_]*` — two registry names that differ only in
# separator characters silently COLLIDE into one exposition family, and
# a name registered as a counter in one module and a gauge in another
# raises at runtime only when the second call site finally executes.
# The namespace grammar that keeps both failure modes impossible:
# lower-case `[a-z0-9_/]` with `/` as the hierarchy separator (the
# go-metrics convention every existing family follows).  metrics/ itself
# is exempt: the sanitizer tests and the synthetic --check registry
# exercise hostile names on purpose, and racecheck's lock/<canonical>
# families (which legally carry `.`/`:`) are registered through the
# metrics-adjacent telemetry helpers documented in OBSERVABILITY.md.
METRICS_FAMILY_RE_SRC = r"^[a-z0-9_/]+$"
_METRICS_FAMILY_RE = re.compile(METRICS_FAMILY_RE_SRC)
_METRICS_FAMILY_CHARSET = frozenset("abcdefghijklmnopqrstuvwxyz0123456789_/")
_METRICS_CTOR_METHODS = ("counter", "gauge", "meter", "timer", "histogram")
_METRICS_EXEMPT_PREFIXES = ("coreth_tpu/metrics/", "coreth_tpu/utils/racecheck")


def _metric_name_parts(node: ast.AST):
    """(kind, literal_fragments) for a metric name argument: kind is
    'literal' (whole name known), 'fragments' (f-string / concat — only
    the constant pieces are checkable), or None (pure variable)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return "literal", [node.value]
    if isinstance(node, ast.JoinedStr):
        frags = [v.value for v in node.values
                 if isinstance(v, ast.Constant) and isinstance(v.value, str)]
        return "fragments", frags
    if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
        frags: List[str] = []
        for side in (node.left, node.right):
            kind, sub = _metric_name_parts(side)
            if kind == "literal":
                frags.extend(sub)
            elif kind == "fragments":
                frags.extend(sub)
        return "fragments", frags
    return None, []


class MetricsFamilyRule(Rule):
    """Registry names created outside metrics/ must follow the
    `^[a-z0-9_/]+$` namespace grammar, and one family name must never be
    registered under two different metric types anywhere in the repo."""

    id = "SA014"
    title = "metric family name breaks the namespace grammar"

    def __init__(self):
        # name -> {metric type -> (relpath, qualname, line)} across files
        self._families: Dict[str, Dict[str, Tuple[str, str, int]]] = {}

    def check(self, src: SourceFile) -> Iterator[Finding]:
        if src.relpath.startswith(_METRICS_EXEMPT_PREFIXES):
            return iter(())
        rule = self
        findings: List[Finding] = []

        class V(QualnameVisitor):
            def visit_Call(self, node: ast.Call) -> None:
                self.generic_visit(node)
                func = node.func
                if not (isinstance(func, ast.Attribute)
                        and func.attr in _METRICS_CTOR_METHODS
                        and node.args):
                    return
                kind, frags = _metric_name_parts(node.args[0])
                if kind == "literal":
                    name = frags[0]
                    if not _METRICS_FAMILY_RE.match(name):
                        findings.append(rule.finding(
                            src, node, self.qualname,
                            f"metric name {name!r} breaks the "
                            f"`{METRICS_FAMILY_RE_SRC}` family grammar — "
                            f"the exposition sanitizer folds every other "
                            f"character to '_', silently colliding "
                            f"families"))
                elif kind == "fragments":
                    for frag in frags:
                        bad = set(frag) - _METRICS_FAMILY_CHARSET
                        if bad:
                            findings.append(rule.finding(
                                src, node, self.qualname,
                                f"metric name fragment {frag!r} carries "
                                f"characters outside the "
                                f"`{METRICS_FAMILY_RE_SRC}` family "
                                f"grammar: {sorted(bad)}"))
                            break

        V().visit(src.tree)
        return iter(findings)

    def summarize(self, src: SourceFile):
        if src.relpath.startswith(_METRICS_EXEMPT_PREFIXES):
            return []
        rows: List[Tuple[str, str, str, int]] = []

        class V(QualnameVisitor):
            def visit_Call(self, node: ast.Call) -> None:
                self.generic_visit(node)
                func = node.func
                if not (isinstance(func, ast.Attribute)
                        and func.attr in _METRICS_CTOR_METHODS
                        and node.args):
                    return
                kind, frags = _metric_name_parts(node.args[0])
                if kind == "literal":
                    rows.append((frags[0], func.attr, self.qualname,
                                 node.lineno))

        V().visit(src.tree)
        return rows

    def absorb(self, relpath: str, summary) -> None:
        for name, mtype, qualname, line in summary or ():
            self._families.setdefault(name, {}).setdefault(
                mtype, (relpath, qualname, line))

    def finalize(self) -> Iterator[Finding]:
        for name in sorted(self._families):
            by_type = self._families[name]
            if len(by_type) < 2:
                continue
            sites = sorted((mtype, loc) for mtype, loc in by_type.items())
            (first_type, first_loc) = sites[0]
            others = ", ".join(
                f"{mtype} at {loc[0]}:{loc[2]}" for mtype, loc in sites[1:])
            yield Finding(
                self.id, first_loc[0], first_loc[2], first_loc[1],
                f"metric family {name!r} registered as {first_type} here "
                f"but also as {others} — the registry raises on the "
                f"second type at runtime; pick one type per family")
        self._families.clear()


ALL_RULES: Tuple[type, ...] = (
    SilentExceptRule, LockDisciplineRule, HotPathPurityRule,
    ConsensusFloatRule, UnorderedIterationRule, FailpointHygieneRule,
    ServingBoundednessRule, BackendIsolationRule, FoldOrderRule,
    ReadTierLockRule, ShardWorkerIsolationRule, ShardingDisciplineRule,
    LockOrderRule, MetricsFamilyRule,
)


def default_rules() -> List[Rule]:
    return [cls() for cls in ALL_RULES]
