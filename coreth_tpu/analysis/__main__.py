"""CLI: `python -m coreth_tpu.analysis [options]`.

Exit codes: 0 clean (every finding baselined), 1 new findings or stale
baseline entries with --strict-baseline, 2 bad invocation/baseline.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import (BASELINE_PATH, PACKAGE_ROOT, BaselineError, run_repo)
from .rules import ALL_RULES


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m coreth_tpu.analysis",
        description="repo-native static analysis (SA001-SA005)")
    ap.add_argument("--package", type=Path, default=PACKAGE_ROOT,
                    help="package dir to walk (default: coreth_tpu)")
    ap.add_argument("--baseline", type=Path, default=BASELINE_PATH,
                    help="allowlist file (default: analysis/baseline.txt)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the allowlist")
    ap.add_argument("--json", action="store_true", help="machine output")
    ap.add_argument("--strict-baseline", action="store_true",
                    help="fail on stale allowlist entries too")
    ap.add_argument("--write-baseline", action="store_true",
                    help="append new findings to the allowlist as TODO "
                         "entries (then edit in real justifications)")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    if args.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.id}  {cls.title}")
        return 0

    try:
        new, suppressed, unused, baseline = run_repo(
            args.package, args.baseline if not args.no_baseline else Path("/nonexistent"))
    except BaselineError as exc:
        print(f"baseline error: {exc}", file=sys.stderr)
        return 2

    if args.json:
        print(json.dumps({
            "new": [f.__dict__ for f in new],
            "suppressed": len(suppressed),
            "unused_baseline": unused,
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        for key in unused:
            print(f"warning: stale baseline entry (no longer fires): {key}",
                  file=sys.stderr)
        print(f"{len(new)} finding(s), {len(suppressed)} baselined, "
              f"{len(unused)} stale baseline entr{'y' if len(unused)==1 else 'ies'}",
              file=sys.stderr)

    if args.write_baseline and new:
        with args.baseline.open("a") as fh:
            for f in new:
                fh.write(f"{f.rule} {f.path}:{f.qualname} — TODO: justify "
                         f"({f.message})\n")
        print(f"appended {len(new)} entries to {args.baseline}",
              file=sys.stderr)

    if new:
        return 1
    if unused and args.strict_baseline:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
