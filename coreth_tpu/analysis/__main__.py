"""CLI: `python -m coreth_tpu.analysis [options]`.

Exit codes: 0 clean (every finding baselined), 1 new findings or stale
baseline entries with --strict-baseline, 2 bad invocation/baseline.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from . import (BASELINE_PATH, PACKAGE_ROOT, BaselineError, run_repo)
from .rules import ALL_RULES


def _graph_mode(program, fragment: str) -> int:
    """`--graph <qualname>`: triage view of the interprocedural layer —
    callers, callees, direct + transitive lock sets for every function
    matching [fragment]; `--graph locks` prints the global lock-order
    edge set and the derived canonical order."""
    if program is None:
        print("no program built (package walk failed?)", file=sys.stderr)
        return 2
    if fragment == "locks":
        edges = program.lock_edges()
        print(f"lock-order graph: {len(edges)} edge(s)")
        for (a, b), e in sorted(edges.items()):
            key, line, _act = e.witness[0]
            node = program.funcs[key]
            print(f"  {a} -> {b}    [{node.relpath}:{line} "
                  f"{node.qualname}]")
        cycles = program.lock_cycles()
        for c in cycles:
            print("CYCLE:")
            print(c.render(program.funcs))
        print("canonical order:" if not cycles
              else "order (unreliable, cycles present):")
        for name in program.lock_order():
            print(f"  {name}")
        return 0
    nodes = program.find(fragment)
    if not nodes:
        print(f"no function matches {fragment!r}", file=sys.stderr)
        return 1
    summaries = program.lock_summaries()
    for node in nodes[:20]:
        print(f"{node.key}  (line {node.line})")
        if node.entry_locks:
            print(f"  entry locks (guarded-by): "
                  f"{', '.join(sorted(node.entry_locks))}")
        direct = sorted({lock for lock, _l, _h, _s in node.acquires})
        if direct:
            print(f"  acquires: {', '.join(direct)}")
        transitive = sorted(set(summaries.get(node.key, ())) - set(direct))
        if transitive:
            print(f"  may acquire transitively: {', '.join(transitive)}")
        for ck, line, held in sorted(node.callees):
            extra = (f"  [holding {', '.join(sorted(held))}]"
                     if held else "")
            print(f"  -> {ck}  (line {line}){extra}")
        for ck, line in sorted(node.callers):
            print(f"  <- {ck}  (line {line})")
        if node.unresolved:
            shown = ", ".join(t for t, _l in node.unresolved[:8])
            more = len(node.unresolved) - 8
            print(f"  unresolved calls: {shown}"
                  + (f" (+{more} more)" if more > 0 else ""))
    if len(nodes) > 20:
        print(f"... {len(nodes) - 20} more matches")
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m coreth_tpu.analysis",
        description="repo-native static analysis (SA001-SA005)")
    ap.add_argument("--package", type=Path, default=PACKAGE_ROOT,
                    help="package dir to walk (default: coreth_tpu)")
    ap.add_argument("--baseline", type=Path, default=BASELINE_PATH,
                    help="allowlist file (default: analysis/baseline.txt)")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignoring the allowlist")
    ap.add_argument("--json", action="store_true", help="machine output")
    ap.add_argument("--strict-baseline", action="store_true",
                    help="fail on stale allowlist entries too")
    ap.add_argument("--write-baseline", action="store_true",
                    help="append new findings to the allowlist as TODO "
                         "entries (then edit in real justifications)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--no-cache", action="store_true",
                    help="ignore the per-file result cache (cold run)")
    ap.add_argument("--graph", metavar="QUALNAME",
                    help="debug mode: print callers/callees + inferred "
                         "lock set for functions matching QUALNAME "
                         "(substring of 'relpath:Class.method'), plus "
                         "the global lock-order graph for 'locks'")
    args = ap.parse_args(argv)

    if args.list_rules:
        for cls in ALL_RULES:
            print(f"{cls.id}  {cls.title}")
        return 0

    from .engine import Engine
    from .rules import default_rules
    engine = Engine(default_rules())
    try:
        new, suppressed, unused, baseline = run_repo(
            args.package,
            args.baseline if not args.no_baseline else Path("/nonexistent"),
            cache=not args.no_cache, engine=engine)
    except BaselineError as exc:
        print(f"baseline error: {exc}", file=sys.stderr)
        return 2

    if args.graph:
        return _graph_mode(engine.program, args.graph)

    if args.json:
        print(json.dumps({
            "new": [f.__dict__ for f in new],
            "suppressed": len(suppressed),
            "unused_baseline": unused,
        }, indent=2))
    else:
        for f in new:
            print(f.render())
        for key in unused:
            print(f"warning: stale baseline entry (no longer fires): {key}",
                  file=sys.stderr)
        print(f"{len(new)} finding(s), {len(suppressed)} baselined, "
              f"{len(unused)} stale baseline entr{'y' if len(unused)==1 else 'ies'}",
              file=sys.stderr)

    if args.write_baseline and new:
        with args.baseline.open("a") as fh:
            for f in new:
                fh.write(f"{f.rule} {f.path}:{f.qualname} — TODO: justify "
                         f"({f.message})\n")
        print(f"appended {len(new)} entries to {args.baseline}",
              file=sys.stderr)

    if new:
        return 1
    if unused and args.strict_baseline:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
