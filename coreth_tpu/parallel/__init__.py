"""Device-mesh parallelism for the state-commitment path.

The reference's only "distributed" hashing is a 16-goroutine fan-out per
branch node (/root/reference/trie/hasher.go:124-139). The TPU-native design
shards the *batch* instead: one level's worth of node RLP is laid out as a
dense tensor and split across every chip of a `jax.sharding.Mesh` over ICI.
Keccak lanes are independent, so the shard axis is pure data parallelism;
the only collective is the digest all-gather back to the host (and a psum
for the batch checksum used by integrity checks).

`ShardedKeccak` is the multi-chip analog of ops.keccak_jax.BatchedKeccak:
same host API (list[bytes] -> list[digest]), device batches sharded over the
mesh's 'batch' axis via NamedSharding + jit.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..ops.keccak_jax import (
    WORDS_PER_BLOCK,
    digest_words_to_bytes,
    keccak256_blocks,
    pack_messages,
)


class MeshConfigError(ValueError):
    """A mesh request that can never produce a working sharded commit —
    raised at mesh construction with an actionable message instead of
    surfacing as an opaque shape/device error deep inside shard_map or
    GSPMD partitioning (the resident-mesh-devices knob's fail-fast)."""


# the planner buckets every segment's lane count to a multiple of this
# (ops/keccak_resident._pow2_bucket floor; mpt_inc.cpp round_lanes), so a
# mesh width must divide it for lanes to split evenly across shards
LANE_BUCKET = 16


def _check_width(n: int, what: str) -> None:
    devs = jax.devices()
    if n <= 0:
        raise MeshConfigError(
            f"{what} must be a positive device count (got {n})")
    if n > len(devs):
        raise MeshConfigError(
            f"{what} requests {n} devices but only {len(devs)} JAX "
            f"device(s) are visible on backend "
            f"{jax.default_backend()!r}; lower the width (e.g. the "
            f"resident-mesh-devices knob) or, for a virtual CPU mesh, "
            f"set XLA_FLAGS=--xla_force_host_platform_device_count={n} "
            f"before the first jax call")
    if LANE_BUCKET % n != 0:
        raise MeshConfigError(
            f"{what} of {n} does not divide the {LANE_BUCKET}-lane "
            f"planner bucket: segment lane counts are multiples of "
            f"{LANE_BUCKET}, so shards would be uneven — use a "
            f"power-of-two width <= {LANE_BUCKET}")


def process_count() -> int:
    """Number of jax processes in this runtime (1 = single-process)."""
    try:
        return int(jax.process_count())
    except AttributeError:  # very old jax without the multi-process API
        return 1


def mesh_spans_processes(mesh: Mesh) -> bool:
    """True when [mesh]'s devices belong to more than one jax process.

    Multi-process readiness gate: a mesh that spans processes runs one
    SPMD program per process, so any UNILATERAL local action on the
    resident state (e.g. the demotion ladder rebuilding on a local
    single device) would desync the other processes — callers must take
    the collective-safe path instead."""
    return len({d.process_index for d in mesh.devices.flat}) > 1


def make_mesh(n_devices: Optional[int] = None, axis: str = "batch") -> Mesh:
    """1-D mesh over the first n devices (all by default).

    Raises MeshConfigError (not an opaque shard_map failure) when the
    requested width exceeds the visible devices or does not divide the
    planner's lane bucketing."""
    devs = jax.devices()
    if n_devices is not None:
        _check_width(int(n_devices), "mesh width")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (axis,))


def make_mesh_2d(n_hosts: int, chips_per_host: int,
                 axes=("host", "batch")) -> Mesh:
    """2-D (host, chip) mesh — the multi-host deployment SHAPE.

    The intent: the outer axis is the host boundary, so its collectives
    ride DCN while the inner axis rides ICI — slow hops stay at the top
    of the reduction tree (the scaling-book layout rule). Keccak lanes
    are pure data parallelism, so the commit path shards lanes over BOTH
    axes and the only cross-host traffic is the digest gather /
    checksum psum.

    Device ordering: mesh_utils.create_device_mesh arranges devices so
    mesh rows align with the physical topology where the backend exposes
    it; the naive reshape fallback is only correct on single-host /
    virtual meshes (where this helper validates sharding LAYOUTS — on a
    real multi-host slice, prefer mesh_utils.create_hybrid_device_mesh
    with explicit per-host groupings)."""
    if n_hosts <= 0 or chips_per_host <= 0:
        raise MeshConfigError(
            f"2-D mesh extents must be positive (got {n_hosts} hosts x "
            f"{chips_per_host} chips/host)")
    want = n_hosts * chips_per_host
    _check_width(want, f"2-D mesh ({n_hosts} hosts x {chips_per_host} "
                       f"chips/host)")
    devs = jax.devices()[:want]
    try:
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_device_mesh(
            (n_hosts, chips_per_host), devices=devs)
    except Exception:  # virtual/CPU meshes: topology-agnostic reshape
        arr = np.array(devs).reshape(n_hosts, chips_per_host)
    return Mesh(arr, axes)


class ShardedKeccak:
    """Batched keccak sharded across a device mesh (data-parallel lanes).

    Host packs messages exactly like the single-chip path; the batch dim is
    padded to a multiple of (mesh size x 8 sublanes) and placed with
    NamedSharding(P('batch')) so XLA splits the scan across chips over ICI.
    """

    def __init__(self, mesh: Mesh, axis="batch"):
        # axis: str | tuple[str, ...] — a tuple shards the lane dim over
        # several mesh axes (the 2-D host x chip layout)
        self.mesh = mesh
        self.axis = axis
        self._sharding = NamedSharding(mesh, P(axis))
        self._fn = jax.jit(
            keccak256_blocks,
            in_shardings=(self._sharding, self._sharding),
            out_shardings=self._sharding,
        )

    @property
    def n_devices(self) -> int:
        return self.mesh.devices.size

    def digests(self, msgs: Sequence[bytes]) -> List[bytes]:
        n = len(msgs)
        if n == 0:
            return []
        words, nblocks = pack_messages(msgs)
        # power-of-two bucket (multiple of devices x 8 sublanes) so the set
        # of compiled shapes stays logarithmic in batch size
        mult = self.n_devices * 8
        target = mult
        while target < n:
            target *= 2
        pad = target - n
        if pad:
            words = np.concatenate(
                [words, np.zeros((pad,) + words.shape[1:], dtype=words.dtype)]
            )
            nblocks = np.concatenate([nblocks, np.ones(pad, dtype=nblocks.dtype)])
        out = np.asarray(
            self._fn(
                jax.device_put(jnp.asarray(words), self._sharding),
                jax.device_put(jnp.asarray(nblocks), self._sharding),
            )
        )
        return digest_words_to_bytes(out[:n])


def commit_step(mesh: Mesh, axis="batch"):
    # axis: str | tuple[str, ...] (tuple = multi-axis lane sharding)
    """Jitted sharded state-commitment step for the multi-chip dry run.

    One "training step" of this framework is a level-batched hash drain:
    hash every lane, then reduce a 32-bit checksum of the digests across the
    mesh (the integrity counter the acceptor queue records per block). The
    jnp.sum over the sharded digest tensor compiles to a real cross-chip
    reduction, so the dry run validates both the sharded compute and the
    collective path.
    """
    sharding = NamedSharding(mesh, P(axis))
    replicated = NamedSharding(mesh, P())

    # explicit in/out shardings (SA012): the checksum must come back
    # replicated and the digests stay lane-sharded — pinning both keeps
    # chained steps reshard-free when the mesh spans processes (pjit
    # multi-process recipe: never let placement be inferred per call)
    @partial(jax.jit,
             in_shardings=(sharding, sharding),
             out_shardings=(sharding, replicated))
    def step(words, nblocks):
        out = keccak256_blocks(words, nblocks)  # [B, 8] uint32, sharded on B
        checksum = jnp.sum(out, dtype=jnp.uint32)  # cross-shard reduction
        return out, checksum

    def run(words: np.ndarray, nblocks: np.ndarray):
        w = jax.device_put(jnp.asarray(words), sharding)
        nb = jax.device_put(jnp.asarray(nblocks), sharding)
        return step(w, nb)

    return run


def sharded_seg_impl(mesh: Mesh, axis: str = "batch", seg_impl=None):
    """Per-segment keccak for ops.keccak_planned.PlannedCommit with the
    lane dimension sharded across [mesh] (SURVEY §2.7: the 16-goroutine
    hasher fan-out re-landed as data parallelism over ICI).

    Composition: the planned executor's surrounding ops (patch gathers,
    scatter-add, digest updates) stay replicated — only the keccak FLOPs
    shard. Lanes are always a multiple of 16 (planner bucketing), so every
    mesh size up to 16 divides evenly.

    seg_impl=None: the XLA scan kernel, partitioned by GSPMD via sharding
    constraints. seg_impl given (e.g. keccak_pallas.staged_seg_impl): the
    kernel is mapped per-device with shard_map — a pallas_call is a custom
    call GSPMD cannot split, so each device runs the kernel on its own
    lane shard (the exact partitioning a pod would use); the impl's own
    static shape logic (Pallas for %1024-lane shards, XLA below) applies
    PER SHARD. GSPMD/shard_map inserts the digest all-gather back to
    replicated either way."""
    if seg_impl is not None:
        from jax import shard_map

        out_replicated = NamedSharding(mesh, P())

        def impl(words):
            # check_vma=False: pallas_call's out_shape carries no varying-
            # mesh-axes annotation, and the kernel is per-shard pure data
            # parallelism anyway (no cross-shard collectives to validate)
            out = shard_map(
                seg_impl, mesh=mesh,
                in_specs=(P(axis, None, None),), out_specs=P(axis, None),
                check_vma=False,
            )(words)
            # all-gather digests back to replicated, matching the GSPMD
            # branch: the planned step's surrounding ops (patch gathers
            # over arbitrary child lanes, dig updates) assume it
            return jax.lax.with_sharding_constraint(out, out_replicated)

        return impl

    from ..ops.keccak_staged import _segment_keccak

    lane_sharded = NamedSharding(mesh, P(axis, None, None))
    replicated = NamedSharding(mesh, P())

    def impl(words):
        w = jax.lax.with_sharding_constraint(words, lane_sharded)
        out = _segment_keccak(w)
        return jax.lax.with_sharding_constraint(out, replicated)

    return impl


_planned_by_mesh: dict = {}


def planned_commit_over_mesh(mesh: Mesh, axis: str = "batch"):
    """A PlannedCommit whose hashing shards across [mesh]. Cached per
    (mesh, axis) so repeated commits reuse one jit trace cache instead of
    re-tracing every segment shape per call."""
    key = (tuple(d.id for d in mesh.devices.flat), axis)
    runner = _planned_by_mesh.get(key)
    if runner is None:
        from ..ops.keccak_planned import PlannedCommit

        runner = PlannedCommit(seg_impl=sharded_seg_impl(mesh, axis))
        _planned_by_mesh[key] = runner
    return runner


def resident_executor_over_mesh(mesh: Mesh, axis: str = "batch",
                                seg_impl=None):
    """A ResidentExecutor whose device-resident state (digest store +
    row arenas) is SHARDED across [mesh] on the row axis — the
    multichip form of the deferred-absorb design: each device holds
    1/N of every arena class and of the digest store, so resident
    memory capacity and fresh-row upload bandwidth scale with the mesh
    (each host feeds its own chips' row shards over its own PCI/ICI
    link in a pod).

    Partitioning is GSPMD-driven: the step's row gathers, delta
    scatter-adds, and store scatters run over the sharded operands with
    XLA inserting the collectives; the per-commit dig matrix stays
    replicated (it is small and every later segment's patches may read
    any earlier lane). One executor per trie, as in the single-chip
    case. Validated on the virtual CPU mesh by __graft_entry__.
    dryrun_multichip's resident leg (root parity vs the host oracle
    across churn + rollback rounds).

    axis may be one mesh axis name or a tuple of names: on a 2-D
    (host, chip) mesh (make_mesh_2d), axis=("host", "batch") shards
    rows over every device — each host owns a contiguous row block, so
    fresh-row uploads stay host-local and only digest traffic crosses
    DCN."""
    from ..ops.keccak_resident import ResidentExecutor

    return ResidentExecutor(
        seg_impl=seg_impl,
        sharding=NamedSharding(mesh, P(axis, None)),
    )
