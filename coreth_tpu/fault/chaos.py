"""Deterministic cross-subsystem chaos conductor.

One seeded scheduler arms and disarms random bounded failpoint specs
from the full SA006 catalogue while driving a randomized workload over
a real in-process node — block inserts through the staged pipeline
with the resident mirror on, mixed RPC traffic, accepts/rejects/
reorgs, a degraded-rung storage drill, and one mid-run SIGKILL-and-
reboot drill — and checks invariants after every step:

  * state-root parity: the accepted root re-derived by a pure-python
    trie walk (iterate_leaves -> fresh CPU Trie) must equal the header
    root, whatever path (device, host fallback, quarantined mirror,
    degraded replay) produced it;
  * un-ragged flight records: every record in the ring carries the
    identical top-level key set — fault paths must not drop fields;
  * no wedged thread: a watchdog bounds each step and disarms
    everything if the budget is blown (a trip IS a violation);
  * bit-exact recovery after the kill: the reopened database repairs
    to exactly the head the child reported before dying.

Everything is derived from one seed — the scheduler RNG, the per-
failpoint fire streams (fault.set_seed), the corrupt-read bit pick —
so two runs with the same seed and steps produce byte-identical JSON
(`json.dumps(..., sort_keys=True)`, no timestamps). The per-run
metric deltas come from counter baselines snapshotted at entry, so
back-to-back runs in one process stay comparable.

CLI:  python -m coreth_tpu.fault.chaos --seed 7 --steps 500 --json

This module lives in coreth_tpu/fault/ on purpose: it is chaos
tooling, so SA006's naked-sleep exemption applies here and nowhere
else it touches.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

from . import clear_all, list_armed, set_failpoint, set_seed

# ---------------------------------------------------------------- catalogue

# (failpoint, subsystem, action, bounded specs to draw from). Every
# entry names an action that is GUARANTEED to reach the site while the
# spec is armed, so coverage pressure converges instead of spinning.
# Specs are bounded on purpose (`*count`, `hang:<ms>`): the conductor
# must never park a worker past the step watchdog.
CATALOGUE = (
    ("ethdb/before_get", "ethdb", "readfault", ("raise*1", "raise*2")),
    ("ethdb/before_put", "ethdb", "degraded", ("raise*24",)),
    ("ethdb/before_batch_write", "ethdb", "batchfault", ("raise*1",)),
    ("ethdb/torn_batch", "ethdb", "tornbatch", ("raise*1",)),
    ("ethdb/corrupt_read", "ethdb", "corrupt", ("raise*1",)),
    ("insert/before_recover", "insert", "insert",
     ("raise*1", "raise%0.5*2", "hang:5*2")),
    ("insert/before_execute", "insert", "insert",
     ("raise*1", "raise%0.5*2", "hang:5*2")),
    ("insert/before_commit", "insert", "insert", ("raise*1", "hang:5*2")),
    ("insert/before_write", "insert", "insert", ("raise*1", "hang:5*2")),
    ("chain/tail/before_body", "insert", "insert", ("raise*1", "hang:5*2")),
    ("chain/tail/partial_body", "insert", "insert", ("raise*1",)),
    ("chain/tail/before_head", "insert", "insert", ("raise*1", "hang:5*2")),
    ("rpc/before_dispatch", "rpc", "rpc",
     ("raise*1", "raise%0.5*4", "hang:5*4")),
    ("rpc/before_dispatch_expensive", "rpc", "rpc", ("raise*1", "hang:5*2")),
    ("ops/device/dispatch", "device", "device", ("raise*4", "hang:5*4")),
    ("resident/before_absorb", "device", "insert", ("hang:5*2",)),
    ("state/resident/spot_check", "device", "spotcheck", ("raise*1",)),
    # exec shards: before_dispatch fires in the parent (raise -> fallback
    # before any fork traffic; hang -> bounded stall under the dispatch
    # span). shard_crash is raise-only HERE because hang specs park the
    # forked child, not the parent — the parent-side translation kills a
    # real worker process, so coverage counts in the parent registry and
    # the serial fallback must still commit the same root (invariant #1).
    ("exec/before_dispatch", "shard", "shard",
     ("raise*1", "raise%0.5*2", "hang:5*2")),
    ("exec/shard_crash", "shard", "shard", ("raise*1", "raise*2")),
)

# exceptions the conductor treats as the *point* of the exercise: every
# armor layer converts an injected fault into exactly one of these (or
# answers in-band, like RPC error objects)
def _expected_types():
    from ..core.blockchain import ChainError, TailStalled
    from ..ethdb import DBError
    from ..ops.device import DeviceDegradedError
    from . import FailpointError

    return (FailpointError, DBError, ChainError, TailStalled,
            DeviceDegradedError)


STEP_BUDGET = 60.0  # watchdog: seconds one step may take before it trips

KEY1 = b"\x11" * 32
KEY2 = b"\x22" * 32
DEST = b"\xbb" * 20
FUND = 10 ** 22


class _Watchdog:
    """Per-step deadline monitor: if a step blows its budget the
    watchdog records the trip (a violation) and disarms every failpoint
    so parked workers release and the run can finish with evidence
    instead of hanging CI."""

    def __init__(self, budget: float):
        self.budget = budget
        self.tripped: List[str] = []
        self._mu = threading.Lock()
        self._label: Optional[str] = None
        self._deadline: Optional[float] = None
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="chaos-watchdog", daemon=True)
        self._thread.start()

    def begin(self, label: str) -> None:
        with self._mu:
            self._label = label
            self._deadline = time.monotonic() + self.budget

    def end(self) -> None:
        with self._mu:
            self._label = None
            self._deadline = None

    def _loop(self) -> None:
        while not self._stop.wait(0.25):
            with self._mu:
                expired = (self._deadline is not None
                           and time.monotonic() > self._deadline)
                label = self._label
                if expired:
                    self._deadline = None  # one trip per step
            if expired:
                self.tripped.append(label or "?")
                clear_all()  # release anything parked on a hang

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=2)


# ------------------------------------------------------- kill-reboot drill

# Child for the mid-run SIGKILL drill: builds a real chain on SQLite,
# tears block 3's insert tail with an armed failpoint (head pointer
# lands, body never does), reports its hashes, then parks until the
# parent SIGKILLs it. Same harness shape as tests/test_tail_repair.py.
_KILL_CHILD = r"""
import sys, threading
sys.path.insert(0, sys.argv[2])
from coreth_tpu import fault, params
from coreth_tpu.consensus.dummy import new_dummy_engine
from coreth_tpu.core.blockchain import BlockChain, CacheConfig, ChainError
from coreth_tpu.core.chain_makers import generate_chain
from coreth_tpu.core.genesis import Genesis, GenesisAccount
from coreth_tpu.core.types import Signer, Transaction
from coreth_tpu.crypto.secp256k1 import priv_to_address
from coreth_tpu.ethdb.faultdb import FaultInjectingDB
from coreth_tpu.ethdb.sqlitedb import SQLiteDB
from coreth_tpu.state.database import Database
from coreth_tpu.trie.triedb import TrieDatabase

KEY = b"\x11" * 32
ADDR = priv_to_address(KEY)
DEST = b"\xbb" * 20

def tx(nonce):
    t = Transaction(type=2, chain_id=43112, nonce=nonce, max_fee=10**12,
                    max_priority_fee=10**9, gas=21000, to=DEST, value=1000)
    return Signer(43112).sign(t, KEY)

diskdb = FaultInjectingDB(SQLiteDB(sys.argv[1]))
genesis = Genesis(config=params.TEST_CHAIN_CONFIG,
                  gas_limit=params.CORTINA_GAS_LIMIT,
                  alloc={ADDR: GenesisAccount(balance=10**22)})
chain = BlockChain(diskdb, CacheConfig(commit_interval=4096),
                   params.TEST_CHAIN_CONFIG, genesis, new_dummy_engine(),
                   state_database=Database(TrieDatabase(diskdb)))

def build(n):
    blocks, _ = generate_chain(
        chain.config, chain.current_block, chain.engine,
        chain.state_database, n,
        gen=lambda i, bg: bg.add_tx(tx(chain.current_block.number + i)))
    for b in blocks:
        chain.insert_block(b)
    return blocks

blocks = build(2)
chain.join_tail()
fault.set_failpoint("chain/tail/partial_body", "raise*1")
extra = build(1)
try:
    chain.join_tail()
except ChainError:
    pass
print("B2", blocks[1].hash().hex(), flush=True)
print("B3", extra[0].hash().hex(), flush=True)
print("READY", flush=True)
threading.Event().wait(120)  # parked until SIGKILL
"""


# ------------------------------------------------------------ the conductor

class Conductor:
    """One chaos run: owns the chain + RPC surface, the seeded
    scheduler, and the invariant checks. `run()` returns the
    deterministic result dict."""

    def __init__(self, seed: int, steps: int, kill_drill: bool = True,
                 step_budget: float = STEP_BUDGET):
        self.seed = int(seed)
        self.steps = int(steps)
        self.kill_drill = bool(kill_drill)
        self.step_budget = float(step_budget)
        self.violations: List[Dict[str, object]] = []
        self.step_log: List[Dict[str, object]] = []
        self.kill_result: Optional[Dict[str, object]] = None
        self._watchdog_seen = 0
        self._pick_attempts: Dict[str, int] = {}

    # ---- lifecycle -------------------------------------------------------

    def _boot(self) -> None:
        import random

        from .. import params
        from ..consensus.dummy import new_dummy_engine
        from ..core.blockchain import BlockChain, CacheConfig
        from ..core.genesis import Genesis, GenesisAccount
        from ..core.txpool import TxPool, TxPoolConfig
        from ..crypto.secp256k1 import priv_to_address
        from ..eth.api import EthAPI
        from ..eth.backend import EthBackend
        from ..ethdb import MemoryDB
        from ..ethdb.faultdb import FaultInjectingDB
        from ..metrics import default_registry
        from ..rpc.server import RPCServer
        from ..state.database import Database
        from ..trie.triedb import TrieDatabase

        from ..ops.device import default_ladder

        clear_all()
        set_seed(self.seed)
        # the ladder is process-global: start from HEALTHY so a prior
        # run (or test) that left it demoted cannot leak into this one
        default_ladder().reset()
        self.rng = random.Random(self.seed)
        self.addr1 = priv_to_address(KEY1)
        self.addr2 = priv_to_address(KEY2)

        self.baseline = {
            name: m.count() for name, m in default_registry.each()
            if hasattr(m, "count") and not hasattr(m, "update")
        }

        cfg = params.TEST_CHAIN_CONFIG
        self.diskdb = FaultInjectingDB(MemoryDB())
        state_db = Database(TrieDatabase(self.diskdb))
        genesis = Genesis(
            config=cfg, gas_limit=params.CORTINA_GAS_LIMIT,
            alloc={self.addr1: GenesisAccount(balance=FUND),
                   self.addr2: GenesisAccount(balance=FUND)},
        )
        # commit_interval=1: accepted tries land on disk every block, so
        # the pure-trie oracle can walk any accepted root; verify-on-read
        # + bounded retries + the degraded rung all armed; probe loop off
        # (device re-promotion is driven deterministically by the
        # conductor, not a timer); no resident watchdog — the only
        # timing authority in the run is the conductor's own watchdog.
        self.chain = BlockChain(
            self.diskdb,
            CacheConfig(pruning=True, commit_interval=1,
                        resident_account_trie=True,
                        resident_prefer_host=False,
                        resident_pipeline_depth=2,
                        resident_spot_check_interval=1,
                        insert_pipeline_depth=2,
                        evm_exec_shards=2,
                        db_verify_on_read=True, db_retry_budget=2,
                        tail_join_timeout=self.step_budget / 2,
                        device_probe_interval=0.0),
            cfg, genesis, new_dummy_engine(), state_database=state_db,
        )
        self.txpool = TxPool(TxPoolConfig(), cfg, self.chain)
        self.server = RPCServer()
        self.server.register_api("eth", EthAPI(EthBackend(
            self.chain, self.txpool)))
        self.genesis_hash = self.chain.get_canonical_hash(0)

        # lock-order witness (invariant #6): every chain-path lock from
        # racecheck.CANONICAL_LOCK_ORDER that exists in this topology is
        # swapped for an order-tracking proxy, immediately after
        # construction so no Condition can capture a raw inner lock.
        from ..utils.racecheck import LockOrderWitness
        self.witness = LockOrderWitness()
        self.witness.wrap(self.chain, "chainmu", "BlockChain.chainmu")
        self.witness.wrap(self.chain, "_acceptor_tip_lock",
                          "BlockChain._acceptor_tip_lock")
        self.witness.wrap(self.chain, "_insert_recs_mu",
                          "BlockChain._insert_recs_mu")
        self.witness.wrap(self.chain, "_view_mu", "BlockChain._view_mu")
        self.witness.wrap(self.chain, "_degraded_mu",
                          "BlockChain._degraded_mu")
        if getattr(self.chain, "pipeline", None) is not None:
            self.witness.wrap(self.chain.pipeline, "_mu",
                              "InsertPipeline._mu")
        if self.chain.snaps is not None:
            self.witness.wrap(self.chain.snaps, "lock", "Tree.lock")
        self.witness.wrap(self.txpool, "mu", "TxPool.mu")
        self.witness.wrap(default_registry, "_lock", "Registry._lock")

        # sampling profiler armed hot for the whole run (invariant #7):
        # 50 Hz against every witnessed lock above — the sampler must
        # never throw into the workload, and its lock-tag reads of the
        # witness mirror must not perturb the lock-order record
        from ..metrics.profiler import start_profiler
        self.profiler = start_profiler(50.0, ring_size=4096)

        self.watchdog = _Watchdog(self.step_budget)
        self.expected = _expected_types()

    def _shutdown(self) -> None:
        clear_all()
        try:
            self.chain.stop()
        except Exception as e:  # noqa: BLE001 - teardown is best-effort
            self._record_violation("shutdown", f"chain.stop failed: {e!r}")
        if getattr(self, "profiler", None) is not None:
            # stop sampling BEFORE the witness unwraps: the sampler's
            # lock-tag reads reference the witness held-stack mirror
            from ..metrics.profiler import stop_profiler
            stop_profiler()
            self.profiler = None
        if getattr(self, "witness", None) is not None:
            # the metrics registry is process-global; it must not keep a
            # witness proxy once this conductor is gone
            self.witness.unwrap_all()
        self.watchdog.close()

    def _record_violation(self, what: str, detail: str, step: int = -1) -> None:
        self.violations.append(
            {"step": step, "what": what, "detail": detail})

    # ---- deterministic helpers ------------------------------------------

    def _tx(self, nonce: int, value: int = 1000):
        from ..core.types import Signer, Transaction

        t = Transaction(type=2, chain_id=43112, nonce=nonce,
                        max_fee=10 ** 12, max_priority_fee=10 ** 9,
                        gas=21000, to=DEST, value=value)
        return Signer(43112).sign(t, KEY1)

    def _make_blocks(self, n: int, gap: int = 10):
        from ..core.chain_makers import generate_chain

        chain = self.chain
        nonce = chain.state().get_nonce(self.addr1)
        blocks, _ = generate_chain(
            chain.config, chain.current_block, chain.engine,
            chain.state_database, n, gap=gap,
            gen=lambda i, bg: bg.add_tx(self._tx(nonce + i)))
        return blocks

    def _quiesce(self) -> int:
        """Land every async worker so step accounting is deterministic.
        Returns how many expected (injected) failures surfaced here."""
        faults = 0
        chain = self.chain
        for closer in (
                (chain.pipeline.drain if chain.pipeline is not None
                 else lambda: None),
                chain.join_tail,
                chain.drain_acceptor_queue):
            try:
                closer()
            except self.expected:
                faults += 1
        return faults

    def _accept_pending(self) -> int:
        """Accept every canonical block above last-accepted, in order.

        Accepts are deliberately deferred to here, AFTER clear_all: the
        acceptor's post-process join_tail would otherwise consume an
        injected tail tear mid-accept, skip the flatten/export for that
        block, and poison every later flatten with an accept-order
        violation. Consensus delivers accepts in order on a healthy
        node; the conductor plays consensus."""
        faults = 0
        chain = self.chain
        try:
            while (chain.last_accepted.number
                   < chain.current_block.number):
                n = chain.last_accepted.number + 1
                h = chain.get_canonical_hash(n)
                b = chain.get_block(h) if h else None
                if b is None:
                    self._record_violation("accept-backlog",
                                  f"canonical block {n} unresolvable")
                    break
                chain.accept(b)
            faults += self._quiesce()
        except self.expected:
            faults += 1
        return faults

    def _recover(self) -> int:
        """Undo every armed consequence: disarm, re-promote the device
        ladder, walk the chain out of the degraded rung (checking that
        reads kept serving while it was degraded), and play consensus —
        accept the canonical backlog in order."""
        from ..ops.device import default_ladder

        faults = 0
        clear_all()
        ladder = default_ladder()
        if not ladder.healthy:
            ladder.promote()
        if self.chain.degraded:
            faults += self._check_degraded_serving()
            faults += self._heal_degraded()
        faults += self._quiesce()
        faults += self._accept_pending()
        return faults

    def _check_degraded_serving(self) -> int:
        """The degraded acceptance surface: a chain that cannot write
        must still answer reads."""
        ok, errs = self._rpc_batch()
        if errs:
            self._record_violation("degraded-serving",
                          f"{errs} RPC read(s) failed while degraded")
        return 0

    def _heal_degraded(self) -> int:
        """With failpoints disarmed, the next insert probes the store,
        replays the stashed tail writes, and clears the rung."""
        faults = 0
        try:
            blocks = self._make_blocks(1)
            self.chain.insert_block(blocks[0])
            faults += self._quiesce()
        except self.expected:
            faults += 1
        if self.chain.degraded:
            self._record_violation("degraded-recovery",
                          "chain still degraded after disarm + insert")
        return faults

    # ---- workload actions ------------------------------------------------

    def act_insert(self) -> int:
        """The bread-and-butter action: a 1-2 block burst through the
        pipelined insert path, driving the tail, the resident mirror,
        the spot check, and the interval flush. Accepts are NOT issued
        here — _recover plays them in order once faults are disarmed,
        like consensus would on a healthy node."""
        faults = 0
        chain = self.chain
        try:
            blocks = self._make_blocks(self.rng.randint(1, 2))
            for b in blocks:
                chain.insert_block(b)
        except self.expected:
            faults += 1
        faults += self._quiesce()
        return faults

    def act_reorg(self) -> int:
        """Two competing children of the same parent (same txs, gap-
        skewed timestamps, so the nonce model is fork-independent);
        prefer and accept one, reject the other."""
        faults = 0
        chain = self.chain
        try:
            fork_a = self._make_blocks(1, gap=10)
            fork_b = self._make_blocks(1, gap=11)
            chain.insert_block(fork_a[0])
            chain.insert_block(fork_b[0])
            winner, loser = ((fork_a[0], fork_b[0])
                            if self.rng.random() < 0.5
                            else (fork_b[0], fork_a[0]))
            chain.set_preference(winner)
            chain.accept(winner)
            chain.reject(loser)
            faults += self._quiesce()
        except self.expected:
            faults += 1
        return faults

    def act_spotcheck(self) -> int:
        """Forced mirror divergence: the armed spot check quarantines
        the mirror (rebuilt from last-accepted state), which drops the
        unaccepted block it was mid-insert on. The consensus contract
        (test_resident_chain) is that the suffix gets RE-DELIVERED, so
        the conductor re-inserts it through the rebuilt mirror before
        accepting."""
        faults = 0
        chain = self.chain
        try:
            blocks = self._make_blocks(1)
            chain.insert_block(blocks[0])
            faults += self._quiesce()  # lands commit + any quarantine
            clear_all()
            chain.insert_block(blocks[0])  # consensus re-delivery
            faults += self._quiesce()
        except self.expected:
            faults += 1
        return faults

    def _rpc_batch(self):
        """One mixed JSON-RPC batch (cheap + expensive lanes) through
        the wire-format dispatch path. -> (ok_count, err_count)."""
        a1 = "0x" + self.addr1.hex()
        reqs = [
            {"jsonrpc": "2.0", "id": 1, "method": "eth_blockNumber",
             "params": []},
            {"jsonrpc": "2.0", "id": 2, "method": "eth_getBalance",
             "params": [a1, "latest"]},
            {"jsonrpc": "2.0", "id": 3, "method": "eth_getBlockByNumber",
             "params": ["latest", False]},
            {"jsonrpc": "2.0", "id": 4, "method": "eth_call",
             "params": [{"from": a1, "to": "0x" + DEST.hex(),
                         "value": "0x0"}, "latest"]},
        ]
        out = json.loads(self.server.handle_raw(json.dumps(reqs).encode()))
        ok = sum(1 for r in out if "result" in r)
        return ok, len(out) - ok

    def act_rpc(self) -> int:
        """RPC traffic. Injected dispatch faults come back as JSON
        error objects (the armor), never exceptions."""
        _, errs = self._rpc_batch()
        return errs

    def act_device(self) -> int:
        """Drive the process-wide device ladder directly (its docstring
        sanctions exactly this): an armed dispatch failure exhausts the
        retry budget, demotes to host, and raises the typed error."""
        from ..ops.device import DeviceDegradedError, default_ladder

        try:
            default_ladder().dispatch(lambda: b"pong", "chaos device drill")
            return 0
        except DeviceDegradedError:
            return 1

    def act_readfault(self) -> int:
        """A direct storage read with ethdb/before_get armed: the
        boundary must answer with typed DBError, not a raw failure."""
        from ..core import rawdb
        from ..ethdb import DBError

        head = self.chain.current_block
        try:
            rawdb.read_header_rlp(self.diskdb, head.number, head.hash())
            return 0
        except DBError:
            return 1

    def act_corrupt(self) -> int:
        """ethdb/corrupt_read flips one seeded bit in the next read;
        verify-on-read must catch it as CorruptDataError — silent
        propagation into consensus is a violation."""
        from ..core import rawdb
        from ..ethdb import CorruptDataError, DBError

        head = self.chain.current_block
        number, h = head.number, head.hash()
        # probe the UNWRAPPED backend: a previous step's injected tail
        # tear may have legitimately left the head's header row off the
        # disk, and a get through the wrapper would consume the armed
        # one-shot without flipping anything. Genesis is always durable.
        if self.diskdb._db.get(rawdb.header_key(number, h)) is None:
            number, h = 0, self.genesis_hash
        try:
            rawdb.read_header_rlp(self.diskdb, number, h)
        except CorruptDataError:
            return 1
        except DBError:
            return 1  # armed %prob can fire on before_get instead
        self._record_violation("corrupt-read",
                      "flipped bit passed verify-on-read unnoticed")
        return 0

    def act_batchfault(self) -> int:
        """Scratch-batch write with before_batch_write armed: typed
        DBError and NOTHING applied."""
        from ..ethdb import DBError, MemoryDB
        from ..ethdb.faultdb import FaultInjectingDB

        scratch = FaultInjectingDB(MemoryDB())
        try:
            scratch.write_batch([(b"k%d" % i, b"v%d" % i)
                                 for i in range(4)])
        except DBError:
            if len(scratch) != 0:
                self._record_violation("batch-atomicity",
                              "bytes applied before the injected "
                              "batch failure")
            return 1
        return 0

    def act_tornbatch(self) -> int:
        """Scratch-batch write with torn_batch armed: exactly the first
        half lands — the non-atomic-backend shape the boot repair and
        the SQLite transaction contract exist for."""
        from ..ethdb import DBError, MemoryDB
        from ..ethdb.faultdb import FaultInjectingDB

        scratch = FaultInjectingDB(MemoryDB())
        try:
            scratch.write_batch([(b"k%d" % i, b"v%d" % i)
                                 for i in range(4)])
        except DBError:
            if len(scratch) != 2:
                self._record_violation("torn-batch",
                              f"expected a 2-entry torn prefix, found "
                              f"{len(scratch)}")
            return 1
        self._record_violation("torn-batch", "armed torn_batch never fired")
        return 0

    def act_degraded(self) -> int:
        """The full degraded-rung drill: persistent write failure while
        the tail lands a block -> chain turns read-only instead of
        crashing; reads keep serving; a write while sick raises the
        typed error; disarm -> probe -> replay -> recovered."""
        from ..core.blockchain import ChainDegradedError

        faults = 0
        chain = self.chain
        try:
            blocks = self._make_blocks(2)
        except self.expected:
            return 1
        chain.insert_block(blocks[0])
        faults += self._quiesce()  # tail retries exhaust -> degraded
        if not chain.degraded:
            self._record_violation("degraded-entry",
                          "persistent put failure never engaged the "
                          "degraded rung")
            return faults
        faults += self._check_degraded_serving()
        try:
            chain.insert_block(blocks[1])
            self._record_violation("degraded-gate",
                          "insert during degraded did not raise")
        except ChainDegradedError:
            faults += 1
        clear_all()
        try:
            chain.insert_block(blocks[1])  # probe + replay + recover
            faults += self._quiesce()
        except self.expected as e:
            self._record_violation("degraded-recovery", f"recovery insert: {e!r}")
        if chain.degraded:
            self._record_violation("degraded-recovery",
                          "rung still engaged after disarm")
        return faults

    def _make_shard_block(self, txs: int = 4):
        """One block with enough txs to clear the shard dispatch gate
        (exec_shards.MIN_SHARD_TXS) — _make_blocks' 1-tx blocks never
        reach the forked workers."""
        from ..core.chain_makers import generate_chain

        chain = self.chain
        nonce = chain.state().get_nonce(self.addr1)

        def gen(i, bg):
            for j in range(txs):
                bg.add_tx(self._tx(nonce + j))

        blocks, _ = generate_chain(
            chain.config, chain.current_block, chain.engine,
            chain.state_database, 1, gap=10, gen=gen)
        return blocks[0]

    def act_shard(self) -> int:
        """A multi-tx block through the forked execution shards. An
        armed shard_crash SIGKILLs a real worker mid-dispatch; the pool
        ladder respawns it and the block falls back to the untouched
        serial loop. The committed root must be identical either way —
        invariant #1 (pure-trie root parity) is exactly the killed-
        shard-never-changes-the-root check, run after every step."""
        faults = 0
        chain = self.chain
        crashes_before = self._counter_delta("exec/shard/crashes")
        try:
            chain.insert_block(self._make_shard_block())
        except self.expected:
            faults += 1
        faults += self._quiesce()
        # a worker killed by the armed failpoint surfaces as a crash
        # counted in the parent, not as an exception out of insert
        faults += (self._counter_delta("exec/shard/crashes")
                   - crashes_before)
        return faults

    ACTIONS = {
        "insert": act_insert,
        "shard": act_shard,
        "spotcheck": act_spotcheck,
        "reorg": act_reorg,
        "rpc": act_rpc,
        "device": act_device,
        "readfault": act_readfault,
        "corrupt": act_corrupt,
        "batchfault": act_batchfault,
        "tornbatch": act_tornbatch,
        "degraded": act_degraded,
    }

    # ---- invariants ------------------------------------------------------

    def _check_invariants(self, step: int) -> None:
        from ..trie.iterator import iterate_leaves
        from ..trie.trie import Trie

        chain = self.chain
        # 1. state-root parity against the pure-python trie oracle
        root = chain.last_accepted.root
        try:
            st = chain.state_database.triedb.open_state_trie(root)
            oracle = Trie()
            for k, v in iterate_leaves(st.trie):
                oracle.update(k, v)
            if oracle.hash() != root:
                self._record_violation(
                    "root-parity",
                    f"pure-trie root {oracle.hash().hex()} != accepted "
                    f"header root {root.hex()}", step)
        except Exception as e:  # noqa: BLE001 - any oracle failure counts
            self._record_violation("root-parity", f"oracle walk failed: {e!r}", step)
        # 2. un-ragged flight records
        keysets = {tuple(sorted(r)) for r in chain.flight_recorder.last()}
        if len(keysets) > 1:
            self._record_violation("flight-ragged",
                          f"{len(keysets)} distinct key sets in the "
                          f"flight ring", step)
        # 3. the acceptor thread survived AND swallowed nothing: every
        # injected fault must be consumed by the conductor's own joins,
        # never by the async acceptor (where a skipped flatten/export
        # would silently poison later accepts)
        if chain.acceptor_error is not None:
            err = chain.acceptor_error.strip().splitlines()[-1]
            chain.acceptor_error = None  # one event, one violation
            self._record_violation("acceptor-error", err, step)
        # 4. watchdog trips are violations
        while self._watchdog_seen < len(self.watchdog.tripped):
            self._record_violation("watchdog",
                          f"step budget blown at "
                          f"{self.watchdog.tripped[self._watchdog_seen]}",
                          step)
            self._watchdog_seen += 1
        # 5. nothing left armed between steps
        leftovers = list_armed()
        if leftovers:
            clear_all()
            self._record_violation("armed-leak",
                          f"{[a['name'] for a in leftovers]} still armed "
                          f"after recovery", step)
        # 6. lock-order witness: no thread acquired canonical locks out
        # of order during the step (runtime twin of the SA013 lint)
        if self.witness.violations:
            for v in self.witness.violations:
                self._record_violation("lock-order", v, step)
            self.witness.violations = []
        # 7. the sampling profiler stayed silent and alive: its tick is
        # fenced — any exception it swallowed counts sampler_errors, and
        # a dead sampler thread means a tick escaped the fence entirely
        if self.profiler is not None:
            errs = self._counter_delta("profile/sampler_errors")
            if errs > 0:
                self._record_violation(
                    "profiler-error",
                    f"{errs} fenced sampler exception(s)", step)
            if not self.profiler.alive():
                self._record_violation(
                    "profiler-dead", "sampler thread exited mid-run", step)

    # ---- kill drill ------------------------------------------------------

    def _run_kill_drill(self, step: int) -> None:
        """SIGKILL a child mid-torn-tail and reboot its database: the
        repair must land on exactly the head the child reported."""
        from ..core import rawdb
        from ..ethdb.sqlitedb import SQLiteDB

        repo = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        tmp = tempfile.mkdtemp(prefix="coreth-chaos-")
        path = os.path.join(tmp, "kill.db")
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(
            [sys.executable, "-c", _KILL_CHILD, path, repo],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)
        lines: List[str] = []
        deadline = time.time() + 300
        try:
            while time.time() < deadline:
                line = proc.stdout.readline()
                if not line:
                    break
                lines.append(line.strip())
                if line.strip() == "READY":
                    break
        finally:
            proc.kill()  # SIGKILL: no atexit, no close, no flush
            proc.wait(30)
        hashes = {p[0]: p[1] for p in (l.split() for l in lines)
                  if len(p) == 2 and p[0].startswith("B")}
        if "READY" not in lines or "B2" not in hashes:
            self._record_violation("kill-drill",
                          f"child never reached READY: {lines[-3:]}", step)
            self.kill_result = {"ok": False, "reason": "child-not-ready"}
            return
        h2 = bytes.fromhex(hashes["B2"])
        h3 = bytes.fromhex(hashes["B3"])
        reopened = None
        diskdb = None
        try:
            diskdb = SQLiteDB(path)
            torn = (rawdb.read_head_block_hash(diskdb) == h3
                    and rawdb.read_body_rlp(diskdb, 3, h3) is None)
            reopened = self._reopen_chain(diskdb)
            repaired_head = reopened.current_block.hash()
            ok = (torn and reopened.current_block.number == 2
                  and repaired_head == h2
                  and rawdb.read_head_block_hash(diskdb) == h2
                  and reopened.state().get_balance(DEST) == 2 * 1000)
            if not ok:
                self._record_violation(
                    "kill-drill",
                    f"reboot repair not bit-exact: torn={torn} "
                    f"head={repaired_head.hex()} expected={h2.hex()}",
                    step)
            self.kill_result = {
                "ok": ok, "torn_on_disk": torn,
                "repaired_number": reopened.current_block.number,
                "repaired_head": repaired_head.hex(),
                "expected_head": h2.hex(),
            }
        except Exception as e:  # noqa: BLE001 - the drill must not abort the run
            self._record_violation("kill-drill", f"reboot failed: {e!r}", step)
            self.kill_result = {"ok": False, "reason": repr(e)}
        finally:
            if reopened is not None:
                reopened.stop()
            if diskdb is not None:
                diskdb.close()

    def _reopen_chain(self, diskdb):
        from .. import params
        from ..consensus.dummy import new_dummy_engine
        from ..core.blockchain import BlockChain, CacheConfig
        from ..core.genesis import Genesis, GenesisAccount
        from ..state.database import Database
        from ..trie.triedb import TrieDatabase

        genesis = Genesis(
            config=params.TEST_CHAIN_CONFIG,
            gas_limit=params.CORTINA_GAS_LIMIT,
            alloc={self.addr1: GenesisAccount(balance=FUND)})
        # db_verify_on_read mounts into a process-wide rawdb flag at
        # chain boot; a plain-default reopen here would silently disarm
        # the conductor's own verify-on-read for the rest of the run.
        return BlockChain(
            diskdb, CacheConfig(commit_interval=4096,
                                db_verify_on_read=True),
            params.TEST_CHAIN_CONFIG, genesis, new_dummy_engine(),
            state_database=Database(TrieDatabase(diskdb)))

    # ---- scheduling ------------------------------------------------------

    def _applicable(self):
        resident = self.chain.state_database.mirror is not None
        return [e for e in CATALOGUE
                if resident or not e[0].startswith(("resident/",
                                                    "state/resident/"))]

    def _pick(self, fired: Dict[str, int]):
        """Coverage-pressured choice: failpoints that have not fired yet
        this run go first, but only for a bounded number of attempts
        each — a site the workload cannot reach in this environment must
        not starve the rest of the schedule."""
        cat = self._applicable()
        unfired = [e for e in cat
                   if fired.get(e[0], 0) == 0
                   and self._pick_attempts.get(e[0], 0) < 3]
        pool = unfired or cat
        entry = pool[self.rng.randrange(len(pool))]
        spec = entry[3][self.rng.randrange(len(entry[3]))]
        self._pick_attempts[entry[0]] = (
            self._pick_attempts.get(entry[0], 0) + 1)
        return entry, spec

    def _fired_deltas(self) -> Dict[str, int]:
        from ..metrics import default_registry

        out: Dict[str, int] = {}
        for name, m in default_registry.each():
            if not name.startswith("fault/fired/") or not hasattr(m, "count"):
                continue
            delta = m.count() - self.baseline.get(name, 0)
            if delta > 0:
                out[name[len("fault/fired/"):]] = delta
        return out

    def _counter_delta(self, name: str) -> int:
        from ..metrics import default_registry

        return (default_registry.counter(name).count()
                - self.baseline.get(name, 0))

    # ---- the run ---------------------------------------------------------

    def run(self) -> Dict[str, object]:
        self._boot()
        try:
            kill_step = None
            if self.kill_drill and self.steps >= 5:
                kill_step = self.rng.randrange(self.steps // 2,
                                               self.steps)
            for step in range(self.steps):
                self.watchdog.begin(f"step {step}")
                try:
                    if step == kill_step:
                        self._run_kill_drill(step)
                        self.step_log.append(
                            {"step": step, "action": "kill-drill",
                             "armed": None, "spec": None, "faults": 0})
                        continue
                    fired = self._fired_deltas()
                    (name, _subsystem, action, _specs), spec = \
                        self._pick(fired)
                    set_failpoint(name, spec)
                    faults = self.ACTIONS[action](self)
                    faults += self._recover()
                    # unarmed mix-in traffic so steps overlap subsystems
                    extra = self.rng.choice(
                        ("rpc", "insert", "reorg", "none"))
                    if extra != "none":
                        faults += self.ACTIONS[extra](self)
                        faults += self._quiesce()
                        faults += self._accept_pending()
                    self.step_log.append(
                        {"step": step, "action": action, "armed": name,
                         "spec": spec, "faults": faults})
                    self._check_invariants(step)
                finally:
                    self.watchdog.end()
            fired = self._fired_deltas()
            subsystems = sorted({sub for fp, sub, _a, _s in CATALOGUE
                                 if fired.get(fp, 0) > 0})
            result = {
                "seed": self.seed,
                "steps": self.steps,
                "violations": self.violations,
                "fired": fired,
                "coverage": {"failpoints_fired": len(fired),
                             "subsystems": subsystems},
                "kill_drill": self.kill_result,
                "step_log": self.step_log,
                "final": {
                    "height": self.chain.current_block.number,
                    "accepted": self.chain.last_accepted.number,
                    "root": self.chain.last_accepted.root.hex(),
                    "degraded_entries":
                        self._counter_delta("chain/degraded_entries"),
                    "degraded_recoveries":
                        self._counter_delta("chain/degraded_recoveries"),
                    "db_retries": self._counter_delta("db/retries"),
                    "db_verify_failures":
                        self._counter_delta("db/verify_failures"),
                    "corrupt_injected":
                        self._counter_delta("ethdb/corrupt_injected"),
                    "device_demotions":
                        self._counter_delta("ops/device/demotions"),
                    "mirror_quarantines":
                        self._counter_delta("chain/mirror/quarantines"),
                    "shard_crashes":
                        self._counter_delta("exec/shard/crashes"),
                    "shard_respawns":
                        self._counter_delta("exec/shard/respawns"),
                    "shard_fallbacks":
                        self._counter_delta("exec/shard/fallbacks"),
                    "profiler_errors":
                        self._counter_delta("profile/sampler_errors"),
                },
            }
            return result
        finally:
            self._shutdown()


def run_chaos(seed: int, steps: int, kill_drill: bool = True,
              step_budget: float = STEP_BUDGET) -> Dict[str, object]:
    """Run one conducted chaos session; returns the deterministic
    result dict (same seed + steps -> byte-identical
    `json.dumps(..., sort_keys=True)`)."""
    return Conductor(seed, steps, kill_drill=kill_drill,
                     step_budget=step_budget).run()


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m coreth_tpu.fault.chaos",
        description="seeded cross-subsystem chaos conductor")
    ap.add_argument("--seed", type=int, default=1)
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--json", action="store_true",
                    help="emit the full deterministic result as JSON")
    ap.add_argument("--no-kill-drill", action="store_true",
                    help="skip the SIGKILL-and-reboot subprocess drill")
    ap.add_argument("--step-budget", type=float, default=STEP_BUDGET,
                    help="watchdog seconds per step")
    args = ap.parse_args(argv)

    result = run_chaos(args.seed, args.steps,
                       kill_drill=not args.no_kill_drill,
                       step_budget=args.step_budget)
    if args.json:
        print(json.dumps(result, sort_keys=True, indent=2))
    else:
        cov = result["coverage"]
        print(f"chaos seed={args.seed} steps={args.steps}: "
              f"{len(result['violations'])} violation(s), "
              f"{cov['failpoints_fired']} failpoint(s) fired across "
              f"{len(cov['subsystems'])} subsystem(s) "
              f"{cov['subsystems']}, "
              f"height={result['final']['height']}")
        for v in result["violations"]:
            print(f"  VIOLATION step={v['step']} {v['what']}: {v['detail']}")
    return 1 if result["violations"] else 0


if __name__ == "__main__":
    sys.exit(main())
