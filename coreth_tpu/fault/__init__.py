"""Deterministic failpoints + the shared backoff helper.

Named fault-injection sites compiled into production code paths. The
contract mirrors metrics.spans: when nothing is armed the whole package
is a single module-bool check per site (`if not enabled: return`), so
hot paths pay one dict-free branch. Arming any failpoint (env, RPC, or
tests) flips the bool and routes the named site through its action.

Site names are registered at import time of the module that contains
them (`register("chain/tail/before_head")`); arming an unregistered
name raises, so a typo'd chaos script fails loudly instead of silently
never firing (enforced statically by lint rule SA006).

Action spec grammar (env `CORETH_TPU_FAILPOINTS="name=spec;name2=spec2"`
or `debug_setFailpoint`):

    spec   := verb [":" arg] ["%" prob] ["*" count]
    verb   := "raise" | "hang"
    arg    := message (raise) | milliseconds (hang)
    prob   := fire probability in (0, 1]   (default 1 = always)
    count  := max number of fires          (default unlimited)

`hang` with no argument parks the caller on an event that `clear()` /
`clear_all()` releases — kill-injection tests SIGKILL the process while
parked, in-process tests un-hang by disarming. Probabilistic fires draw
from a per-failpoint `random.Random` seeded from
`CORETH_TPU_FAILPOINT_SEED` xor a stable crc32 of the name, so chaos
runs replay exactly.

This module is also the one sanctioned home of `time.sleep` outside
tests (SA006): `Backoff` below is the capped-exponential-plus-jitter
helper every retry loop in the tree must go through.
"""

from __future__ import annotations

import os
import random
import threading
import time
import zlib
from typing import Dict, List, Optional

# Fast-path gate: True iff at least one failpoint is currently armed.
# Sites check this bare module bool before touching any dict or lock.
enabled = False

_lock = threading.Lock()
_registry: Dict[str, str] = {}  # name -> site description
_armed: Dict[str, "_Armed"] = {}
_unhang = threading.Event()  # released when the armed config changes


def _env_seed() -> int:
    try:
        return int(os.environ.get("CORETH_TPU_FAILPOINT_SEED", "") or "0")
    except ValueError:
        return 0


_seed = _env_seed()


class FailpointError(RuntimeError):
    """Raised by an armed `raise` failpoint at its site."""

    def __init__(self, name: str, message: str = ""):
        super().__init__(message or f"failpoint {name} fired")
        self.failpoint = name


class _Armed:
    """One armed failpoint: parsed spec + deterministic RNG + fire budget."""

    __slots__ = ("name", "spec", "verb", "arg", "prob", "remaining", "rng",
                 "fired")

    def __init__(self, name: str, spec: str):
        self.name = name
        self.spec = spec
        body = spec
        self.remaining: Optional[int] = None
        if "*" in body:
            body, _, count = body.rpartition("*")
            self.remaining = int(count)
            if self.remaining <= 0:
                raise ValueError(f"failpoint {name}: count must be > 0")
        self.prob = 1.0
        if "%" in body:
            body, _, prob = body.rpartition("%")
            self.prob = float(prob)
            if not 0.0 < self.prob <= 1.0:
                raise ValueError(f"failpoint {name}: prob must be in (0, 1]")
        verb, _, arg = body.partition(":")
        if verb not in ("raise", "hang"):
            raise ValueError(f"failpoint {name}: unknown verb {verb!r}")
        self.verb = verb
        self.arg = arg
        if verb == "hang" and arg:
            float(arg)  # validate at arm time, not fire time
        # Stable per-(seed, name) stream so probabilistic chaos replays.
        self.rng = random.Random(_seed ^ zlib.crc32(name.encode()))
        self.fired = 0

    def should_fire(self) -> bool:
        if self.remaining is not None and self.remaining <= 0:
            return False
        if self.prob < 1.0 and self.rng.random() >= self.prob:
            return False
        if self.remaining is not None:
            self.remaining -= 1
        self.fired += 1
        return True


def register(name: str, doc: str = "") -> str:
    """Declare a failpoint site at module import. Duplicate names raise:
    every site string must be unique so arming is unambiguous."""
    with _lock:
        if name in _registry:
            raise ValueError(f"failpoint {name!r} registered twice")
        _registry[name] = doc
    return name


def registered() -> Dict[str, str]:
    with _lock:
        return dict(_registry)


def list_armed() -> List[Dict[str, object]]:
    with _lock:
        return [
            {"name": a.name, "spec": a.spec, "fired": a.fired,
             "remaining": a.remaining}
            for a in _armed.values()
        ]


def set_failpoint(name: str, spec: Optional[str]) -> None:
    """Arm [name] with [spec], or disarm it when spec is None/''.
    Unknown names raise KeyError (see SA006)."""
    global enabled, _unhang
    with _lock:
        if name not in _registry:
            raise KeyError(f"unknown failpoint {name!r}; "
                           f"registered: {sorted(_registry)}")
        if spec:
            _armed[name] = _Armed(name, spec)
        else:
            _armed.pop(name, None)
        enabled = bool(_armed)
        # Wake anything parked on a `hang` under the previous config.
        _unhang.set()
        _unhang = threading.Event()


def clear_all() -> None:
    global enabled, _unhang
    with _lock:
        _armed.clear()
        enabled = False
        _unhang.set()
        _unhang = threading.Event()


def set_seed(seed: int) -> None:
    """Reseed the deterministic fire streams (tests); takes effect for
    failpoints armed after the call."""
    global _seed
    _seed = seed


def seed() -> int:
    """The active deterministic seed (env or set_seed). Sites that need
    their own seeded randomness (ethdb/corrupt_read's bit pick) derive
    from this so chaos runs replay bit-exactly."""
    return _seed


def is_armed(name: str) -> bool:
    """True iff [name] is currently armed. For sites whose *shape*
    changes when armed (FaultInjectingDB splits a batch in two only
    while ethdb/torn_batch is armed) — never needed on the fast path,
    which stays on the bare `enabled` bool."""
    if not enabled:
        return False
    with _lock:
        return name in _armed


def armed_spec(name: str) -> Optional[str]:
    """The spec string [name] is currently armed with, or None. Lets a
    site branch on the armed *verb* (exec_shards must not park its own
    dispatch thread on a `hang` meant for a forked child)."""
    if not enabled:
        return None
    with _lock:
        a = _armed.get(name)
        return a.spec if a is not None else None


def _fire_counter(name: str) -> None:
    # imported lazily: `fault` sits in the forked shard worker's import
    # closure, and a module-scope metrics import would copy the parent's
    # registry singleton into every child image (SA011). Only the parent
    # ever reaches this hook — child_after_fork() swaps in a no-op.
    from ..metrics import default_registry
    default_registry.counter(f"fault/fired/{name}").inc()


_fired_hook = _fire_counter


def child_after_fork() -> None:
    """Re-arm this module inside a forked shard worker (core/shard_worker):
    fresh lock/event objects — the parent's copies may have been held/set
    by a thread that does not exist after fork — and a no-op fired-counter
    sink, so an env-inherited failpoint firing in the child never touches
    the (invisible, copy-on-write) metrics registry. Env/fork-inherited
    arming itself is preserved: `_armed` carries over, which is what makes
    CORETH_TPU_FAILPOINTS drills replayable inside forked children."""
    global _lock, _unhang, _fired_hook
    _lock = threading.Lock()
    _unhang = threading.Event()
    _fired_hook = lambda name: None


def failpoint(name: str) -> None:
    """The injection site. A single module-bool check when nothing is
    armed; otherwise fires the configured action for [name]."""
    if not enabled:
        return
    with _lock:
        armed = _armed.get(name)
        if armed is None or not armed.should_fire():
            return
        verb, arg = armed.verb, armed.arg
        unhang = _unhang
    _fired_hook(name)
    if verb == "raise":
        raise FailpointError(name, arg)
    if arg:  # hang:<ms>
        time.sleep(float(arg) / 1000.0)
    else:  # hang until disarmed (or the process is killed)
        unhang.wait()


def _parse_env() -> None:
    spec = os.environ.get("CORETH_TPU_FAILPOINTS", "")
    if not spec:
        return
    global enabled
    for item in spec.split(";"):
        item = item.strip()
        if not item:
            continue
        name, _, action = item.partition("=")
        name, action = name.strip(), action.strip()
        if not name or not action:
            raise ValueError(f"CORETH_TPU_FAILPOINTS: bad entry {item!r}")
        with _lock:
            # Env arming happens before site modules import and register,
            # so env names bypass the registry check; a bad name simply
            # never fires and shows up un-registered in debug_listFailpoints.
            _armed[name] = _Armed(name, action)
            enabled = True


_parse_env()


class Backoff:
    """Capped exponential backoff with jitter — the one sanctioned
    retry-delay primitive (SA006 rejects naked time.sleep elsewhere).

    delay_n = min(cap, base * factor**n) * (1 + jitter * U[-1, 1))
    """

    def __init__(self, base: float = 0.05, factor: float = 2.0,
                 cap: float = 5.0, jitter: float = 0.25,
                 rng: Optional[random.Random] = None):
        self.base = base
        self.factor = factor
        self.cap = cap
        self.jitter = jitter
        self.attempt = 0
        self._rng = rng if rng is not None else random.Random(_seed or None)

    def reset(self) -> None:
        self.attempt = 0

    def next_delay(self) -> float:
        delay = min(self.cap, self.base * (self.factor ** self.attempt))
        self.attempt += 1
        if self.jitter:
            delay *= 1.0 + self.jitter * (self._rng.random() * 2.0 - 1.0)
        return max(0.0, delay)

    def sleep(self) -> float:
        delay = self.next_delay()
        if delay > 0:
            time.sleep(delay)
        return delay
