"""Stdlib-HTTP `/metrics` + `/healthz` endpoint (role of coreth's
Prometheus gatherer handler + the avalanchego health API, without any
third-party dependency).

Hardening rules: GET only (405 otherwise), exact-path routing (404
otherwise), Content-Length always set, handler exceptions become plain
500s (never a traceback on the wire), access logging suppressed, and the
server binds loopback by default — exposure beyond localhost is an
explicit config decision (`metrics-http-host`).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Optional

from . import Registry, default_registry

PROMETHEUS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class MetricsHTTPServer:
    """Owns a daemon-threaded ThreadingHTTPServer serving:

    GET /metrics  -> Prometheus text exposition of the registry
    GET /healthz  -> JSON health verdict, 200 healthy / 503 not

    `health_fn` returns a JSON-able dict with a boolean "healthy" key
    (vm.api.health_check has exactly that shape); omitted, the endpoint
    reports healthy as long as the process serves requests.
    """

    def __init__(self, registry: Optional[Registry] = None,
                 health_fn: Optional[Callable[[], dict]] = None):
        self.registry = registry or default_registry
        self.health_fn = health_fn
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # --- lifecycle --------------------------------------------------------

    def start(self, host: str = "127.0.0.1", port: int = 0) -> int:
        """Bind and serve in a daemon thread; returns the bound port
        (useful with port=0)."""
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # no access-log spam
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                try:
                    path = self.path.split("?", 1)[0]
                    if path == "/metrics":
                        body = server.registry.export_prometheus().encode()
                        self._send(200, body, PROMETHEUS_CONTENT_TYPE)
                    elif path == "/healthz":
                        verdict = (server.health_fn() if server.health_fn
                                   else {"healthy": True})
                        code = 200 if verdict.get("healthy") else 503
                        self._send(code, json.dumps(verdict).encode(),
                                   "application/json")
                    else:
                        self._send(404, b"not found\n", "text/plain")
                except BrokenPipeError:
                    pass  # client went away mid-response
                except Exception:
                    from . import count_drop

                    count_drop("metrics/http/handler_error")
                    try:
                        self._send(500, b"internal error\n", "text/plain")
                    except OSError:
                        pass  # socket already dead; the counter is enough

            def do_POST(self):
                self._send(405, b"method not allowed\n", "text/plain")

            do_PUT = do_DELETE = do_PATCH = do_POST

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="metrics-http", daemon=True)
        self._thread.start()
        return self._httpd.server_address[1]

    @property
    def port(self) -> Optional[int]:
        return self._httpd.server_address[1] if self._httpd else None

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
