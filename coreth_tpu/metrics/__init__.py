"""Metrics registry (role of /root/reference/metrics/ — the go-metrics
fork: counters, gauges, meters, histograms, timers, with the
EnabledExpensive gate and Prometheus-style export)."""

from __future__ import annotations

import re
import threading
import time
from typing import Dict, List, Optional, Tuple

enabled = True
enabled_expensive = False  # metrics.EnabledExpensive gate


class Counter:
    def __init__(self):
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    def dec(self, n: int = 1) -> None:
        with self._lock:
            self._v -= n

    def count(self) -> int:
        return self._v

    def clear(self) -> None:
        with self._lock:
            self._v = 0


class Gauge:
    def __init__(self):
        self._v = 0.0
        self._lock = threading.Lock()

    def update(self, v) -> None:
        with self._lock:
            self._v = v

    def value(self):
        with self._lock:
            return self._v


# fixed latency buckets for SLO histograms (seconds); chosen to straddle
# the cheap-lane (tens of ms) and expensive-lane (seconds) budgets
DEFAULT_SLO_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                       1.0, 2.5, 5.0, 10.0)


class Histogram:
    """Sampling histogram with percentile queries.  With `buckets` set it
    additionally keeps fixed-bucket counts plus one exemplar (trace id +
    observed value) per bucket, and exports as a real Prometheus
    histogram family instead of a summary."""

    def __init__(self, reservoir: int = 1028, buckets=None):
        self._samples: List[float] = []
        self._reservoir = reservoir
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()
        if buckets:
            self._buckets: Optional[Tuple[float, ...]] = tuple(
                sorted(float(b) for b in buckets))
            self._bucket_counts = [0] * len(self._buckets)
            # per finite bucket: latest (value, trace_id) landing in it
            self._exemplars: List[Optional[Tuple[float, str]]] = (
                [None] * len(self._buckets))
        else:
            self._buckets = None
            self._bucket_counts = []
            self._exemplars = []

    def update(self, v: float, exemplar: Optional[str] = None) -> None:
        with self._lock:
            self._count += 1
            self._sum += v
            if self._buckets is not None:
                import bisect

                i = bisect.bisect_left(self._buckets, v)
                if i < len(self._buckets):
                    self._bucket_counts[i] += 1
                    if exemplar:
                        self._exemplars[i] = (v, exemplar)
            if len(self._samples) < self._reservoir:
                self._samples.append(v)
            else:
                import random

                i = random.randrange(self._count)
                if i < self._reservoir:
                    self._samples[i] = v

    def bucket_bounds(self) -> Optional[Tuple[float, ...]]:
        return self._buckets

    def buckets(self) -> List[Tuple[float, int]]:
        """Cumulative (upper_bound, count<=bound) pairs; the implicit
        +Inf bucket is the total count (``count()``)."""
        if self._buckets is None:
            return []
        with self._lock:
            out: List[Tuple[float, int]] = []
            cum = 0
            for le, n in zip(self._buckets, self._bucket_counts):
                cum += n
                out.append((le, cum))
            return out

    def exemplars(self) -> Dict[str, Dict[str, object]]:
        """{le_label: {"trace_id": ..., "value": ...}} for buckets that
        have captured one."""
        if self._buckets is None:
            return {}
        with self._lock:
            out: Dict[str, Dict[str, object]] = {}
            for le, ex in zip(self._buckets, self._exemplars):
                if ex is not None:
                    out[_fmt_value(le)] = {"value": ex[0], "trace_id": ex[1]}
            return out

    def count(self) -> int:
        return self._count

    def sum(self) -> float:
        """Exact cumulative sum across every update (survives reservoir
        eviction, unlike mean()*count())."""
        with self._lock:
            return self._sum

    def mean(self) -> float:
        with self._lock:
            return sum(self._samples) / len(self._samples) if self._samples else 0.0

    def percentile(self, p: float) -> float:
        with self._lock:
            if not self._samples:
                return 0.0
            s = sorted(self._samples)
            return s[min(len(s) - 1, int(len(s) * p))]

    def percentiles(self, ps) -> List[float]:
        """Batch percentile query: one sort under one lock acquisition."""
        with self._lock:
            if not self._samples:
                return [0.0 for _ in ps]
            s = sorted(self._samples)
            return [s[min(len(s) - 1, int(len(s) * p))] for p in ps]


class Meter:
    """Rate meter (events/sec with total count)."""

    def __init__(self):
        self._count = 0
        self._start = time.monotonic()
        self._lock = threading.Lock()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            self._count += n

    def count(self) -> int:
        return self._count

    def rate_mean(self) -> float:
        elapsed = time.monotonic() - self._start
        return self._count / elapsed if elapsed > 0 else 0.0


class Timer:
    """Histogram of durations + a meter of calls."""

    def __init__(self):
        self.hist = Histogram()
        self.meter = Meter()
        self._total = 0.0
        self._lock = threading.Lock()

    def update(self, seconds: float) -> None:
        self.hist.update(seconds)
        self.meter.mark()
        with self._lock:
            self._total += seconds

    def time(self):
        timer = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.monotonic()
                return self

            def __exit__(self, *a):
                timer.update(time.monotonic() - self.t0)

        return _Ctx()

    def count(self) -> int:
        return self.meter.count()

    def mean(self) -> float:
        return self.hist.mean()

    def total(self) -> float:
        """Exact cumulative seconds across every update (unlike
        mean()*count(), which drifts once the reservoir saturates) —
        what the bench phase-attribution report divides."""
        with self._lock:
            return self._total


# --- Prometheus exposition helpers ------------------------------------------

# Exposition sample names may legally contain ':' ([a-zA-Z_:][a-zA-Z0-9_:]*)
# but Prometheus reserves colons for recording rules, and registry names
# DO contain colons (the module-lock canonical form `module:NAME` feeds
# the lock/<name>/... contention families) — so the sanitizer rewrites
# them to '_' like every other separator, keeping scraped families
# recording-rule-clean and label-legal.
_NAME_SANITIZE_RE = re.compile(r"[^a-zA-Z0-9_]")

# summary quantiles exported for every Timer/Histogram
_QUANTILES = (0.5, 0.9, 0.99)
_QUANTILE_LABELS = ("0.5", "0.9", "0.99")


def sanitize_metric_name(name: str) -> str:
    """Registry names use `/`, `.` and `:` separators (go-metrics style,
    plus the module-lock canonical form); the exposition gets
    `[a-zA-Z_][a-zA-Z0-9_]*`."""
    out = _NAME_SANITIZE_RE.sub("_", name)
    if not out or not (out[0].isalpha() or out[0] == "_"):
        out = "_" + out
    return out


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return str(int(v))
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if f != f:
        return "NaN"
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    return repr(f)


class Registry:
    """metrics.Registry: name → metric, lazily created."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get_or_register(self, name: str, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_register(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_register(name, Gauge)

    def histogram(self, name: str, buckets=None) -> Histogram:
        return self._get_or_register(
            name, lambda: Histogram(buckets=buckets))

    def meter(self, name: str) -> Meter:
        return self._get_or_register(name, Meter)

    def timer(self, name: str) -> Timer:
        return self._get_or_register(name, Timer)

    def each(self):
        with self._lock:
            return list(self._metrics.items())

    def export_prometheus(self) -> str:
        """Full text exposition (the avalanchego gatherer analog): every
        family gets `# HELP`/`# TYPE` lines, Timer/Histogram export as
        Prometheus summaries (p50/p90/p99 quantiles + exact `_sum` and
        `_count`), and names are sanitized to the legal charset. The
        output parses under any Prometheus scraper; `python -m
        coreth_tpu.metrics --check` validates it in CI."""
        lines: List[str] = []

        def family(fam: str, kind: str, help_text: str,
                   samples: List[Tuple[str, tuple, object]]) -> None:
            lines.append(f"# HELP {fam} {help_text}")
            lines.append(f"# TYPE {fam} {kind}")
            for sname, labels, value in samples:
                if labels:
                    lab = ",".join(f'{k}="{v}"' for k, v in labels)
                    lines.append(f"{sname}{{{lab}}} {_fmt_value(value)}")
                else:
                    lines.append(f"{sname} {_fmt_value(value)}")

        def summary(fam: str, help_text: str, quantiles: List[float],
                    total: float, count: int) -> None:
            samples: List[Tuple[str, tuple, object]] = [
                (fam, (("quantile", _QUANTILE_LABELS[i]),), q)
                for i, q in enumerate(quantiles)
            ]
            samples.append((fam + "_sum", (), total))
            samples.append((fam + "_count", (), count))
            family(fam, "summary", help_text, samples)

        for name, m in sorted(self.each()):
            fam = sanitize_metric_name(name)
            if isinstance(m, Counter):
                family(fam, "counter", f"coreth_tpu counter {name}",
                       [(fam, (), m.count())])
            elif isinstance(m, Gauge):
                family(fam, "gauge", f"coreth_tpu gauge {name}",
                       [(fam, (), m.value())])
            elif isinstance(m, Meter):
                family(fam + "_total", "counter",
                       f"coreth_tpu meter {name} (event count)",
                       [(fam + "_total", (), m.count())])
                family(fam + "_rate", "gauge",
                       f"coreth_tpu meter {name} (events/sec)",
                       [(fam + "_rate", (), m.rate_mean())])
            elif isinstance(m, Timer):
                summary(fam + "_seconds",
                        f"coreth_tpu timer {name} (seconds)",
                        m.hist.percentiles(_QUANTILES), m.total(), m.count())
            elif isinstance(m, Histogram):
                if m.bucket_bounds() is not None:
                    # real histogram family: cumulative le buckets, the
                    # +Inf bucket equal to _count, then _sum/_count.
                    # Exemplars ride as comment lines (text-format 0.0.4
                    # has no inline exemplar syntax; any scraper skips
                    # comments, and our --check validates them).
                    samples: List[Tuple[str, tuple, object]] = []
                    for le, cum in m.buckets():
                        samples.append((fam + "_bucket",
                                        (("le", _fmt_value(le)),), cum))
                    samples.append((fam + "_bucket", (("le", "+Inf"),),
                                    m.count()))
                    samples.append((fam + "_sum", (), m.sum()))
                    samples.append((fam + "_count", (), m.count()))
                    family(fam, "histogram",
                           f"coreth_tpu slo histogram {name}", samples)
                    for le_label, ex in sorted(m.exemplars().items()):
                        lines.append(
                            f'# EXEMPLAR {fam}_bucket{{le="{le_label}"}} '
                            f"trace_id={ex['trace_id']} "
                            f"value={_fmt_value(ex['value'])}")
                else:
                    summary(fam, f"coreth_tpu histogram {name}",
                            m.percentiles(_QUANTILES), m.sum(), m.count())
        return "\n".join(lines) + "\n"

    def marshal(self) -> Dict[str, dict]:
        """JSON-friendly dump of every metric — the `debug_metrics` RPC
        payload (go-ethereum's debug/metrics.go analog)."""
        out: Dict[str, dict] = {}
        for name, m in sorted(self.each()):
            if isinstance(m, Counter):
                out[name] = {"type": "counter", "count": m.count()}
            elif isinstance(m, Gauge):
                out[name] = {"type": "gauge", "value": m.value()}
            elif isinstance(m, Meter):
                out[name] = {"type": "meter", "count": m.count(),
                             "rate": m.rate_mean()}
            elif isinstance(m, Timer):
                p50, p90, p99 = m.hist.percentiles(_QUANTILES)
                out[name] = {"type": "timer", "count": m.count(),
                             "total_seconds": m.total(),
                             "mean_seconds": m.mean(),
                             "p50": p50, "p90": p90, "p99": p99}
            elif isinstance(m, Histogram):
                p50, p90, p99 = m.percentiles(_QUANTILES)
                out[name] = {"type": "histogram", "count": m.count(),
                             "sum": m.sum(), "mean": m.mean(),
                             "p50": p50, "p90": p90, "p99": p99}
                if m.bucket_bounds() is not None:
                    out[name]["buckets"] = {
                        _fmt_value(le): cum for le, cum in m.buckets()}
                    out[name]["exemplars"] = m.exemplars()
        return out


# default registry (metrics.DefaultRegistry)
default_registry = Registry()


def get_or_register_counter(name: str, registry: Optional[Registry] = None) -> Counter:
    return (registry or default_registry).counter(name)


def get_or_register_timer(name: str, registry: Optional[Registry] = None) -> Timer:
    return (registry or default_registry).timer(name)


def count_drop(name: str, registry: Optional[Registry] = None) -> None:
    """Increment a drop/swallowed-exception counter (coreth's gossip and
    handler stats pattern): the ONE helper every silenced except-path
    uses, so the drop namespace stays in one place."""
    (registry or default_registry).counter(name).inc(1)


def get_or_register_meter(name: str, registry: Optional[Registry] = None) -> Meter:
    return (registry or default_registry).meter(name)


def get_or_register_gauge(name: str, registry: Optional[Registry] = None) -> Gauge:
    return (registry or default_registry).gauge(name)


class _NullCtx:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NULL_CTX = _NullCtx()


def phase_timer(name: str, registry: Optional[Registry] = None):
    """Always-on phase-attribution timer for the commit pipeline
    (plan / export / scatter / patch / store decomposition). Unlike
    expensive_timer this is NOT gated: it fires a handful of times per
    block commit, and the regression it guards (the resident-path CPU
    overhead) must decompose mechanically in every bench run."""
    if not enabled:
        return _NULL_CTX
    return (registry or default_registry).timer(name).time()


def observe_slo(name: str, seconds: float, exemplar: Optional[str] = None,
                registry: Optional[Registry] = None) -> None:
    """Record one latency observation into a fixed-bucket SLO histogram
    (created on first use with DEFAULT_SLO_BUCKETS), optionally attaching
    a trace-id exemplar to the bucket the observation lands in."""
    if not enabled:
        return
    (registry or default_registry).histogram(
        name, buckets=DEFAULT_SLO_BUCKETS).update(seconds, exemplar=exemplar)


def expensive_timer(name: str, registry: Optional[Registry] = None):
    """Context-managed timer gated on EnabledExpensive (metrics.go gate):
    zero overhead beyond one flag check when the gate is off. Used for
    the per-phase statedb timers (statedb.go:1006-1119
    AccountHashes/AccountCommits/StorageCommits analogs)."""
    if not enabled_expensive:
        return _NULL_CTX
    return (registry or default_registry).timer(name).time()
