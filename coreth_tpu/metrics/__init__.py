"""Metrics registry (role of /root/reference/metrics/ — the go-metrics
fork: counters, gauges, meters, histograms, timers, with the
EnabledExpensive gate and Prometheus-style export)."""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional

enabled = True
enabled_expensive = False  # metrics.EnabledExpensive gate


class Counter:
    def __init__(self):
        self._v = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    def dec(self, n: int = 1) -> None:
        with self._lock:
            self._v -= n

    def count(self) -> int:
        return self._v

    def clear(self) -> None:
        with self._lock:
            self._v = 0


class Gauge:
    def __init__(self):
        self._v = 0.0

    def update(self, v) -> None:
        self._v = v

    def value(self):
        return self._v


class Histogram:
    """Sampling histogram with percentile queries."""

    def __init__(self, reservoir: int = 1028):
        self._samples: List[float] = []
        self._reservoir = reservoir
        self._count = 0
        self._lock = threading.Lock()

    def update(self, v: float) -> None:
        with self._lock:
            self._count += 1
            if len(self._samples) < self._reservoir:
                self._samples.append(v)
            else:
                import random

                i = random.randrange(self._count)
                if i < self._reservoir:
                    self._samples[i] = v

    def count(self) -> int:
        return self._count

    def mean(self) -> float:
        with self._lock:
            return sum(self._samples) / len(self._samples) if self._samples else 0.0

    def percentile(self, p: float) -> float:
        with self._lock:
            if not self._samples:
                return 0.0
            s = sorted(self._samples)
            return s[min(len(s) - 1, int(len(s) * p))]


class Meter:
    """Rate meter (events/sec with total count)."""

    def __init__(self):
        self._count = 0
        self._start = time.monotonic()
        self._lock = threading.Lock()

    def mark(self, n: int = 1) -> None:
        with self._lock:
            self._count += n

    def count(self) -> int:
        return self._count

    def rate_mean(self) -> float:
        elapsed = time.monotonic() - self._start
        return self._count / elapsed if elapsed > 0 else 0.0


class Timer:
    """Histogram of durations + a meter of calls."""

    def __init__(self):
        self.hist = Histogram()
        self.meter = Meter()
        self._total = 0.0

    def update(self, seconds: float) -> None:
        self.hist.update(seconds)
        self.meter.mark()
        self._total += seconds

    def time(self):
        timer = self

        class _Ctx:
            def __enter__(self):
                self.t0 = time.monotonic()
                return self

            def __exit__(self, *a):
                timer.update(time.monotonic() - self.t0)

        return _Ctx()

    def count(self) -> int:
        return self.meter.count()

    def mean(self) -> float:
        return self.hist.mean()

    def total(self) -> float:
        """Exact cumulative seconds across every update (unlike
        mean()*count(), which drifts once the reservoir saturates) —
        what the bench phase-attribution report divides."""
        return self._total


class Registry:
    """metrics.Registry: name → metric, lazily created."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get_or_register(self, name: str, factory):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = factory()
                self._metrics[name] = m
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_register(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get_or_register(name, Gauge)

    def histogram(self, name: str) -> Histogram:
        return self._get_or_register(name, Histogram)

    def meter(self, name: str) -> Meter:
        return self._get_or_register(name, Meter)

    def timer(self, name: str) -> Timer:
        return self._get_or_register(name, Timer)

    def each(self):
        with self._lock:
            return list(self._metrics.items())

    def export_prometheus(self) -> str:
        """Text exposition (the avalanchego gatherer analog)."""
        lines = []
        for name, m in self.each():
            metric_name = name.replace("/", "_").replace(".", "_")
            if isinstance(m, Counter):
                lines.append(f"{metric_name} {m.count()}")
            elif isinstance(m, Gauge):
                lines.append(f"{metric_name} {m.value()}")
            elif isinstance(m, Meter):
                lines.append(f"{metric_name}_total {m.count()}")
                lines.append(f"{metric_name}_rate {m.rate_mean():.6f}")
            elif isinstance(m, Histogram):
                lines.append(f"{metric_name}_count {m.count()}")
                lines.append(f"{metric_name}_mean {m.mean():.6f}")
            elif isinstance(m, Timer):
                lines.append(f"{metric_name}_count {m.count()}")
                lines.append(f"{metric_name}_mean_seconds {m.mean():.6f}")
        return "\n".join(lines) + "\n"


# default registry (metrics.DefaultRegistry)
default_registry = Registry()


def get_or_register_counter(name: str, registry: Optional[Registry] = None) -> Counter:
    return (registry or default_registry).counter(name)


def get_or_register_timer(name: str, registry: Optional[Registry] = None) -> Timer:
    return (registry or default_registry).timer(name)


def count_drop(name: str, registry: Optional[Registry] = None) -> None:
    """Increment a drop/swallowed-exception counter (coreth's gossip and
    handler stats pattern): the ONE helper every silenced except-path
    uses, so the drop namespace stays in one place."""
    (registry or default_registry).counter(name).inc(1)


def get_or_register_meter(name: str, registry: Optional[Registry] = None) -> Meter:
    return (registry or default_registry).meter(name)


def get_or_register_gauge(name: str, registry: Optional[Registry] = None) -> Gauge:
    return (registry or default_registry).gauge(name)


class _NullCtx:
    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


_NULL_CTX = _NullCtx()


def phase_timer(name: str, registry: Optional[Registry] = None):
    """Always-on phase-attribution timer for the commit pipeline
    (plan / export / scatter / patch / store decomposition). Unlike
    expensive_timer this is NOT gated: it fires a handful of times per
    block commit, and the regression it guards (the resident-path CPU
    overhead) must decompose mechanically in every bench run."""
    if not enabled:
        return _NULL_CTX
    return (registry or default_registry).timer(name).time()


def expensive_timer(name: str, registry: Optional[Registry] = None):
    """Context-managed timer gated on EnabledExpensive (metrics.go gate):
    zero overhead beyond one flag check when the gate is off. Used for
    the per-phase statedb timers (statedb.go:1006-1119
    AccountHashes/AccountCommits/StorageCommits analogs)."""
    if not enabled_expensive:
        return _NULL_CTX
    return (registry or default_registry).timer(name).time()
