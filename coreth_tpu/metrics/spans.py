"""Structured spans for the block pipeline (the tracing half of the
observability layer; OBSERVABILITY.md has the span taxonomy).

Design constraints, in order:

1. Near-zero cost when disabled. `span(...)` is a module function that
   checks ONE module-level bool and returns a shared null context
   manager — no allocation, no lock, no clock read. The `# hot-path`
   static-analysis rule (SA003) only admits this helper (plus the gated
   timer helpers) inside hot functions for exactly this reason.
2. Thread-safe with context propagation. Each thread carries its own
   stack of open spans (threading.local); entering a span parents it
   under the thread's current top. Finished spans land in one bounded
   ring shared across threads, guarded by a lock.
3. Exportable. `chrome_trace()` renders the ring as Chrome trace-event
   JSON ("X" complete events, microsecond ts/dur) — loadable directly
   in Perfetto / chrome://tracing.

Enable per-process via the `spans-enabled` VM config knob (vm/config),
the `debug_setSpans` RPC, or the CORETH_TPU_SPANS=1 env override.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from . import tracectx as _tracectx

# process-global fast gate: checked (unlocked) on every span() call.
# Torn reads are harmless — the worst case is one span recorded or
# skipped around the toggle instant.
enabled = os.environ.get("CORETH_TPU_SPANS", "").lower() in ("1", "true", "on")

DEFAULT_RING_SIZE = 4096


class Span:
    """One timed region. Context manager: enter starts the clock and
    pushes onto the owning thread's stack; exit pops, stamps `end`, and
    commits to the tracer ring. Only ever constructed when spans are
    enabled, so its cost is off the disabled path entirely."""

    __slots__ = ("name", "span_id", "parent_id", "start", "end",
                 "attrs", "tid", "_tracer")

    def __init__(self, tracer: "Tracer", name: str, attrs: Dict[str, object]):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self.span_id = next(tracer._ids)
        self.parent_id: Optional[int] = None
        self.start = 0.0
        self.end = 0.0
        self.tid = 0

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def __enter__(self) -> "Span":
        self.tid = threading.get_ident()
        stack = self._tracer._stack()
        if stack:
            self.parent_id = stack[-1].span_id
        else:
            # lane handoff: a root span on a worker thread inherits its
            # parent from the ambient trace context captured at admission,
            # so parenting survives the thread boundary
            ctx = _tracectx.current()
            if ctx is not None:
                self.parent_id = ctx.parent_span_id
                self.attrs.setdefault("trace_id", ctx.trace_id)
        stack.append(self)
        self.start = time.monotonic()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        self.end = time.monotonic()
        if exc_type is not None:
            self.attrs["error"] = exc_type.__name__
        stack = self._tracer._stack()
        # pop by identity: an unbalanced exit (generator abandoned
        # mid-span, etc.) must not corrupt siblings
        if stack and stack[-1] is self:
            stack.pop()
        elif self in stack:
            stack.remove(self)
        self._tracer._commit(self)
        ctx = _tracectx.current()
        if ctx is not None:
            ctx.add_span({
                "name": self.name,
                "span_id": self.span_id,
                "parent_id": self.parent_id,
                "start_s": self.start,
                "duration_s": self.duration(),
                "tid": self.tid,
                "attrs": {k: v for k, v in self.attrs.items()
                          if k != "trace_id"},
            })
        return False

    def duration(self) -> float:
        return max(0.0, self.end - self.start)


class Tracer:
    """Owns the finished-span ring and the per-thread open-span stacks."""

    def __init__(self, capacity: int = DEFAULT_RING_SIZE):
        self._ring: deque = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._tls = threading.local()
        self._ids = itertools.count(1)
        self._t0 = time.monotonic()  # export epoch

    def _stack(self) -> list:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def _commit(self, span: Span) -> None:
        with self._lock:
            self._ring.append(span)

    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs)

    def current(self) -> Optional[Span]:
        stack = self._stack()
        return stack[-1] if stack else None

    def set_capacity(self, capacity: int) -> None:
        with self._lock:
            self._ring = deque(self._ring, maxlen=max(1, int(capacity)))

    def capacity(self) -> int:
        with self._lock:
            return self._ring.maxlen or 0

    def snapshot(self, clear: bool = False) -> List[Span]:
        with self._lock:
            spans = list(self._ring)
            if clear:
                self._ring.clear()
        return spans

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()

    def chrome_trace(self, clear: bool = False) -> dict:
        """Chrome trace-event JSON: {"traceEvents": [...]} with "X"
        (complete) events, ts/dur in microseconds relative to tracer
        construction. Loadable in Perfetto / chrome://tracing."""
        events = []
        for s in self.snapshot(clear=clear):
            args = dict(s.attrs)
            args["span_id"] = s.span_id
            if s.parent_id is not None:
                args["parent_id"] = s.parent_id
            events.append({
                "name": s.name,
                "cat": s.name.split("/", 1)[0],
                "ph": "X",
                "ts": (s.start - self._t0) * 1e6,
                "dur": s.duration() * 1e6,
                "pid": 0,
                "tid": s.tid,
                "args": args,
            })
        return {"traceEvents": events, "displayTimeUnit": "ms"}


# default tracer (mirrors metrics.default_registry)
tracer = Tracer()


class _NullSpan:
    """Shared no-op context manager returned when spans are disabled."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False

    def set_attr(self, key: str, value) -> None:
        pass


_NULL_SPAN = _NullSpan()


def span(name: str, **attrs):
    """THE instrumentation entry point: `with span("chain/verify"): ...`.
    One bool check when disabled; a real parented Span when enabled."""
    if not enabled:
        return _NULL_SPAN
    return tracer.span(name, **attrs)


def set_enabled(flag: bool) -> None:
    global enabled
    enabled = bool(flag)
