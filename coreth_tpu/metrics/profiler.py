"""Continuous sampling profiler — stdlib-only wall-clock attribution
(ISSUE 20 tentpole, part 1).

A daemon thread walks `sys._current_frames()` at `profiler-hz` and folds
each thread's stack into a bounded collapsed-stack table keyed by
thread-ROLE (rpc lane, pipeline commit worker, insert tail, acceptor,
shard driver, ...).  Samples taken while the sampled thread holds a
canonical lock (per the PR-19 `LockOrderWitness` held-stack mirror) get
the lock appended as a synthetic leaf frame, so a flamegraph renders
"time under chainmu" as its own tower.  `debug_profileDump` serves the
table as flamegraph-ready collapsed text plus JSON; per-role sample
counts land on /metrics as the `profile/samples/<role>` family.

Design constraints, in order:

* The sampler must NEVER throw into the workload: every tick is fenced,
  failures count `profile/sampler_errors` and the loop keeps going
  (chaos invariant #7 asserts that counter stays zero over a 50-step
  conductor run with the sampler armed at 50 Hz).
* Overhead at 25 Hz must stay under 2% on the config-10 insert leg
  (bench_suite config-21 gates this): the per-tick work is one
  `sys._current_frames()` call, a dict mirror read, and string folds —
  no locks shared with the workload, no allocation on the workload side.
* Deterministic unit-testing: the frame walk, the thread-name map and
  the held-lock mirror are injectable (`frames_fn` / `threads_fn` /
  `locks_fn`), so tests drive `sample_once()` with synthetic frames and
  never depend on scheduler timing.
"""

from __future__ import annotations

import os.path
import sys
import threading
from typing import Callable, Dict, List, Optional, Tuple

from . import count_drop, default_registry

# thread-name prefix -> role; first match wins, order = specificity.
# These mirror the names the runtime actually assigns (rpc/admission.py
# lanes, core/insert_pipeline.py commit worker, core/blockchain.py tail
# worker + acceptor, core/exec_shards.py shard drivers, ...).
_ROLE_PREFIXES: Tuple[Tuple[str, str], ...] = (
    ("rpc-", "rpc"),
    ("insert-pipeline", "commit"),
    ("insert-tail", "tail"),
    ("acceptor", "acceptor"),
    ("shard-drive-", "shard"),
    ("parallel-exec-", "exec"),
    ("wd-", "watchdog"),
    ("MainThread", "main"),
)

SAMPLER_THREAD_NAME = "profile-sampler"


def role_for_thread_name(name: str) -> str:
    for prefix, role in _ROLE_PREFIXES:
        if name.startswith(prefix):
            return role
    return "other"


def _default_threads_fn() -> Dict[int, str]:
    return {t.ident: t.name for t in threading.enumerate()
            if t.ident is not None}


def _default_locks_fn() -> Dict[int, Tuple[str, ...]]:
    from ..utils.racecheck import held_locks_snapshot
    return held_locks_snapshot()


def fold_stack(frame, limit: int = 64) -> str:
    """Collapse a frame chain into `root;...;leaf` (flamegraph input
    grammar: semicolon-joined frames, spaces reserved for the count)."""
    parts: List[str] = []
    depth = 0
    while frame is not None and depth < limit:
        code = frame.f_code
        parts.append("%s:%s" % (
            os.path.basename(code.co_filename).replace(" ", "_"),
            code.co_name.replace(" ", "_")))
        frame = frame.f_back
        depth += 1
    parts.reverse()
    return ";".join(parts)


class Profiler:
    """Bounded collapsed-stack sampler; one instance per process."""

    def __init__(self, hz: float = 25.0, ring_size: int = 2048,
                 frames_fn: Optional[Callable[[], Dict]] = None,
                 threads_fn: Optional[Callable[[], Dict[int, str]]] = None,
                 locks_fn: Optional[
                     Callable[[], Dict[int, Tuple[str, ...]]]] = None):
        self.hz = float(hz)
        self.ring_size = int(ring_size)
        self._frames_fn = frames_fn or sys._current_frames
        self._threads_fn = threads_fn or _default_threads_fn
        self._locks_fn = locks_fn or _default_locks_fn
        # (role, collapsed-stack) -> sample count; bounded at ring_size
        # distinct keys, overflow folds into a per-role "(overflow)" row
        self._table: Dict[Tuple[str, str], int] = {}
        self._mu = threading.Lock()
        self._stop_evt = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.samples_total = 0
        self.overflowed = 0
        # pre-bound instruments (never constructed on the tick path)
        self._c_errors = default_registry.counter("profile/sampler_errors")
        self._c_roles: Dict[str, object] = {}

    # -- sampling --------------------------------------------------------

    def _role_counter(self, role: str):
        c = self._c_roles.get(role)
        if c is None:
            c = default_registry.counter("profile/samples/%s" % role)
            self._c_roles[role] = c
        return c

    def sample_once(self) -> int:
        """Take one sample of every thread except the sampler itself;
        returns the number of stacks folded.  Deterministic under
        injected frames_fn/threads_fn/locks_fn."""
        frames = self._frames_fn()
        names = self._threads_fn()
        held = self._locks_fn()
        me = threading.get_ident()
        folded = 0
        for ident, frame in frames.items():
            if ident == me:
                continue
            role = role_for_thread_name(names.get(ident, "?"))
            stack = fold_stack(frame)
            locks = held.get(ident)
            if locks:
                # synthetic leaf frame: time-under-lock becomes its own
                # flamegraph tower without a second table dimension
                stack = "%s;<lock:%s>" % (stack, ",".join(
                    dict.fromkeys(locks)))
            key = (role, stack)
            with self._mu:
                if key in self._table:
                    self._table[key] += 1
                elif len(self._table) < self.ring_size:
                    self._table[key] = 1
                else:
                    okey = (role, "(overflow)")
                    self._table[okey] = self._table.get(okey, 0) + 1
                    self.overflowed += 1
                    count_drop("drop/profile/table_overflow")
                self.samples_total += 1
            self._role_counter(role).inc()
            folded += 1
        return folded

    def _run(self) -> None:
        interval = 1.0 / self.hz if self.hz > 0 else 1.0
        while not self._stop_evt.wait(interval):
            try:
                self.sample_once()
            except Exception:  # noqa: BLE001 - sampler must never throw
                self._c_errors.inc()

    # -- lifecycle -------------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop_evt.clear()
        self._thread = threading.Thread(
            target=self._run, name=SAMPLER_THREAD_NAME, daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 2.0) -> None:
        self._stop_evt.set()
        t = self._thread
        if t is not None:
            t.join(timeout=timeout)
        self._thread = None

    def alive(self) -> bool:
        return self._thread is not None and self._thread.is_alive()

    # -- export ----------------------------------------------------------

    def collapsed(self) -> str:
        """Flamegraph-ready text: `role;frame;...;frame count` lines,
        heaviest first (stable tie-break on the key for determinism)."""
        with self._mu:
            items = sorted(self._table.items(),
                           key=lambda kv: (-kv[1], kv[0]))
        return "\n".join("%s;%s %d" % (role, stack, n)
                         for (role, stack), n in items)

    def dump(self) -> Dict[str, object]:
        with self._mu:
            items = sorted(self._table.items(),
                           key=lambda kv: (-kv[1], kv[0]))
            total = self.samples_total
            overflowed = self.overflowed
        roles: Dict[str, int] = {}
        for (role, _stack), n in items:
            roles[role] = roles.get(role, 0) + n
        return {
            "hz": self.hz,
            "ring_size": self.ring_size,
            "running": self.alive(),
            "samples_total": total,
            "distinct_stacks": len(items),
            "overflowed": overflowed,
            "roles": roles,
            "table": [
                {"role": role, "stack": stack, "count": n}
                for (role, stack), n in items
            ],
            "collapsed": self.collapsed(),
        }


# -- module singleton (vm.py wiring + debug_profileDump) -----------------
#
# The singleton is REFCOUNTED: every start_profiler() must be paired with
# one stop_profiler(), and the sampler only dies with the last holder.
# Without this, one VM's shutdown would silently kill sampling for every
# other user of the process profiler (a second VM, the chaos conductor,
# bench_suite's A/B leg).

_profiler: Optional[Profiler] = None
_singleton_mu = threading.Lock()
_refs = 0


def start_profiler(hz: float, ring_size: int = 2048) -> Optional[Profiler]:
    """Start (or take a reference on the already-running) process
    profiler; hz <= 0 is the documented off switch and returns None.
    A differing hz never restarts a live sampler — first starter wins
    and the mismatch is logged instead of silently ignored."""
    global _profiler, _refs
    if hz <= 0:
        return None
    with _singleton_mu:
        if _profiler is None or not _profiler.alive():
            _profiler = Profiler(hz=hz, ring_size=ring_size)
            _profiler.start()
            _refs = 1
        else:
            _refs += 1
            if float(hz) != _profiler.hz:
                from ..log import get_logger, warn
                warn(get_logger("metrics"),
                     "sampling profiler already running; keeping its rate",
                     running_hz=_profiler.hz, requested_hz=float(hz))
        return _profiler


def stop_profiler() -> None:
    """Drop one start_profiler() reference; the sampler stops only when
    the last holder lets go.  A stray stop with no profiler is a no-op."""
    global _profiler, _refs
    with _singleton_mu:
        if _profiler is None:
            return
        _refs -= 1
        if _refs <= 0:
            _profiler.stop()
            _profiler = None
            _refs = 0


def get_profiler() -> Optional[Profiler]:
    return _profiler


def profile_dump() -> Dict[str, object]:
    p = _profiler
    if p is None:
        return {"running": False, "samples_total": 0, "table": [],
                "collapsed": "", "roles": {}}
    return p.dump()
