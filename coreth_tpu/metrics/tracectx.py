"""Request-scoped trace context: cheap thread-local trace ids + a bounded
capture ring.

A trace id is minted at an admission point (RPC dispatch, block insert) and
travels with the request across thread boundaries: the admission lanes hand
the context to their worker threads, deadline expiries stamp it into the
raised error, spans inherit their parent across the handoff, and the flight
record carries it per block.  Interesting traces (sheds, deadline expiries,
abandoned requests, over-SLO completions) are captured into a process-global
bounded ring that ``debug_traceRequest`` serves from.

Everything here is gated on the module-level ``enabled`` flag — one bool
check per call site when tracing is off — and id formatting goes through the
single gated :func:`mint` helper so hot paths never build trace strings
inline (enforced by the SA003 lint).
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional

enabled = os.environ.get("CORETH_TPU_TRACING", "1").lower() not in (
    "0", "false", "off")

DEFAULT_RING_SIZE = 256

# spans appended per trace are bounded so a pathological handler cannot
# balloon a ring entry
MAX_SPANS_PER_TRACE = 128

_ids = itertools.count(1)
# short per-process prefix keeps ids from colliding across restarts in logs
_prefix = "%04x" % (os.getpid() & 0xFFFF)
_tls = threading.local()


def mint(kind: str) -> str:
    """Format a fresh trace id.  The one sanctioned trace-id formatting
    site — hot paths must call this instead of building f-strings."""
    return "%s-%s-%06x" % (kind, _prefix, next(_ids))


class TraceCtx:
    """Ambient per-request context.  Created once at admission and installed
    on every thread that works on the request via :class:`scope`."""

    __slots__ = ("trace_id", "kind", "t0", "parent_span_id", "meta", "spans")

    def __init__(self, trace_id: str, kind: str,
                 parent_span_id: Optional[int] = None):
        self.trace_id = trace_id
        self.kind = kind
        self.t0 = time.monotonic()
        self.parent_span_id = parent_span_id
        self.meta: Dict[str, Any] = {}
        self.spans: List[Dict[str, Any]] = []

    def add_span(self, rec: Dict[str, Any]) -> None:
        if len(self.spans) < MAX_SPANS_PER_TRACE:
            self.spans.append(rec)

    def elapsed(self) -> float:
        return time.monotonic() - self.t0


def begin(kind: str, parent_span_id: Optional[int] = None) -> Optional[TraceCtx]:
    """Mint a context for a new request, or None when tracing is off."""
    if not enabled:
        return None
    return TraceCtx(mint(kind), kind, parent_span_id)


def current() -> Optional[TraceCtx]:
    return getattr(_tls, "ctx", None)


def current_id() -> Optional[str]:
    ctx = getattr(_tls, "ctx", None)
    return ctx.trace_id if ctx is not None else None


class scope:
    """Install a TraceCtx on this thread for the duration of a block.
    ``scope(None)`` is a no-op so call sites need no branching."""

    __slots__ = ("ctx", "_prev")

    def __init__(self, ctx: Optional[TraceCtx]):
        self.ctx = ctx

    def __enter__(self) -> Optional[TraceCtx]:
        if self.ctx is not None:
            self._prev = getattr(_tls, "ctx", None)
            _tls.ctx = self.ctx
        return self.ctx

    def __exit__(self, exc_type, exc, tb) -> None:
        if self.ctx is not None:
            _tls.ctx = self._prev


class TraceRing:
    """Bounded, thread-safe ring of captured trace records keyed by id."""

    def __init__(self, capacity: int = DEFAULT_RING_SIZE):
        self._capacity = max(1, int(capacity))
        self._recs: "OrderedDict[str, Dict[str, Any]]" = OrderedDict()
        self._lock = threading.Lock()

    def set_capacity(self, capacity: int) -> None:
        with self._lock:
            self._capacity = max(1, int(capacity))
            while len(self._recs) > self._capacity:
                self._recs.popitem(last=False)

    def put(self, rec: Dict[str, Any]) -> None:
        tid = rec.get("trace_id")
        if not tid:
            return
        with self._lock:
            self._recs[tid] = rec
            self._recs.move_to_end(tid)
            while len(self._recs) > self._capacity:
                self._recs.popitem(last=False)

    def get(self, trace_id: str) -> Optional[Dict[str, Any]]:
        with self._lock:
            return self._recs.get(trace_id)

    def last(self, n: int = 16) -> List[Dict[str, Any]]:
        with self._lock:
            recs = list(self._recs.values())
        return recs[-max(0, int(n)):]

    def __len__(self) -> int:
        with self._lock:
            return len(self._recs)


ring = TraceRing()


def capture(ctx: Optional[TraceCtx], outcome: str, **fields: Any) -> None:
    """Snapshot a finished (or shed) request into the ring.  Cheap no-op
    when tracing is off or the request was admitted without a context."""
    if ctx is None:
        return
    rec: Dict[str, Any] = {
        "trace_id": ctx.trace_id,
        "kind": ctx.kind,
        "outcome": outcome,
        "elapsed_s": ctx.elapsed(),
        "meta": dict(ctx.meta),
        "spans": list(ctx.spans),
    }
    rec.update(fields)
    ring.put(rec)


def set_enabled(flag: bool) -> None:
    global enabled
    enabled = bool(flag)
