"""Fork-clean shard-worker telemetry accumulator (ISSUE 20 tentpole,
part 3).

Exec-shard workers (core/shard_worker.py) may not import the metrics
registry — SA011 bans it because the registry drags in locks, spans and
an export thread that must not exist in a forked child.  This module is
the sanctioned alternative: pure stdlib, no package-relative imports,
no module-level mutable state, no threads.  A worker builds ONE
`ShardStats` function-locally, accumulates counter/timer deltas while
executing, and ships `snapshot_and_reset()`'s compact dict piggybacked
on each write-set reply; the PARENT (core/exec_shards.py) merges those
deltas into the real registry under `exec/shard/worker/<i>/*` and stamps
per-shard execute time into the pipeline flight records.

The wire shape is two flat str->number dicts — picklable by the
multiprocessing Connection with no custom reduction:

    {"counts": {"txs": 17, "errors": 0},
     "seconds": {"execute": 0.0123}}

SA011 allowlists exactly this module for shard_worker imports and still
verifies at module scope that nothing here can re-introduce the banned
machinery (tests/test_static_analysis.py pins that).
"""

from __future__ import annotations

import time
from typing import Dict


class ShardStats:
    """Local counter/timer delta accumulator; one per worker loop."""

    __slots__ = ("counts", "seconds")

    def __init__(self) -> None:
        self.counts: Dict[str, int] = {}
        self.seconds: Dict[str, float] = {}

    def inc(self, key: str, n: int = 1) -> None:
        self.counts[key] = self.counts.get(key, 0) + n

    def add_seconds(self, key: str, s: float) -> None:
        self.seconds[key] = self.seconds.get(key, 0.0) + s

    def timed(self, key: str) -> "_Timed":
        return _Timed(self, key)

    def snapshot_and_reset(self) -> Dict[str, Dict[str, float]]:
        """The piggyback payload: current deltas, then zeroed — each
        dispatch reply carries only what THAT dispatch accumulated, so
        the parent-side merge is exactly-once by construction."""
        snap = {"counts": dict(self.counts), "seconds": dict(self.seconds)}
        self.counts.clear()
        self.seconds.clear()
        return snap


class _Timed:
    """`with stats.timed("execute"):` — monotonic span accumulator."""

    __slots__ = ("_stats", "_key", "_t0")

    def __init__(self, stats: ShardStats, key: str) -> None:
        self._stats = stats
        self._key = key
        self._t0 = 0.0

    def __enter__(self) -> "_Timed":
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> bool:
        self._stats.add_seconds(self._key, time.monotonic() - self._t0)
        return False
