"""Exposition self-check CLI.

    python -m coreth_tpu.metrics            # print the live exposition
    python -m coreth_tpu.metrics --json     # debug_metrics-shaped JSON
    python -m coreth_tpu.metrics --check    # validate and exit 0/1

`--check` runs in tools/lint.sh: it builds a synthetic registry that
exercises every metric type (plus hostile names) AND the process
default registry, then validates both expositions line-by-line —
malformed metric registrations fail CI instead of breaking the scraper.
"""

from __future__ import annotations

import argparse
import json
import math
import re
import sys
from typing import Dict, List, Optional, Tuple

from . import Registry, default_registry

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{([^}]*)\})?\s+(\S+)(?:\s+(-?\d+))?$")
_LABEL_RE = re.compile(r'^([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"$')
_HELP_RE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) (.*)$")
_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
                      r"(counter|gauge|summary|histogram|untyped)$")
_EXEMPLAR_RE = re.compile(
    r'^# EXEMPLAR ([a-zA-Z_:][a-zA-Z0-9_:]*)_bucket\{le="([^"]+)"\} '
    r"trace_id=(\S+) value=(\S+)$")

# suffixes a sample may add to its family name, by family type
_FAMILY_SUFFIXES = {
    "summary": ("", "_sum", "_count"),
    "histogram": ("", "_sum", "_count", "_bucket"),
    "counter": ("",),
    "gauge": ("",),
    "untyped": ("",),
}


def _parse_value(raw: str) -> Optional[float]:
    if raw in ("+Inf", "-Inf", "NaN", "Nan", "nan"):
        return {"+Inf": math.inf, "-Inf": -math.inf}.get(raw, math.nan)
    try:
        return float(raw)
    except ValueError:
        return None


def validate_exposition(text: str) -> List[str]:
    """Validate a Prometheus text exposition. Returns a list of error
    strings (empty = valid). Checks: every line parses, metric/label
    names are legal, HELP/TYPE declared once per family and before its
    samples, every sample belongs to a declared family, summary
    quantiles are float labels with monotone values, summaries carry
    _sum and _count, counters are finite and non-negative, histogram
    buckets carry a parseable `le` label with cumulative-monotone counts
    and a `+Inf` bucket equal to `_count`, and `# EXEMPLAR` comment lines
    reference a declared histogram bucket with a value inside it."""
    errors: List[str] = []
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    # family -> [(quantile, value)] for monotonicity; family -> suffixes seen
    quantiles: Dict[str, List[Tuple[float, float]]] = {}
    suffixes_seen: Dict[str, set] = {}
    # family -> {le_label: cumulative_count}; family -> {_sum/_count: value}
    buckets: Dict[str, Dict[str, float]] = {}
    hist_scalars: Dict[str, Dict[str, float]] = {}
    # (lineno, family, le_label, trace_id, raw_value) for post-pass checks
    exemplars: List[Tuple[int, str, str, str, str]] = []

    def owning_family(sample: str) -> Optional[Tuple[str, str]]:
        best = None
        for fam, kind in types.items():
            for sfx in _FAMILY_SUFFIXES[kind]:
                if sample == fam + sfx:
                    if best is None or len(fam) > len(best[0]):
                        best = (fam, sfx)
        return best

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            mh = _HELP_RE.match(line)
            mt = _TYPE_RE.match(line)
            if mh:
                fam = mh.group(1)
                if fam in helps:
                    errors.append(f"line {lineno}: duplicate HELP for {fam}")
                helps[fam] = mh.group(2)
            elif mt:
                fam = mt.group(1)
                if fam in types:
                    errors.append(f"line {lineno}: duplicate TYPE for {fam}")
                types[fam] = mt.group(2)
            elif line.startswith("# HELP") or line.startswith("# TYPE"):
                errors.append(f"line {lineno}: malformed HELP/TYPE: {line!r}")
            elif line.startswith("# EXEMPLAR"):
                me = _EXEMPLAR_RE.match(line)
                if me:
                    exemplars.append((lineno, me.group(1), me.group(2),
                                      me.group(3), me.group(4)))
                else:
                    errors.append(
                        f"line {lineno}: malformed EXEMPLAR: {line!r}")
            continue  # other comments are legal

        m = _SAMPLE_RE.match(line)
        if not m:
            errors.append(f"line {lineno}: unparseable sample: {line!r}")
            continue
        name, rawlabels, rawvalue = m.group(1), m.group(2), m.group(3)
        value = _parse_value(rawvalue)
        if value is None:
            errors.append(f"line {lineno}: bad value {rawvalue!r} for {name}")
            continue
        labels: Dict[str, str] = {}
        if rawlabels:
            for part in rawlabels.split(","):
                lm = _LABEL_RE.match(part.strip())
                if not lm:
                    errors.append(
                        f"line {lineno}: bad label {part!r} on {name}")
                    continue
                labels[lm.group(1)] = lm.group(2)

        owner = owning_family(name)
        if owner is None:
            errors.append(
                f"line {lineno}: sample {name} has no preceding # TYPE")
            continue
        fam, sfx = owner
        kind = types[fam]
        suffixes_seen.setdefault(fam, set()).add(sfx)
        if kind == "counter" and not (value >= 0 and math.isfinite(value)):
            errors.append(
                f"line {lineno}: counter {name} value {rawvalue} invalid")
        if kind == "histogram":
            if sfx == "_bucket":
                le = labels.get("le")
                if le is None:
                    errors.append(
                        f"line {lineno}: histogram bucket {name} missing le")
                elif _parse_value(le) is None:
                    errors.append(
                        f"line {lineno}: unparseable le {le!r} on {name}")
                elif le in buckets.setdefault(fam, {}):
                    errors.append(
                        f"line {lineno}: duplicate bucket le={le} on {name}")
                else:
                    buckets[fam][le] = value
            elif sfx in ("_sum", "_count"):
                hist_scalars.setdefault(fam, {})[sfx] = value
        if kind == "summary" and sfx == "":
            q = labels.get("quantile")
            if q is None:
                errors.append(
                    f"line {lineno}: summary sample {name} missing quantile")
            else:
                try:
                    quantiles.setdefault(fam, []).append((float(q), value))
                except ValueError:
                    errors.append(
                        f"line {lineno}: bad quantile {q!r} on {name}")

    for fam, kind in types.items():
        if fam not in helps:
            errors.append(f"family {fam}: TYPE without HELP")
        if kind == "summary" and fam in suffixes_seen:
            for want in ("_sum", "_count"):
                if want not in suffixes_seen[fam]:
                    errors.append(f"summary {fam}: missing {fam}{want}")
    for fam, qs in quantiles.items():
        ordered = sorted(qs)
        values = [v for _, v in ordered]
        if any(b < a for a, b in zip(values, values[1:])):
            errors.append(f"summary {fam}: quantile values not monotone: "
                          f"{ordered}")
    for fam, kind in types.items():
        if kind != "histogram" or fam not in suffixes_seen:
            continue
        for want in ("_sum", "_count"):
            if want not in suffixes_seen[fam]:
                errors.append(f"histogram {fam}: missing {fam}{want}")
        bks = buckets.get(fam, {})
        if not bks:
            errors.append(f"histogram {fam}: no buckets")
            continue
        if "+Inf" not in bks:
            errors.append(f"histogram {fam}: missing +Inf bucket")
        ordered_b = sorted(bks.items(), key=lambda kv: _parse_value(kv[0]))
        counts = [v for _, v in ordered_b]
        if any(b < a for a, b in zip(counts, counts[1:])):
            errors.append(f"histogram {fam}: bucket counts not "
                          f"cumulative-monotone: {ordered_b}")
        count = hist_scalars.get(fam, {}).get("_count")
        if count is not None and "+Inf" in bks and bks["+Inf"] != count:
            errors.append(f"histogram {fam}: +Inf bucket {bks['+Inf']} != "
                          f"_count {count}")
    for lineno, fam, le, trace_id, rawv in exemplars:
        if types.get(fam) != "histogram":
            errors.append(f"line {lineno}: EXEMPLAR for non-histogram {fam}")
            continue
        if le not in buckets.get(fam, {}):
            errors.append(
                f"line {lineno}: EXEMPLAR references unknown bucket "
                f"le={le} on {fam}")
        v = _parse_value(rawv)
        bound = _parse_value(le)
        if v is None or not math.isfinite(v):
            errors.append(f"line {lineno}: EXEMPLAR bad value {rawv!r}")
        elif bound is not None and v > bound:
            errors.append(
                f"line {lineno}: EXEMPLAR value {rawv} outside le={le}")
        if not trace_id:
            errors.append(f"line {lineno}: EXEMPLAR missing trace_id")
    return errors


def _synthetic_registry() -> Registry:
    """Exercise every metric type, including names the sanitizer must
    rewrite, so --check proves the whole exposition path."""
    r = Registry()
    r.counter("chain/blocks/inserted").inc(7)
    r.counter("9starts/with-digit").inc(1)
    r.gauge("chain/head.height").update(42)
    r.gauge("resident/fill+ratio").update(0.75)
    r.meter("chain/txs").mark(1000)
    h = r.histogram("trie/keccak/batch_msgs")
    for i in range(500):
        h.update(float(i))
    t = r.timer("chain/phase/verify")
    for i in range(200):
        t.update(0.001 * (i + 1))
    r.timer("chain/phase/empty")  # registered but never updated
    from . import DEFAULT_SLO_BUCKETS

    slo = r.histogram("slo/rpc/eth_call", buckets=DEFAULT_SLO_BUCKETS)
    for i in range(100):
        slo.update(0.004 * (i % 30), exemplar="rpc-test-%06x" % i)
    slo.update(99.0, exemplar="rpc-test-above-top-bucket")
    r.histogram("slo/chain/insert", buckets=DEFAULT_SLO_BUCKETS)  # empty

    # PR 20 families: lock-contention histograms (including the
    # module-lock canonical form `module:NAME`, whose ':' the sanitizer
    # must flatten to a legal exposition name) and profiler counters
    for lock in ("BlockChain.chainmu", "BlockChain._view_mu",
                 "blockchain:_ACCEPTOR_SIG"):
        for kind in ("wait", "hold"):
            lh = r.histogram(f"lock/{lock}/{kind}_seconds",
                             buckets=DEFAULT_SLO_BUCKETS)
            for i in range(50):
                lh.update(0.002 * (i % 20))
    r.counter("lock/slow_holds").inc(2)
    for role in ("rpc", "commit", "tail", "main"):
        r.counter(f"profile/samples/{role}").inc(100)
    r.counter("profile/sampler_errors")
    return r


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m coreth_tpu.metrics",
        description="Prometheus exposition printer / self-check")
    ap.add_argument("--check", action="store_true",
                    help="validate the exposition (synthetic + live "
                         "registry) and exit non-zero on any error")
    ap.add_argument("--json", action="store_true",
                    help="print the debug_metrics JSON marshal instead")
    args = ap.parse_args(argv)

    if args.check:
        failed = False
        for label, reg in (("synthetic", _synthetic_registry()),
                           ("default", default_registry)):
            errs = validate_exposition(reg.export_prometheus())
            if errs:
                failed = True
                print(f"[metrics --check] {label} registry: "
                      f"{len(errs)} error(s)")
                for e in errs:
                    print(f"  {e}")
            else:
                print(f"[metrics --check] {label} registry: OK")
        return 1 if failed else 0

    if args.json:
        print(json.dumps(default_registry.marshal(), indent=2, sort_keys=True))
        return 0

    sys.stdout.write(default_registry.export_prometheus())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
