"""Exposition self-check CLI.

    python -m coreth_tpu.metrics            # print the live exposition
    python -m coreth_tpu.metrics --json     # debug_metrics-shaped JSON
    python -m coreth_tpu.metrics --check    # validate and exit 0/1

`--check` runs in tools/lint.sh: it builds a synthetic registry that
exercises every metric type (plus hostile names) AND the process
default registry, then validates both expositions line-by-line —
malformed metric registrations fail CI instead of breaking the scraper.
"""

from __future__ import annotations

import argparse
import json
import math
import re
import sys
from typing import Dict, List, Optional, Tuple

from . import Registry, default_registry

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{([^}]*)\})?\s+(\S+)(?:\s+(-?\d+))?$")
_LABEL_RE = re.compile(r'^([a-zA-Z_][a-zA-Z0-9_]*)="([^"]*)"$')
_HELP_RE = re.compile(r"^# HELP ([a-zA-Z_:][a-zA-Z0-9_:]*) (.*)$")
_TYPE_RE = re.compile(r"^# TYPE ([a-zA-Z_:][a-zA-Z0-9_:]*) "
                      r"(counter|gauge|summary|histogram|untyped)$")

# suffixes a sample may add to its family name, by family type
_FAMILY_SUFFIXES = {
    "summary": ("", "_sum", "_count"),
    "histogram": ("", "_sum", "_count", "_bucket"),
    "counter": ("",),
    "gauge": ("",),
    "untyped": ("",),
}


def _parse_value(raw: str) -> Optional[float]:
    if raw in ("+Inf", "-Inf", "NaN", "Nan", "nan"):
        return {"+Inf": math.inf, "-Inf": -math.inf}.get(raw, math.nan)
    try:
        return float(raw)
    except ValueError:
        return None


def validate_exposition(text: str) -> List[str]:
    """Validate a Prometheus text exposition. Returns a list of error
    strings (empty = valid). Checks: every line parses, metric/label
    names are legal, HELP/TYPE declared once per family and before its
    samples, every sample belongs to a declared family, summary
    quantiles are float labels with monotone values, summaries carry
    _sum and _count, counters are finite and non-negative."""
    errors: List[str] = []
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    # family -> [(quantile, value)] for monotonicity; family -> suffixes seen
    quantiles: Dict[str, List[Tuple[float, float]]] = {}
    suffixes_seen: Dict[str, set] = {}

    def owning_family(sample: str) -> Optional[Tuple[str, str]]:
        best = None
        for fam, kind in types.items():
            for sfx in _FAMILY_SUFFIXES[kind]:
                if sample == fam + sfx:
                    if best is None or len(fam) > len(best[0]):
                        best = (fam, sfx)
        return best

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            mh = _HELP_RE.match(line)
            mt = _TYPE_RE.match(line)
            if mh:
                fam = mh.group(1)
                if fam in helps:
                    errors.append(f"line {lineno}: duplicate HELP for {fam}")
                helps[fam] = mh.group(2)
            elif mt:
                fam = mt.group(1)
                if fam in types:
                    errors.append(f"line {lineno}: duplicate TYPE for {fam}")
                types[fam] = mt.group(2)
            elif line.startswith("# HELP") or line.startswith("# TYPE"):
                errors.append(f"line {lineno}: malformed HELP/TYPE: {line!r}")
            continue  # other comments are legal

        m = _SAMPLE_RE.match(line)
        if not m:
            errors.append(f"line {lineno}: unparseable sample: {line!r}")
            continue
        name, rawlabels, rawvalue = m.group(1), m.group(2), m.group(3)
        value = _parse_value(rawvalue)
        if value is None:
            errors.append(f"line {lineno}: bad value {rawvalue!r} for {name}")
            continue
        labels: Dict[str, str] = {}
        if rawlabels:
            for part in rawlabels.split(","):
                lm = _LABEL_RE.match(part.strip())
                if not lm:
                    errors.append(
                        f"line {lineno}: bad label {part!r} on {name}")
                    continue
                labels[lm.group(1)] = lm.group(2)

        owner = owning_family(name)
        if owner is None:
            errors.append(
                f"line {lineno}: sample {name} has no preceding # TYPE")
            continue
        fam, sfx = owner
        kind = types[fam]
        suffixes_seen.setdefault(fam, set()).add(sfx)
        if kind == "counter" and not (value >= 0 and math.isfinite(value)):
            errors.append(
                f"line {lineno}: counter {name} value {rawvalue} invalid")
        if kind == "summary" and sfx == "":
            q = labels.get("quantile")
            if q is None:
                errors.append(
                    f"line {lineno}: summary sample {name} missing quantile")
            else:
                try:
                    quantiles.setdefault(fam, []).append((float(q), value))
                except ValueError:
                    errors.append(
                        f"line {lineno}: bad quantile {q!r} on {name}")

    for fam, kind in types.items():
        if fam not in helps:
            errors.append(f"family {fam}: TYPE without HELP")
        if kind == "summary" and fam in suffixes_seen:
            for want in ("_sum", "_count"):
                if want not in suffixes_seen[fam]:
                    errors.append(f"summary {fam}: missing {fam}{want}")
    for fam, qs in quantiles.items():
        ordered = sorted(qs)
        values = [v for _, v in ordered]
        if any(b < a for a, b in zip(values, values[1:])):
            errors.append(f"summary {fam}: quantile values not monotone: "
                          f"{ordered}")
    return errors


def _synthetic_registry() -> Registry:
    """Exercise every metric type, including names the sanitizer must
    rewrite, so --check proves the whole exposition path."""
    r = Registry()
    r.counter("chain/blocks/inserted").inc(7)
    r.counter("9starts/with-digit").inc(1)
    r.gauge("chain/head.height").update(42)
    r.gauge("resident/fill+ratio").update(0.75)
    r.meter("chain/txs").mark(1000)
    h = r.histogram("trie/keccak/batch_msgs")
    for i in range(500):
        h.update(float(i))
    t = r.timer("chain/phase/verify")
    for i in range(200):
        t.update(0.001 * (i + 1))
    r.timer("chain/phase/empty")  # registered but never updated
    return r


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m coreth_tpu.metrics",
        description="Prometheus exposition printer / self-check")
    ap.add_argument("--check", action="store_true",
                    help="validate the exposition (synthetic + live "
                         "registry) and exit non-zero on any error")
    ap.add_argument("--json", action="store_true",
                    help="print the debug_metrics JSON marshal instead")
    args = ap.parse_args(argv)

    if args.check:
        failed = False
        for label, reg in (("synthetic", _synthetic_registry()),
                           ("default", default_registry)):
            errs = validate_exposition(reg.export_prometheus())
            if errs:
                failed = True
                print(f"[metrics --check] {label} registry: "
                      f"{len(errs)} error(s)")
                for e in errs:
                    print(f"  {e}")
            else:
                print(f"[metrics --check] {label} registry: OK")
        return 1 if failed else 0

    if args.json:
        print(json.dumps(default_registry.marshal(), indent=2, sort_keys=True))
        return 0

    sys.stdout.write(default_registry.export_prometheus())
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
