"""Per-block flight recorder: a bounded ring of the last N block-insert
records, always on (the cost is a handful of clock reads and counter
snapshots per block — noise next to execution/commit).

Each record is a plain dict built by core/blockchain during insert:

    {"number": int, "hash": bytes, "txs": int, "gas_used": int,
     "phases": {"recover"|"verify"|"execute"|"validate"|"commit"|"write":
                seconds, ...},
     "resident": {phase: seconds, ...},      # resident/phase/* deltas
     "counters": {name: delta, ...},         # snap + plan-cache + keccak
     "parallel": {"mode": ..., ...},         # optimistic-executor verdict
     "host_mode": bool | None,               # device vs host hashing
     "trace_id": str | None,                 # insert-… id (tracectx)
     "accepted": bool, "seq": int}

`parallel` starts present-but-empty and `host_mode`/`counters` are
stamped in the insert's finally block, so host-fallback and
failed-before-execute records carry the same key set as the happy path
(`counters["resident/h2d_bytes"]` is an explicit 0 on host-mode
commits — bench attribution must never average over a ragged set).

The `write` phase is stamped asynchronously by the overlapped insert
tail; records are shared dicts, so readers see it once the tail worker
lands. On verify/execute failure the in-flight record is attached to the
chain's `bad_blocks` ring instead, and `debug_blockFlightRecord` serves
the accepted view over RPC.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

DEFAULT_CAPACITY = 64


class FlightRecorder:
    """Lock-guarded bounded ring of per-block records. One instance per
    BlockChain (NOT process-global) so tests and multi-VM processes
    don't bleed into each other."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self._events: deque = deque(maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        self._seq = 0

    def record(self, rec: Dict[str, object]) -> Dict[str, object]:
        """Append one block record (mutated in place later for the async
        `write` phase and the accept mark). Returns the same dict."""
        with self._lock:
            self._seq += 1
            rec["seq"] = self._seq
            rec.setdefault("accepted", False)
            self._ring.append(rec)
        return rec

    def mark_accepted(self, block_hash: bytes) -> None:
        """Flip `accepted` on the record for this hash (newest match)."""
        with self._lock:
            for rec in reversed(self._ring):
                if rec.get("hash") == block_hash:
                    rec["accepted"] = True
                    return

    def last(self, n: Optional[int] = None,
             accepted_only: bool = False) -> List[Dict[str, object]]:
        """Newest-last list of the most recent records. The dicts are the
        live ones (so late `write` stamps show up); callers that marshal
        should copy."""
        with self._lock:
            recs = list(self._ring)
        if accepted_only:
            recs = [r for r in recs if r.get("accepted")]
        if n is not None:
            recs = recs[-max(0, int(n)):]
        return recs

    def note_event(self, kind: str, **fields) -> Dict[str, object]:
        """Append one out-of-band lifecycle event (device demotion/
        re-promotion, mirror quarantine, torn-tail repair, ...) to a ring
        parallel to the block records, sharing the same seq counter so
        events interleave with blocks in wall order."""
        ev = {"event": kind, "ts": time.time()}
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._events.append(ev)
        return ev

    def events(self, n: Optional[int] = None,
               kind: Optional[str] = None) -> List[Dict[str, object]]:
        """Newest-last list of recent lifecycle events."""
        with self._lock:
            evs = list(self._events)
        if kind is not None:
            evs = [e for e in evs if e.get("event") == kind]
        if n is not None:
            evs = evs[-max(0, int(n)):]
        return evs

    def find(self, block_hash: bytes) -> Optional[Dict[str, object]]:
        with self._lock:
            for rec in reversed(self._ring):
                if rec.get("hash") == block_hash:
                    return rec
        return None

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def capacity(self) -> int:
        with self._lock:
            return self._ring.maxlen or 0

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()


def marshal_record(rec: Dict[str, object]) -> Dict[str, object]:
    """JSON-safe copy of one record (bytes hash → 0x-hex) — shared by
    debug_blockFlightRecord and debug_getBadBlocks."""
    out = dict(rec)
    h = out.get("hash")
    if isinstance(h, (bytes, bytearray)):
        out["hash"] = "0x" + bytes(h).hex()
    for k in ("phases", "counters", "resident", "parallel"):
        if isinstance(out.get(k), dict):
            out[k] = dict(out[k])
    return out
