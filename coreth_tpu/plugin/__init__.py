"""Out-of-process VM boundary (role of /root/reference/plugin/ — the
rpcchainvm plugin shape, main.go:33): serve a VM's snowman interface
over a unix socket so the consensus engine can live in another process.

    # VM process
    from coreth_tpu.plugin import serve
    serve(vm, "/tmp/coreth.sock")

    # engine process
    from coreth_tpu.plugin import RemoteVM
    remote = RemoteVM("/tmp/coreth.sock")
    blk = remote.build_block(); remote.block_verify(blk.id); ...
"""

from .client import RemoteBlock, RemoteVM, RemoteVMError
from .server import VMServer, serve

__all__ = ["RemoteBlock", "RemoteVM", "RemoteVMError", "VMServer", "serve"]
