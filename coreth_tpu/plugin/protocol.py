"""Wire protocol for the out-of-process VM boundary.

Role of the reference's rpcchainvm gRPC plugin transport
(/root/reference/plugin/main.go:33 rpcchainvm.Serve): the consensus
engine and the VM live in DIFFERENT PROCESSES and speak the snowman
interface over a unix socket. The framing is deliberately minimal —
length-prefixed JSON with binary fields hex-encoded — because the point
of the boundary is process isolation + interface serialization, not RPC
framework parity.

Frame:  u32 BE payload_len | payload (UTF-8 JSON object)
Request:  {"id": n, "method": str, "params": {...}}
Response: {"id": n, "result": {...}} | {"id": n, "error": str}
"""

from __future__ import annotations

import json
import struct
import socket
from typing import Optional

_MAX_FRAME = 64 * 1024 * 1024


class ProtocolError(Exception):
    pass


def b2h(b: Optional[bytes]) -> Optional[str]:
    return None if b is None else "0x" + b.hex()


def h2b(s: Optional[str]) -> Optional[bytes]:
    if s is None:
        return None
    return bytes.fromhex(s[2:] if s.startswith("0x") else s)


def send_msg(sock: socket.socket, obj: dict) -> None:
    data = json.dumps(obj).encode()
    if len(data) > _MAX_FRAME:
        raise ProtocolError(f"frame too large ({len(data)} bytes)")
    sock.sendall(struct.pack(">I", len(data)) + data)


def _read_exact(sock: socket.socket, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ProtocolError("connection closed")
        buf += chunk
    return buf


def recv_msg(sock: socket.socket) -> dict:
    (n,) = struct.unpack(">I", _read_exact(sock, 4))
    if n > _MAX_FRAME:
        raise ProtocolError(f"frame too large ({n} bytes)")
    return json.loads(_read_exact(sock, n).decode())
