"""VM-side of the process boundary: serve a VM's snowman interface over
a unix socket (role of /root/reference/plugin/main.go:33
`rpcchainvm.Serve(ctx, &evm.VM{IsPlugin: true})`).

The engine process drives the full ChainVM lifecycle — buildBlock,
parseBlock, Verify/Accept/Reject by block id, setPreference — plus the
state-sync server surface (appRequest forwards to sync/handlers.py, the
summaries come from vm/syncervm.py), all across serialized frames.
Every block crossing the boundary travels as its canonical RLP bytes,
so this doubles as a continuous test that the VM interface survives
serialization (VERDICT r4 missing-item #2).
"""

from __future__ import annotations

import os
import socket
import threading

from .protocol import ProtocolError, b2h, h2b, recv_msg, send_msg


class VMServer:
    """Serve [vm] on a unix socket until shutdown is requested."""

    def __init__(self, vm, sock_path: str):
        self.vm = vm
        self.sock_path = sock_path
        self._blocks: dict = {}  # id -> VMBlock (parsed/built, pre-decision)
        # RLock: lifecycle ops are engine-ordered, but parseBlock/getBlock
        # may arrive on other connections concurrently and _block_info
        # mutates _blocks (lifecycle paths re-enter it while holding)
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._sync_server = None
        self._listener = None

    # --- snowman surface --------------------------------------------------

    def _block_info(self, vmb) -> dict:
        bid = vmb.id()
        with self._lock:
            self._blocks.pop(bid, None)  # refresh insertion order
            self._blocks[bid] = vmb
            # bound retention: undecided blocks the engine abandoned must
            # not pin memory forever; decided/canonical blocks re-resolve
            # through vm.get_block, so eviction only drops in-flight
            # handles
            while len(self._blocks) > 512:
                self._blocks.pop(next(iter(self._blocks)))
        return {
            "id": b2h(vmb.id()),
            "parentID": b2h(vmb.parent_id()),
            "height": vmb.height(),
            "bytes": b2h(vmb.bytes()),
        }

    def _get(self, params) -> "object":
        bid = h2b(params["id"])
        vmb = self._blocks.get(bid)
        if vmb is None:
            vmb = self.vm.get_block(bid)
        if vmb is None:
            raise ProtocolError(f"unknown block {params['id']}")
        return vmb

    def _sync(self):
        if self._sync_server is None:
            from ..vm.syncervm import StateSyncServer

            # syncable heights must land on committed roots, so the
            # serving interval rides the chain's commit interval
            self._sync_server = StateSyncServer(
                self.vm.blockchain,
                syncable_interval=self.vm.config.commit_interval,
            )
        return self._sync_server

    def dispatch(self, method: str, params: dict) -> dict:
        vm = self.vm
        if method == "handshake":
            return {"ok": True,
                    "lastAcceptedID": b2h(vm.last_accepted().id())}
        if method == "buildBlock":
            with self._lock:
                return self._block_info(vm.build_block())
        if method == "parseBlock":
            return self._block_info(vm.parse_block(h2b(params["bytes"])))
        if method == "getBlock":
            return self._block_info(self._get(params))
        if method == "blockVerify":
            with self._lock:
                self._get(params).verify()
            return {}
        if method == "blockAccept":
            with self._lock:
                vmb = self._get(params)
                vmb.accept()
                vm.blockchain.drain_acceptor_queue()
                self._blocks.pop(vmb.id(), None)
            return {}
        if method == "blockReject":
            with self._lock:
                vmb = self._get(params)
                vmb.reject()
                self._blocks.pop(vmb.id(), None)
            return {}
        if method == "setPreference":
            with self._lock:
                vm.set_preference(h2b(params["id"]))
            return {}
        if method == "lastAccepted":
            return self._block_info(vm.last_accepted())
        if method == "issueTx":
            from ..core.types import Transaction

            vm.issue_tx(Transaction.decode(h2b(params["raw"])))
            return {}
        if method == "appRequest":
            # the sync-server path (leafs/blocks/code w/ range proofs)
            resp = vm.sync_handler.handle(b"engine", h2b(params["request"]))
            return {"response": b2h(resp)}
        if method == "getLastStateSummary":
            s = self._sync().get_last_state_summary()
            return {"summary": b2h(s.encode()) if s else None}
        if method == "getStateSummary":
            s = self._sync().get_state_summary(int(params["height"]))
            return {"summary": b2h(s.encode()) if s else None}
        if method == "health":
            return {"healthy": True}
        if method == "shutdown":
            self._stop.set()
            return {}
        raise ProtocolError(f"unknown method {method!r}")

    # --- socket plumbing --------------------------------------------------

    def _serve_conn(self, conn):
        try:
            while not self._stop.is_set():
                msg = recv_msg(conn)
                out = {"id": msg.get("id")}
                try:
                    out["result"] = self.dispatch(
                        msg.get("method", ""), msg.get("params") or {})
                except Exception as e:  # noqa: BLE001 — cross the boundary
                    out["error"] = f"{type(e).__name__}: {e}"
                send_msg(conn, out)
        except (ProtocolError, OSError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    def serve_forever(self) -> None:
        try:
            os.unlink(self.sock_path)
        except FileNotFoundError:
            pass
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self.sock_path)
        self._listener.listen(8)
        self._listener.settimeout(0.5)
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()
        try:
            self._listener.close()
        except OSError:
            pass
        try:
            os.unlink(self.sock_path)
        except FileNotFoundError:
            pass


def serve(vm, sock_path: str) -> None:
    """Block serving [vm] on [sock_path] until a shutdown request
    arrives, then shut the VM down (plugin/main.go's lifetime)."""
    srv = VMServer(vm, sock_path)
    try:
        srv.serve_forever()
    finally:
        vm.shutdown()
