"""Engine-side of the process boundary: a proxy that drives a VM served
by plugin/server.py over its unix socket.

Role of the engine half of the reference's rpcchainvm plugin transport
(avalanchego's vms/rpcchainvm client, reached from
/root/reference/plugin/main.go:33). `RemoteVM.app_request` matches the
peer.Network transport contract `(sender, request) -> response`, so a
local sync client can state-sync FROM the remote process exactly like
from an in-process peer — the cross-process variant of the two-VM
harness (syncervm_test.go:269 pattern).
"""

from __future__ import annotations

import socket
import threading
import time
from dataclasses import dataclass
from typing import Optional

from .protocol import b2h, h2b, recv_msg, send_msg


class RemoteVMError(Exception):
    pass


@dataclass
class RemoteBlock:
    """Serialized block handle (id + canonical RLP) from the remote VM."""

    id: bytes
    parent_id: bytes
    height: int
    bytes: bytes

    @classmethod
    def from_info(cls, info: dict) -> "RemoteBlock":
        return cls(id=h2b(info["id"]), parent_id=h2b(info["parentID"]),
                   height=int(info["height"]), bytes=h2b(info["bytes"]))


class RemoteVM:
    """Blocking JSON-frame client; one in-flight request at a time per
    connection (requests are serialized by a lock — the engine drives
    the lifecycle sequentially anyway, and sync requests are small)."""

    def __init__(self, sock_path: str, connect_timeout: float = 10.0):
        deadline = time.monotonic() + connect_timeout
        last_err: Optional[Exception] = None
        self._sock = None
        while time.monotonic() < deadline:
            try:
                s = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                s.connect(sock_path)
                self._sock = s
                break
            except OSError as e:  # server still booting
                last_err = e
                time.sleep(0.05)
        if self._sock is None:
            raise RemoteVMError(f"cannot connect to {sock_path}: {last_err}")
        self._lock = threading.Lock()
        self._next_id = 0

    def request(self, method: str, **params) -> dict:
        with self._lock:
            self._next_id += 1
            rid = self._next_id
            send_msg(self._sock, {"id": rid, "method": method,
                                  "params": params})
            resp = recv_msg(self._sock)
        if resp.get("id") != rid:
            raise RemoteVMError(f"response id mismatch: {resp}")
        if "error" in resp:
            raise RemoteVMError(resp["error"])
        return resp.get("result") or {}

    # --- snowman ChainVM --------------------------------------------------

    def handshake(self) -> bytes:
        return h2b(self.request("handshake")["lastAcceptedID"])

    def build_block(self) -> RemoteBlock:
        return RemoteBlock.from_info(self.request("buildBlock"))

    def parse_block(self, blob: bytes) -> RemoteBlock:
        return RemoteBlock.from_info(
            self.request("parseBlock", bytes=b2h(blob)))

    def get_block(self, block_id: bytes) -> RemoteBlock:
        return RemoteBlock.from_info(
            self.request("getBlock", id=b2h(block_id)))

    def block_verify(self, block_id: bytes) -> None:
        self.request("blockVerify", id=b2h(block_id))

    def block_accept(self, block_id: bytes) -> None:
        self.request("blockAccept", id=b2h(block_id))

    def block_reject(self, block_id: bytes) -> None:
        self.request("blockReject", id=b2h(block_id))

    def set_preference(self, block_id: bytes) -> None:
        self.request("setPreference", id=b2h(block_id))

    def last_accepted(self) -> RemoteBlock:
        return RemoteBlock.from_info(self.request("lastAccepted"))

    def issue_tx(self, raw: bytes) -> None:
        self.request("issueTx", raw=b2h(raw))

    # --- state sync -------------------------------------------------------

    def app_request(self, sender: bytes, request: bytes) -> bytes:
        """peer.Network transport contract: plug into Network.connect."""
        return h2b(self.request("appRequest",
                                request=b2h(request))["response"])

    def get_last_state_summary(self):
        from ..sync.messages import SyncSummary

        blob = self.request("getLastStateSummary").get("summary")
        return SyncSummary.decode(h2b(blob)) if blob else None

    def get_state_summary(self, height: int):
        from ..sync.messages import SyncSummary

        blob = self.request("getStateSummary", height=height).get("summary")
        return SyncSummary.decode(h2b(blob)) if blob else None

    def health(self) -> bool:
        return bool(self.request("health").get("healthy"))

    def shutdown(self) -> None:
        try:
            self.request("shutdown")
        except Exception:  # noqa: BLE001 — server may die before replying
            from ..metrics import count_drop

            count_drop("plugin/client/shutdown_rpc_error")
        self.close()

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass
