"""Chain configuration & fork schedule (role of /root/reference/params/).

ChainConfig carries Ethereum fork block numbers plus the Avalanche fork
timestamps (ApricotPhase1-6/Pre6/Post6, Banff, Cortina, DUpgrade —
params/config.go:514-535); Rules snapshots the active forks for one
(block number, timestamp). Protocol constants from avalanche_params.go
and protocol_params.go.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

# --- gas / protocol constants (protocol_params.go, avalanche_params.go) ----
GAS_LIMIT_BOUND_DIVISOR = 1024
MIN_GAS_LIMIT = 5000
MAX_GAS_LIMIT = 0x7FFFFFFFFFFFFFFF
GENESIS_GAS_LIMIT = 4_712_388

MAX_CODE_SIZE = 24576
MAX_INIT_CODE_SIZE = 2 * MAX_CODE_SIZE

TX_GAS = 21000
TX_GAS_CONTRACT_CREATION = 53000
TX_DATA_ZERO_GAS = 4
TX_DATA_NON_ZERO_GAS_FRONTIER = 68
TX_DATA_NON_ZERO_GAS_EIP2028 = 16
TX_ACCESS_LIST_ADDRESS_GAS = 2400
TX_ACCESS_LIST_STORAGE_KEY_GAS = 1900
INIT_CODE_WORD_GAS = 2

LAUNCH_MIN_GAS_PRICE = 470_000_000_000
APRICOT_PHASE1_MIN_GAS_PRICE = 225_000_000_000
APRICOT_PHASE1_GAS_LIMIT = 8_000_000
CORTINA_GAS_LIMIT = 15_000_000

APRICOT_PHASE3_EXTRA_DATA_SIZE = 80
APRICOT_PHASE3_MIN_BASE_FEE = 75_000_000_000
APRICOT_PHASE3_MAX_BASE_FEE = 225_000_000_000
APRICOT_PHASE3_INITIAL_BASE_FEE = 225_000_000_000
APRICOT_PHASE3_TARGET_GAS = 10_000_000
APRICOT_PHASE4_MIN_BASE_FEE = 25_000_000_000
APRICOT_PHASE4_MAX_BASE_FEE = 1_000_000_000_000
APRICOT_PHASE4_BASE_FEE_CHANGE_DENOMINATOR = 12
APRICOT_PHASE5_TARGET_GAS = 15_000_000
APRICOT_PHASE5_BASE_FEE_CHANGE_DENOMINATOR = 36

ATOMIC_TX_BASE_COST = 10_000
ATOMIC_GAS_LIMIT = 100_000

# rolling-window fee algo (consensus/dummy/dynamic_fees.go:33)
ROLLUP_WINDOW = 10

# AP4 block gas cost params (dynamic_fees.go)
AP4_MIN_BLOCK_GAS_COST = 0
AP4_MAX_BLOCK_GAS_COST = 1_000_000
AP4_BLOCK_GAS_COST_STEP = 50_000
AP4_TARGET_BLOCK_RATE = 2  # seconds
AP5_BLOCK_GAS_COST_STEP = 200_000


@dataclass
class ChainConfig:
    chain_id: int = 1

    # Ethereum forks (block numbers; None = never). The Avalanche configs
    # activate all of these at genesis (params/config.go:108-133).
    homestead_block: Optional[int] = 0
    eip150_block: Optional[int] = 0
    eip155_block: Optional[int] = 0
    eip158_block: Optional[int] = 0
    byzantium_block: Optional[int] = 0
    constantinople_block: Optional[int] = 0
    petersburg_block: Optional[int] = 0
    istanbul_block: Optional[int] = 0
    muir_glacier_block: Optional[int] = 0

    # Avalanche forks (timestamps; None = never)
    apricot_phase1_time: Optional[int] = None
    apricot_phase2_time: Optional[int] = None
    apricot_phase3_time: Optional[int] = None
    apricot_phase4_time: Optional[int] = None
    apricot_phase5_time: Optional[int] = None
    apricot_phase_pre6_time: Optional[int] = None
    apricot_phase6_time: Optional[int] = None
    apricot_phase_post6_time: Optional[int] = None
    banff_time: Optional[int] = None
    cortina_time: Optional[int] = None
    d_upgrade_time: Optional[int] = None

    # Stateful-precompile registrations (precompile/ framework): configs
    # with .address/.timestamp/.is_activated/.configure/.contract —
    # reference params/config.go:1027-1101
    precompile_upgrades: tuple = ()

    # ---- stateful precompiles -------------------------------------------

    def enabled_stateful_precompiles(self):
        """Configs in activation order (config.go:1082-1089)."""
        return sorted(
            (c for c in self.precompile_upgrades if c.timestamp is not None),
            key=lambda c: c.timestamp,
        )

    def check_configure_precompiles(self, parent_ts: Optional[int],
                                    block_header, statedb) -> None:
        """Activate any precompile whose timestamp falls in the
        parent->block transition (config.go:1092-1101); called from the
        processor, the miner, and genesis construction."""
        from ..precompile import check_configure

        for cfg in self.enabled_stateful_precompiles():
            check_configure(self, parent_ts, block_header, cfg, statedb)

    # ---- per-block fork checks ------------------------------------------

    def _is_block(self, fork: Optional[int], number: int) -> bool:
        return fork is not None and fork <= number

    def _is_time(self, fork: Optional[int], time: int) -> bool:
        return fork is not None and fork <= time

    def is_homestead(self, n): return self._is_block(self.homestead_block, n)
    def is_eip150(self, n): return self._is_block(self.eip150_block, n)
    def is_eip155(self, n): return self._is_block(self.eip155_block, n)
    def is_eip158(self, n): return self._is_block(self.eip158_block, n)
    def is_byzantium(self, n): return self._is_block(self.byzantium_block, n)
    def is_constantinople(self, n): return self._is_block(self.constantinople_block, n)
    def is_petersburg(self, n): return self._is_block(self.petersburg_block, n)
    def is_istanbul(self, n): return self._is_block(self.istanbul_block, n)

    def is_apricot_phase1(self, t): return self._is_time(self.apricot_phase1_time, t)
    def is_apricot_phase2(self, t): return self._is_time(self.apricot_phase2_time, t)
    def is_apricot_phase3(self, t): return self._is_time(self.apricot_phase3_time, t)
    def is_apricot_phase4(self, t): return self._is_time(self.apricot_phase4_time, t)
    def is_apricot_phase5(self, t): return self._is_time(self.apricot_phase5_time, t)
    def is_apricot_phase_pre6(self, t): return self._is_time(self.apricot_phase_pre6_time, t)
    def is_apricot_phase6(self, t): return self._is_time(self.apricot_phase6_time, t)
    def is_apricot_phase_post6(self, t): return self._is_time(self.apricot_phase_post6_time, t)
    def is_banff(self, t): return self._is_time(self.banff_time, t)
    def is_cortina(self, t): return self._is_time(self.cortina_time, t)
    def is_d_upgrade(self, t): return self._is_time(self.d_upgrade_time, t)

    def rules(self, number: int, timestamp: int) -> "Rules":
        return Rules(
            chain_id=self.chain_id,
            is_homestead=self.is_homestead(number),
            is_eip150=self.is_eip150(number),
            is_eip155=self.is_eip155(number),
            is_eip158=self.is_eip158(number),
            is_byzantium=self.is_byzantium(number),
            is_constantinople=self.is_constantinople(number),
            is_petersburg=self.is_petersburg(number),
            is_istanbul=self.is_istanbul(number),
            is_apricot_phase1=self.is_apricot_phase1(timestamp),
            is_apricot_phase2=self.is_apricot_phase2(timestamp),
            is_apricot_phase3=self.is_apricot_phase3(timestamp),
            is_apricot_phase4=self.is_apricot_phase4(timestamp),
            is_apricot_phase5=self.is_apricot_phase5(timestamp),
            is_apricot_phase_pre6=self.is_apricot_phase_pre6(timestamp),
            is_apricot_phase6=self.is_apricot_phase6(timestamp),
            is_apricot_phase_post6=self.is_apricot_phase_post6(timestamp),
            is_banff=self.is_banff(timestamp),
            is_cortina=self.is_cortina(timestamp),
            is_d_upgrade=self.is_d_upgrade(timestamp),
            active_precompiles={
                cfg.address: cfg.contract()
                for cfg in self.precompile_upgrades
                if cfg.is_activated(timestamp)
            },
        )


@dataclass
class Rules:
    """Fork-rule snapshot for one block (params/config.go Rules/AvalancheRules)."""

    chain_id: int = 1
    is_homestead: bool = True
    is_eip150: bool = True
    is_eip155: bool = True
    is_eip158: bool = True
    is_byzantium: bool = True
    is_constantinople: bool = True
    is_petersburg: bool = True
    is_istanbul: bool = True
    is_apricot_phase1: bool = False
    is_apricot_phase2: bool = False
    is_apricot_phase3: bool = False
    is_apricot_phase4: bool = False
    is_apricot_phase5: bool = False
    is_apricot_phase_pre6: bool = False
    is_apricot_phase6: bool = False
    is_apricot_phase_post6: bool = False
    is_banff: bool = False
    is_cortina: bool = False
    is_d_upgrade: bool = False

    # stateful-precompile activation registry hook (precompile/ framework)
    active_precompiles: dict = field(default_factory=dict)

    # EVM aliases: Avalanche phases imply the Ethereum mainnet forks coreth
    # maps them to (params/config.go AvalancheRules)
    @property
    def is_berlin(self) -> bool:
        return self.is_apricot_phase2

    @property
    def is_london(self) -> bool:
        return self.is_apricot_phase3

    @property
    def is_shanghai(self) -> bool:
        return self.is_d_upgrade


def avalanche_local_chain_config() -> ChainConfig:
    """All forks at genesis (params/config.go:107-132 local preset)."""
    return ChainConfig(
        chain_id=43112,
        apricot_phase1_time=0, apricot_phase2_time=0, apricot_phase3_time=0,
        apricot_phase4_time=0, apricot_phase5_time=0,
        apricot_phase_pre6_time=0, apricot_phase6_time=0,
        apricot_phase_post6_time=0, banff_time=0, cortina_time=0,
        d_upgrade_time=0,
    )


def avalanche_mainnet_chain_config() -> ChainConfig:
    """Mainnet C-Chain cadence (params/config.go:53-77 timestamps)."""
    return ChainConfig(
        chain_id=43114,
        apricot_phase1_time=1617199200,
        apricot_phase2_time=1620644400,
        apricot_phase3_time=1629813600,
        apricot_phase4_time=1632344400,
        apricot_phase5_time=1638468000,
        apricot_phase_pre6_time=1662341400,
        apricot_phase6_time=1662494400,
        apricot_phase_post6_time=1662519600,
        banff_time=1666108800,
        cortina_time=1682434800,
        d_upgrade_time=None,
    )


def avalanche_fuji_chain_config() -> ChainConfig:
    """Fuji testnet cadence (params/config.go:80-105 timestamps)."""
    return ChainConfig(
        chain_id=43113,
        apricot_phase1_time=1616767200,   # 2021-03-26T14:00Z
        apricot_phase2_time=1620223200,   # 2021-05-05T14:00Z
        apricot_phase3_time=1629140400,   # 2021-08-16T19:00Z
        apricot_phase4_time=1631826000,   # 2021-09-16T21:00Z
        apricot_phase5_time=1637766000,   # 2021-11-24T15:00Z
        apricot_phase_pre6_time=1662494400,   # 2022-09-06T20:00Z
        apricot_phase6_time=1662494400,       # 2022-09-06T20:00Z
        apricot_phase_post6_time=1662530400,  # 2022-09-07T06:00Z
        banff_time=1664805600,            # 2022-10-03T14:00Z
        cortina_time=1680793200,          # 2023-04-06T15:00Z
        d_upgrade_time=None,
    )


def chain_config_for_network(network_id: int) -> ChainConfig:
    """Genesis/network -> fork schedule selection (vm.go:383-403)."""
    if network_id == 1:       # avalanche mainnet network id
        return avalanche_mainnet_chain_config()
    if network_id == 5:       # fuji network id
        return avalanche_fuji_chain_config()
    return avalanche_local_chain_config()


TEST_CHAIN_CONFIG = avalanche_local_chain_config()
